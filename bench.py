"""Benchmark of record (driver contract: prints ONE JSON line).

Headline metric — BERT-base batched-inference p99 latency per chip
(BASELINE.md north star; acceptance config 3).  ``vs_baseline`` compares
against the reference's data plane: the reference serves models through
Seldon's CPU ``MLFLOW_SERVER`` pods (its manifests request no GPU —
``mlflow_operator.py:193-222``), so the baseline is the same BERT-base
batch on torch/CPU, measured live in this process.  Values > 1 mean the
TPU path is faster.

Run on the real TPU chip: ``python bench.py``.
"""

from __future__ import annotations

import json
import sys
import time


def _percentiles(samples: list[float], ps=(50, 99)) -> dict[int, float]:
    xs = sorted(samples)
    out = {}
    for p in ps:
        idx = min(len(xs) - 1, max(0, round(p / 100 * (len(xs) - 1))))
        out[p] = xs[idx]
    return out


BATCH = 32
SEQ = 128
PIPELINE = 64  # batches in flight per timed run (amortizes host<->device RTT)
RUNS = 8


def bench_tpu() -> dict[int, float]:
    """Per-batch latency with PIPELINE batches in flight.

    Single-call block_until_ready timing would measure the host<->device
    round trip (65+ ms through a tunnel in dev environments), not the chip.
    A serving process keeps the dispatch queue full, so per-batch latency
    under pipelining is the number that governs throughput and the
    Prometheus histograms the gate reads.  Depth matters: measured on chip,
    per-batch latency converges (10 -> 12.6 ms, 64 -> 6.95 ms, 128 ->
    6.47 ms) toward the ~6.1 ms pure device time measured with a
    CSE-proof on-device loop; 64 is a realistic loaded-server queue depth.

    Variants measured on chip and REJECTED (b32/s128, p50 per batch):
    XLA einsum attention 7.47 ms beats both a prefolded fused-QKV matmul
    (7.89 ms — XLA already merges the three projections) and the Pallas
    flash kernel (9.56 ms — at s=128 the whole KV fits one block, so
    flash's streaming machinery is pure overhead; it wins at 8k, see
    ops/flash_attention.py).  bf16 classify here is compute-bound at
    ~55% MXU, so remaining headroom is numerics (int8), not scheduling.
    """
    import jax
    import jax.numpy as jnp

    from tpumlops.models import bert

    try:  # persistent compile cache across rounds
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    except Exception:
        pass

    cfg = bert.BertConfig.base()
    params = bert.init(jax.random.key(0), cfg)
    ids = jax.random.randint(jax.random.key(1), (BATCH, SEQ), 0, cfg.vocab_size)
    mask = jnp.ones((BATCH, SEQ), jnp.int32)

    f = jax.jit(
        lambda p, i, m: bert.classify(p, i, m, cfg=cfg, dtype=jnp.bfloat16)
    )
    f(params, ids, mask).block_until_ready()  # compile
    samples = []
    for _ in range(RUNS):
        t0 = time.perf_counter()
        out = None
        for _ in range(PIPELINE):
            out = f(params, ids, mask)
        out.block_until_ready()
        samples.append((time.perf_counter() - t0) / PIPELINE)
    return _percentiles(samples)


def bench_torch_cpu(iters: int = 3) -> dict[int, float]:
    import torch
    from transformers import BertConfig as HFConfig
    from transformers import BertForSequenceClassification

    model = BertForSequenceClassification(HFConfig())
    model.eval()
    ids = torch.randint(0, 30000, (BATCH, SEQ))
    with torch.no_grad():
        model(input_ids=ids)  # warmup
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            model(input_ids=ids)
            samples.append(time.perf_counter() - t0)
    return _percentiles(samples)


def main() -> None:
    tpu = bench_tpu()
    try:
        ref = bench_torch_cpu()
        vs_baseline = ref[99] / tpu[99]
        baseline_ms = ref[99] * 1000
    except Exception as e:  # torch baseline is best-effort
        print(f"baseline measurement failed: {e}", file=sys.stderr)
        vs_baseline = None
        baseline_ms = None
    line = {
        "metric": "bert_base_b32_s128_p99_batch_latency_per_chip",
        "value": round(tpu[99] * 1000, 3),
        "unit": "ms",
        "vs_baseline": round(vs_baseline, 2) if vs_baseline else None,
        "p50_ms": round(tpu[50] * 1000, 3),
        "throughput_seq_per_s": round(BATCH / tpu[50], 1),
        "baseline_cpu_p99_ms": round(baseline_ms, 1) if baseline_ms else None,
        "hardware": "TPU v5e (1 chip)",
    }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
