"""Benchmark of record (driver contract: prints ONE JSON line).

Headline metric — BERT-base batched-inference p99 latency per chip
(BASELINE.md north star; acceptance config 3), served int8 on the MXU's
native s8 path (models/quantization.dense_q8; bf16 comparison included).
``vs_baseline`` compares against the reference's data plane: the reference
serves models through Seldon's CPU ``MLFLOW_SERVER`` pods (its manifests
request no GPU — ``mlflow_operator.py:193-222``), so the baseline is the
same BERT-base batch on torch/CPU, measured live in this process.  Values
> 1 mean the TPU path is faster.

``secondary`` covers the rest of BASELINE.json's configs and the second
north star:

- ``serve_path_http``  — p50/p99 per REQUEST through the real aiohttp
  server + dynamic batcher (and through the native router in front), not
  raw jit calls: the number the promotion gate actually judges.
- ``time_to_100pct_traffic`` — wall time for a full canary 10%→100% on
  the REAL local data plane (two live servers, C++ router split, gate fed
  by the router's actual histograms) at an accelerated step interval,
  with the policy-sleep floor separated out so the operator overhead is
  visible.  The reference's floor for its default policy is 480 s
  (``mlflow_operator.py:291-296``); ours is policy-bound the same way —
  the overhead line is what the rebuild adds on top (≈0 means parity).
- ``iris_sklearn_linear`` / ``xgboost_forest`` — µs-scale tabular configs.
- ``resnet50`` — batch ladder (b8 latency point through b128 throughput)
  with per-point MFU.
- ``prefix_cache_serving`` — shared-prefix workload through the real
  engine scheduler: TTFT cold vs warm and the prefill-chunk-call drop
  when the radix prefix KV cache reuses a cached prompt prefix
  (server/prefix_cache.py).
- ``speculative_serving`` — self-speculative n-gram decoding through
  the engine scheduler: tokens/s, acceptance rate, accepted-length
  distribution, and decode forwards per emitted token (< 1 = the HBM
  weight stream amortized) on a repetitive corpus and a random-token
  worst case (server/speculative.py).
- ``llama_1p35b_decode`` — decode slot ladder 8..64 (int8 weights + int8
  KV + windowed attention) with HBM bw_util and an int8kv logit-parity
  gate (models/llama.py, server/generation.py).
- ``llama_7b_decode`` — the same at real Llama-2-7B geometry from the
  13 GiB checkpoint (BASELINE config[4]).

Run on the real TPU chip: ``python bench.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _percentiles(samples: list[float], ps=(50, 99)) -> dict[int, float]:
    xs = sorted(samples)
    out = {}
    for p in ps:
        idx = min(len(xs) - 1, max(0, round(p / 100 * (len(xs) - 1))))
        out[p] = xs[idx]
    return out


BATCH = 32
SEQ = 128
# 40 sample pairs: the headline is a p99 and 8 samples made it float
# 25% run to run (VERDICT r3 weak #6); 24 still let one noisy run's
# trimmed tail land 15% off (r5: 3.567 vs 4.094 ms across two captured
# runs whose p50s agreed to 3.7%).  Run-to-run p99 stability comes from
# the 1.15x-of-median trim band in _trimmed_tail (the kept max IS the
# nearest-rank p99 at this n); more samples stabilize the p50 that
# anchors that band and populate the kept set densely enough near the
# cap that its max reproduces.  Costs ~80 s more wall per scan-delta.
RUNS = 40

# v5e single-chip peaks (public spec sheet): roofline denominators so every
# entry reports how much of the hardware it actually uses (VERDICT r2 #5).
V5E_BF16_TFLOPS = 197.0
V5E_INT8_TOPS = 394.0
V5E_HBM_GBPS = 819.0

# Published GPU anchors (BASELINE.md "GPU anchor points" — cited figures
# carried in at build time; no GPU or network exists here).  vs_gpu > 1
# means the v5e-1 path beats the anchor.
GPU_ANCHORS = {
    "bert_b32_s128_t4_int8_ms": 9.5,
    "bert_b32_s128_a100_ms": 2.0,
    "resnet50_t4_img_s": 5600.0,
    "resnet50_a100_img_s": 36000.0,
    "llama7b_a100_80g_tok_s": 1900.0,
}


def _scan_delta_timed(
    make_step, make_carry, runs: int = 6, n1: int = 8, n2: int = 40,
    params=None, donate_carry: bool = False,
) -> dict[int, float]:
    """p50/p99 seconds per model iteration from two-length on-device scans.

    THE timing methodology of record (round 3), built to survive this
    environment's device tunnel, which (a) overlaps/elides pipelined
    independent dispatches — ResNet-50 b8 "measured" 0.08 ms/fwd that
    way, an impossible 410 TFLOP/s — and (b) replays cached results for
    repeated calls with identical argument values (a 7B decode scan
    "ran" in 0.0 ms on its second call).  Countermeasures, in order:

    - the timed region is ONE dispatch whose iterations are chained by a
      data dependency XLA cannot fold: ``lax.scan`` with the carry gated
      on the model output (``make_step([params,] c) -> (c2, probe)``);
    - ``make_carry(i)`` must return a carry with DISTINCT VALUES per
      ``i`` so no replay cache across calls can hit;
    - big ``params`` ride as explicit jit arguments, never closure
      constants — closed-over weights are embedded in the serialized
      remote-compile payload, and a 1.35 GiB one wedges the tunnel
      (tcp_sendmsg on a full socket buffer);
    - timing two scan lengths and differencing cancels the constant
      dispatch + tunnel cost; noise enters at RTT-jitter/(n2-n1).

    Cross-checked against chained-dispatch and component-sum ablations
    (scripts/profile_bert_int8*.py): int8 BERT 4.71 ms scan-delta vs
    4.97 ms chained-dispatch (the 0.26 ms is per-dispatch overhead the
    scan correctly excludes)."""
    import jax

    def make(n):
        # donate_carry: the carry (e.g. a multi-GiB KV cache) aliases
        # into the loop instead of living twice (input + loop copy) —
        # what lets the 7B 32-slot point fit 16 GiB at all.  Callers
        # passing donate_carry MUST build a fresh carry per make_carry(i)
        # call: the donated buffer is consumed.
        #
        # The FINAL carry must be a jit OUTPUT: XLA expresses donation as
        # input->output buffer aliasing, so a function returning only the
        # probe ys gives the donated cache nothing to alias into ("Some
        # donated buffers were not usable") and the loop state is a second
        # allocation anyway.  Returning (final_carry, ys) forms the alias
        # pair; call() materializes only the probes, the carry output is
        # dropped on device.
        if params is None:

            def f(carry):
                return jax.lax.scan(
                    lambda c, _: make_step(c), carry, None, length=n
                )

            return jax.jit(f, donate_argnums=(0,) if donate_carry else ())

        def f(params, carry):
            return jax.lax.scan(
                lambda c, _: make_step(params, c), carry, None, length=n
            )

        return jax.jit(f, donate_argnums=(1,) if donate_carry else ())

    import numpy as np

    def call(f, i):
        # np.asarray, not block_until_ready: synchronize through the DATA
        # path.  The tunnel has been observed acking block_until_ready
        # early; pulling the probe values (a few floats) to host cannot
        # complete before the computation actually ran.
        carry = make_carry(i)
        args = (carry,) if params is None else (params, carry)
        final_carry, probes = f(*args)
        del final_carry  # aliases the donated input; only probes come home
        return np.asarray(probes)

    f1, f2 = make(n1), make(n2)
    call(f1, -1)
    call(f2, -2)

    probes: list = [None, None]  # last probe values per scan length

    def wall(f, i, slot):
        t0 = time.perf_counter()
        out = call(f, i)
        dt = time.perf_counter() - t0
        # Replay detector: distinct carry VALUES should yield distinct
        # probe values; bit-identical probes mean a cached result was
        # probably served and this wall is not a measurement.  (Integer
        # argmax probes CAN legitimately collide, so a tainted pair is
        # discarded, not fatal — only an all-tainted run raises.)
        replayed = probes[slot] is not None and np.array_equal(probes[slot], out)
        probes[slot] = out
        return dt, replayed

    def chained_wall(f, i, m):
        """Wall seconds for ``m`` DATA-CHAINED dispatches of ``f``: each
        call's carry is the previous call's final carry, and the probe of
        the last call is pulled through the data path — the whole chain
        (m x scan-length iterations) is serially dependent, so neither
        pipelining, early acks, nor replay caches can shorten it.  The
        fallback methodology when the scan-delta's elision guards fire
        (VERDICT r4 #4): per-dispatch overhead still cancels in the
        two-length difference because both lengths pay m dispatches."""
        carry = make_carry(i)
        t0 = time.perf_counter()
        probes = None
        for _ in range(m):
            args = (carry,) if params is None else (params, carry)
            carry, probes = f(*args)
        np.asarray(probes)
        return time.perf_counter() - t0

    def chained_fallback(reason: str):
        # Carry indices continue PAST the main loop's range (2*runs) so
        # no make_carry(i) value repeats — a colliding index would
        # recreate the bit-identical arguments whose replay this
        # fallback exists to defeat.
        base = 2 * runs
        m, runs_c = 3, 5
        samples_c = []
        for r in range(runs_c):
            w1 = chained_wall(f1, base + 2 * r, m)
            w2 = chained_wall(f2, base + 2 * r + 1, m)
            samples_c.append(max(0.0, (w2 - w1) / (m * (n2 - n1))))
        pc = _percentiles(samples_c)
        if pc[50] <= 0.0:
            raise RuntimeError(
                f"{reason}; chained-dispatch fallback also collapsed "
                "to zero — device path unusable"
            )
        pc["raw99"] = pc[99]
        pc[99] = _trimmed_tail(samples_c, pc[50])
        pc["method"] = "chained"
        return pc

    samples = []
    tainted = 0
    for r in range(runs):
        w1, r1 = wall(f1, 2 * r, 0)
        w2, r2 = wall(f2, 2 * r + 1, 1)
        if r1 or r2:
            tainted += 1
            continue
        samples.append(max(0.0, (w2 - w1) / (n2 - n1)))
    if not samples:
        return chained_fallback(
            f"all {tainted} scan-delta sample pairs were replayed cached "
            "results"
        )
    p = _percentiles(samples)
    if p[50] <= 0.0:
        return chained_fallback("scan-delta collapsed to zero")
    p["method"] = "scan_delta"
    p["raw99"] = p[99]  # untrimmed: keeps masked-regression risk visible
    p[99] = _trimmed_tail(samples, p[50])
    return p


def _trimmed_tail(samples: list[float], med: float) -> float:
    """p99 over samples within a fixed 1.15x-of-median band.

    Each sample is a MEAN over (n2 - n1) = ~16 chained on-device
    iterations, so the per-batch p99 is not directly observable here —
    the headline tail is "p99 of 16-batch windows".  Sustained
    slowdowns of UP TO 15% over 16 consecutive batches (realistic
    throttling) are admitted by the band; windows beyond it are
    classified as host/tunnel stall mass and trimmed (captured
    distribution: a 3.3-3.5 ms core with stall clusters at 2.4 and
    4.5-4.7 ms, BENCH_STABILITY_RUN*.json).

    A fixed band because adaptive scales proved unstable against this
    environment's bursty contamination: the full-sample MAD let a
    run's stall mass widen its own cut (r5 runs measured trimmed p99s
    15% apart while p50s agreed to 2-4%), and a lower-half-only scale
    has a knife-edge flip once short-scan stalls reach a quarter of
    the samples.  The deterministic band's residual risk — masking a
    genuine sustained slowdown > 15% — is covered by recording the
    UNTRIMMED p99 alongside (``raw99`` / ``p99_raw_ms``): a masked
    regression stays visible in the record."""
    return _percentiles([s for s in samples if s <= 1.15 * med])[99]


def _gate(c, logits):
    """Multiply the carry by a runtime-dependent 1 so scan iterations form
    a true data chain (XLA cannot hoist or elide the body).  The -1e30
    threshold (not -inf) keeps the compare un-foldable."""
    return c * (logits.sum() > -1e30).astype(c.dtype)


def _setup_jax():
    import jax

    try:  # persistent compile cache across rounds
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    except Exception:
        pass
    return jax


def bench_bert() -> dict:
    """Per-batch latency via the scan-delta methodology, int8 and bf16.

    Single-call block_until_ready timing would measure the host<->device
    round trip (65+ ms through a tunnel in dev environments), not the
    chip; pipelined independent dispatches get overlapped/elided by the
    round-3 tunnel.  The on-device scan chain is what a saturated serving
    process achieves, and its per-batch latency governs throughput and
    the Prometheus histograms the gate reads.

    Numerics: int8 + tanh-GELU is the headline — what the int8 serving
    path runs (loader._finish_native).  The round-3 ablation
    (scripts/profile_bert_int8*.py) priced the int8 batch: 72 GEMMs with
    dynamic act-quant 3.7 ms (188 TFLOP/s — act quant is FREE, fused
    into the s8 matmuls), exact-erf GELU ~1.8 ms of UNFUSED VPU work,
    attention core ~0.9 ms, LayerNorm ~0.24 ms, softmax ~0.11 ms.
    Swapping erf for the tanh approximation (error ~1e-3, under int8
    quant noise; argmax parity asserted below) fuses the activation into
    the matmul epilogue: 6.8 -> ~5.0 ms p50, ~1.4x over bf16-erf.
    Variants measured on chip and REJECTED: prefolded fused-QKV matmul
    (XLA already merges the projections), Pallas flash at s=128 (whole
    KV fits one block; flash wins at 8k, see ops/flash_attention.py),
    merged-(b,n) attention batched GEMMs (7.25 ms — worse than XLA's
    own einsum lowering), bf16 softmax (no change — already fused).
    """
    jax = _setup_jax()
    import numpy as np
    import jax.numpy as jnp

    from tpumlops.models import bert
    from tpumlops.models.quantization import quantize_bert

    cfg = bert.BertConfig.base()  # exact erf GELU: HF reference numerics
    # What the int8 serving path actually runs (loader._finish_native):
    # tanh-GELU — erf is ~1.8 ms of unfused VPU work per batch on v5e.
    cfg_srv = bert.BertConfig.base(hidden_act="gelu_tanh")
    params = bert.init(jax.random.key(0), cfg)
    qparams = quantize_bert(params)
    ids = jax.random.randint(jax.random.key(1), (BATCH, SEQ), 0, cfg.vocab_size)
    mask = jnp.ones((BATCH, SEQ), jnp.int32)

    f = jax.jit(
        lambda p, i, m: bert.classify(p, i, m, cfg=cfg, dtype=jnp.bfloat16)
    )
    f_srv = jax.jit(
        lambda p, i, m: bert.classify(p, i, m, cfg=cfg_srv, dtype=jnp.bfloat16)
    )

    def step_srv(p, c):
        logits = bert.classify(p, c, mask, cfg=cfg_srv, dtype=jnp.bfloat16)
        return _gate(c, logits), logits[0, 0]

    def step_ref(p, c):
        logits = bert.classify(p, c, mask, cfg=cfg, dtype=jnp.bfloat16)
        return _gate(c, logits), logits[0, 0]

    def carry_at(i):
        return (ids + jnp.int32(i)) % cfg.vocab_size

    q8 = _scan_delta_timed(step_srv, carry_at, runs=RUNS, params=qparams)
    bf16 = _scan_delta_timed(step_ref, carry_at, runs=RUNS, params=params)

    # Parity of the served numerics (int8 weights+acts, tanh GELU) against
    # the bf16 erf reference on the bench batch: the approximation must
    # not flip classifications.  HARD assertion — a numerics regression
    # must fail the bench, not quietly ship a lower agreement number.
    ref = np.asarray(f(params, ids, mask))
    srv = np.asarray(f_srv(qparams, ids, mask))
    agree = float(np.mean(ref.argmax(-1) == srv.argmax(-1)))
    max_delta = float(np.max(np.abs(ref - srv)))
    assert agree >= 0.97, (
        f"int8+tanh flipped {100 * (1 - agree):.1f}% of argmaxes vs bf16-erf"
    )

    # Roofline: encoder GEMMs + attention einsum FLOPs per batch.
    T, H, I = BATCH * SEQ, cfg.hidden_size, cfg.intermediate_size
    flops = cfg.num_layers * (
        2 * T * (4 * H * H + 2 * H * I)
        + 2 * 2 * BATCH * cfg.num_heads * SEQ * SEQ * cfg.head_dim
    )
    return {
        "int8": q8,
        "bf16": bf16,
        "parity": {"argmax_agreement": agree, "max_logit_delta": round(max_delta, 4)},
        "tflops_int8": flops / q8[50] / 1e12,
        "tflops_bf16": flops / bf16[50] / 1e12,
        "mfu_int8": flops / q8[50] / 1e12 / V5E_INT8_TOPS,
        "mfu_bf16": flops / bf16[50] / 1e12 / V5E_BF16_TFLOPS,
    }


def bench_torch_cpu(iters: int = 3) -> dict[int, float]:
    import torch
    from transformers import BertConfig as HFConfig
    from transformers import BertForSequenceClassification

    model = BertForSequenceClassification(HFConfig())
    model.eval()
    ids = torch.randint(0, 30000, (BATCH, SEQ))
    with torch.no_grad():
        model(input_ids=ids)  # warmup
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            model(input_ids=ids)
            samples.append(time.perf_counter() - t0)
    return _percentiles(samples)


# ---------------------------------------------------------------------------
# Serve path: HTTP through the real server (+ router), per-request latency
# ---------------------------------------------------------------------------


def bench_serve_path() -> dict:
    """p50/p99 per single-sequence REQUEST through aiohttp + the dynamic
    batcher (BERT-base int8), then the same through the native router —
    the full Seldon-executor-analogue path the gate's PromQL measures."""
    import concurrent.futures
    import tempfile
    import urllib.request

    import numpy as np

    from tpumlops.clients.localplane import free_port, start_model_server
    from tpumlops.models import bert
    from tpumlops.server.loader import save_native_model
    from tpumlops.utils.config import TpuSpec

    jax = _setup_jax()

    cfg = bert.BertConfig.base()
    params = bert.init(jax.random.key(0), cfg)
    art = tempfile.mkdtemp() + "/bert"
    save_native_model(
        art,
        "bert-classifier",
        params,
        config={
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_position_embeddings": cfg.max_position_embeddings,
            "num_labels": cfg.num_labels,
        },
        # Fixed-length bench traffic: skip the variable-length ladder so
        # server startup warms only the batch buckets at s=128 (the
        # ladder is exercised by tests and the seq-pad drive script).
        builder_kwargs={"seq_len": SEQ, "seq_buckets": False},
    )
    port = free_port()
    handle = start_model_server(
        art,
        "v1",
        port,
        model_name="bert",
        namespace="bench",
        tpu=TpuSpec.from_spec(
            {
                "meshShape": {"tp": 1},
                # 8, not BATCH: each warmed batch bucket is a full XLA
                # compile, and this dev env's remote-compile tunnel does
                # not hit the persistent cache — 4 buckets bound server
                # startup while 8 concurrent clients still fill batches.
                "maxBatchSize": 8,
                "maxBatchDelayMs": 2,
                "quantize": "int8",
            }
        ),
    )

    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, SEQ))
    # Both inputs, matching the engine's warmup examples: the batcher
    # groups by the full input-name/shape key, so an input_ids-only
    # request would form a new group and pay a live XLA compile.
    body = json.dumps(
        {
            "inputs": [
                {
                    "name": "input_ids",
                    "shape": [1, SEQ],
                    "datatype": "INT32",
                    "data": ids.ravel().tolist(),
                },
                {
                    "name": "attention_mask",
                    "shape": [1, SEQ],
                    "datatype": "INT32",
                    "data": [1] * SEQ,
                },
            ]
        }
    ).encode()

    def one_request(url: str, timeout: float) -> float:
        t0 = time.perf_counter()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        urllib.request.urlopen(req, timeout=timeout).read()
        return time.perf_counter() - t0

    def fire(url: str, n: int, timeout: float = 30.0) -> list[float]:
        return [one_request(url, timeout) for _ in range(n)]

    def fire_alternating(urls: tuple, n_pairs: int, timeout: float = 30.0):
        """Alternate between URLs per request so environment drift (the
        tunnel's minutes-scale mood swings) hits both sides equally —
        sequential phases once produced a NEGATIVE router overhead."""
        lats: tuple[list[float], ...] = tuple([] for _ in urls)
        for _ in range(n_pairs):
            for which, url in enumerate(urls):
                lats[which].append(one_request(url, timeout))
        return lats

    def warm(urls: tuple):
        # generous first-request timeout: a cold compile cache may still
        # be building an executable
        for url in urls:
            fire(url, 5, timeout=300.0)

    def measure_pair(urls: tuple, clients: int = 8, per_client: int = 12):
        with concurrent.futures.ThreadPoolExecutor(clients) as ex:
            futs = [
                ex.submit(fire_alternating, urls, per_client)
                for _ in range(clients)
            ]
            results = [f.result() for f in futs]
        out = []
        for which in range(len(urls)):
            lats = [t for r in results for t in r[which]]
            p = _percentiles(lats)
            out.append(
                {
                    "p50_ms": round(p[50] * 1000, 2),
                    "p99_ms": round(p[99] * 1000, 2),
                    "requests": len(lats),
                }
            )
        return out

    def scrape_means(base: str) -> dict[str, tuple[float, float]]:
        """(sum, count) per relevant histogram from the server's own
        /metrics — the series the promotion gate judges."""
        import re

        text = (
            urllib.request.urlopen(f"{base}/metrics", timeout=10)
            .read()
            .decode()
        )
        out = {}
        for name in (
            "seldon_api_executor_client_requests_seconds",
            "tpumlops_queue_seconds",
            "tpumlops_batch_run_seconds",
            "tpumlops_pipeline_wait_seconds",
            "tpumlops_batch_size",
        ):
            s = re.findall(rf"^{name}_sum{{[^}}]*}} ([0-9.e+-]+)", text, re.M)
            c = re.findall(rf"^{name}_count{{[^}}]*}} ([0-9.e+-]+)", text, re.M)
            out[name] = (sum(map(float, s)), sum(map(float, c)))
        return out

    router = None
    try:
        base = f"http://127.0.0.1:{port}"
        # The native router (the Istio-split stand-in) fronts the same
        # server; requests ALTERNATE direct/routed so both see the same
        # environment.
        from tpumlops.clients.router import RouterProcess

        router = RouterProcess(
            port=free_port(),
            backends={"v1": ("127.0.0.1", port, 100)},
            namespace="bench",
        ).start()
        pair_urls = (
            f"{base}/v2/models/bert/infer",
            f"http://127.0.0.1:{router.port}/v2/models/bert/infer",
        )
        warm(pair_urls)
        before = scrape_means(base)
        # Drain AFTER warmup so the warmups' routed requests (cold-path,
        # up to 300 s) cannot land in the measured router-internal tail.
        router.admin.drain_latencies()
        direct, routed = measure_pair(pair_urls)
        after = scrape_means(base)
        # Router-internal exact tail: splits the via-router p99 delta
        # into inside-the-proxy vs kernel/client-side (VERDICT r3 #4).
        internal = router.admin.drain_latencies()
        pin = _percentiles(internal) if internal else {50: 0.0, 99: 0.0}

        def mean_ms(name: str) -> float:
            ds = after[name][0] - before[name][0]
            dc = after[name][1] - before[name][1]
            return ds / dc * 1000 if dc else 0.0

        # Per-request server-side decomposition, env-independent: what
        # the server observed minus queue wait minus the device dispatch
        # itself = JSON/HTTP/glue overhead (queue+run are per-batch
        # means — a close per-request proxy at batch_per_request=1).
        total_ms = mean_ms("seldon_api_executor_client_requests_seconds")
        queue_ms = mean_ms("tpumlops_queue_seconds")
        run_ms = mean_ms("tpumlops_batch_run_seconds")
        # pipeline_wait: time a dispatched batch sat behind its
        # predecessor's device run (pipelined batcher) — real pipeline
        # occupancy, not server glue, so it gets its own term instead of
        # polluting the overhead residual.
        pipe_ms = mean_ms("tpumlops_pipeline_wait_seconds")
        server_overhead_ms = round(total_ms - queue_ms - run_ms - pipe_ms, 2)
        # Mean executed batch size: the coalescing signal (8 clients at
        # batch_per_request=1 should fill batches, not run singletons).
        bs_sum = after["tpumlops_batch_size"][0] - before["tpumlops_batch_size"][0]
        bs_cnt = after["tpumlops_batch_size"][1] - before["tpumlops_batch_size"][1]
        batch_fill = round(bs_sum / bs_cnt, 2) if bs_cnt else None
    finally:
        if router is not None:
            router.stop()
        handle.stop()
    return {
        "direct": direct,
        "via_router": routed,
        "router_overhead_p50_ms": round(
            routed["p50_ms"] - direct["p50_ms"], 2
        ),
        "router_overhead_p99_ms": round(
            routed["p99_ms"] - direct["p99_ms"], 2
        ),
        # Router's own span (headers-complete -> upstream response done),
        # exact per-request.  router_internal_p99 - direct p99 ~ proxy
        # cost; (via_router - router_internal) p99 = kernel + client-side
        # scheduling, NOT the router loop.
        "router_internal_p50_ms": round(pin[50] * 1000, 2),
        "router_internal_p99_ms": round(pin[99] * 1000, 2),
        "router_internal_samples": len(internal),
        "server_observed_mean_ms": round(total_ms, 2),
        "server_queue_mean_ms": round(queue_ms, 2),
        "server_device_run_mean_ms": round(run_ms, 2),
        "server_pipeline_wait_mean_ms": round(pipe_ms, 2),
        "server_overhead_ms": server_overhead_ms,
        "batch_fill_mean": batch_fill,
        "clients": 8,
        "batch_per_request": 1,
        "numerics": "int8",
        "note": (
            "this dev environment reaches the chip through a device "
            "tunnel (~65 ms RTT per dispatch) which dominates these "
            "absolutes; on a TPU host the compute floor is the headline "
            "per-batch latency. router_overhead is the env-independent "
            "signal here."
        ),
    }


# ---------------------------------------------------------------------------
# Time-to-100%-traffic on the real local plane
# ---------------------------------------------------------------------------


def bench_time_to_100() -> dict:
    """Full unscripted canary on the local plane: two live iris servers,
    C++ router split, gate reading the router's real histograms.  The
    step interval is accelerated (0.5 s vs the reference's 60 s); the
    policy floor scales with it, so the reported overhead — measured
    minus floor — is interval-independent."""
    import tempfile
    import threading

    from tpumlops.clients.base import ObjectRef
    from tpumlops.clients.fakes import FakeRegistry
    from tpumlops.clients.localplane import (
        SyncingKube,
        TrafficGenerator,
        free_port,
        relaxed_gate_spec,
        start_model_server,
        train_iris_pair,
    )
    from tpumlops.clients.router import (
        RouterMetricsSource,
        RouterProcess,
        RouterSync,
    )
    from tpumlops.operator.runtime import OperatorRuntime
    from tpumlops.operator.telemetry import OperatorTelemetry
    from tpumlops.utils.clock import SystemClock

    STEP_INTERVAL = 0.5
    root = tempfile.mkdtemp()
    handles = []
    ports = {}
    router = None
    rt = None
    gens = []
    try:
        for tag, uri in train_iris_pair(root).items():
            port = free_port()
            handles.append(
                start_model_server(uri, f"v{tag}", port, namespace="bench")
            )
            ports[f"v{tag}"] = port

        router = RouterProcess(
            port=free_port(), backends={}, namespace="bench"
        ).start()
        sync = RouterSync(router.admin, lambda pred: ("127.0.0.1", ports[pred]))
        kube = SyncingKube(sync)
        registry = FakeRegistry()
        registry.register("iris", "1", "mlflow-artifacts:/1/aaa/artifacts/model")
        registry.set_alias("iris", "prod", "1")
        telemetry = OperatorTelemetry()
        rt = OperatorRuntime(
            kube,
            registry,
            metrics=RouterMetricsSource(router.admin),
            clock=SystemClock(),
            sync_interval_s=0.05,
            telemetry=telemetry,
        )
        CRREF = ObjectRef(
            namespace="bench",
            name="iris",
            group="mlflow.nizepart.com",
            version="v1alpha1",
            plural="mlflowmodels",
        )
        # Reference POLICY shape: 10% steps from a 90/10 start.
        spec = relaxed_gate_spec(
            step=10,
            stepInterval=STEP_INTERVAL,
            maxAttempts=200,
            initialTraffic=10,
        )
        kube.create(
            CRREF,
            {"metadata": {"name": "iris", "namespace": "bench"}, "spec": spec},
        )

        threading.Thread(target=rt.serve, daemon=True).start()
        for _ in range(4):
            gen = TrafficGenerator(router.port)
            gen.__enter__()
            gens.append(gen)

        def status():
            return kube.get(CRREF).get("status") or {}

        # Both waits are capped against the global bench deadline (with
        # a margin for teardown + the remaining secondaries): gate
        # minSampleCount warm-up retries burned the round-4 wall and the
        # record died with the process (VERDICT r4 weak #6).
        warmup_s = min(60.0, max(10.0, _remaining() - 120.0))
        deadline = time.monotonic() + warmup_s
        while status().get("phase") != "Stable" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert status().get("phase") == "Stable", (
            f"initial rollout not Stable within {warmup_s:.0f}s: {status()}"
        )

        def component_sums() -> dict[str, float]:
            import re

            text = telemetry.exposition().decode()
            out: dict[str, float] = {}
            for m in re.finditer(
                r'tpumlops_operator_step_component_seconds_sum{[^}]*'
                r'component="(\w+)"[^}]*} ([0-9.e+-]+)',
                text,
            ):
                out[m.group(1)] = out.get(m.group(1), 0.0) + float(m.group(2))
            m = re.search(
                r"tpumlops_operator_reconcile_seconds_sum{[^}]*} ([0-9.e+-]+)",
                text,
            )
            out["_step_total"] = float(m.group(1)) if m else 0.0
            return out

        comp0 = component_sums()

        # Canary: flip the alias, time to Stable at 100%.
        registry.register("iris", "2", "mlflow-artifacts:/1/bbb/artifacts/model")
        registry.set_alias("iris", "prod", "2")
        t0 = time.monotonic()
        canary_s = min(120.0, max(15.0, _remaining() - 60.0))
        deadline = time.monotonic() + canary_s
        while time.monotonic() < deadline:
            s = status()
            if s.get("phase") == "Stable" and s.get("currentModelVersion") == "2":
                break
            time.sleep(0.05)
        measured = time.monotonic() - t0
        s = status()
        assert s.get("phase") == "Stable" and s.get("currentModelVersion") == "2", s
        comp1 = component_sums()
        breakdown_ms = {
            k: round((comp1.get(k, 0.0) - comp0.get(k, 0.0)) * 1000, 1)
            for k in sorted(set(comp0) | set(comp1))
            if k != "_step_total"
        }
        step_total_ms = round(
            (comp1.get("_step_total", 0.0) - comp0.get("_step_total", 0.0)) * 1000,
            1,
        )
    finally:
        for gen in gens:
            gen.__exit__()
        if rt is not None:
            rt.stop()
        if router is not None:
            router.stop()
        for h in handles:
            h.stop()

    # 9 gate passes take the split 10->100; the first fires immediately,
    # the rest wait out STEP_INTERVAL: floor = 8 * STEP_INTERVAL (+ one
    # monitoringInterval for the alias poll to notice the flip).
    floor = 8 * STEP_INTERVAL + 0.2
    return {
        "measured_s": round(measured, 2),
        "policy_floor_s": round(floor, 2),
        "operator_overhead_s": round(measured - floor, 2),
        "step_interval_s": STEP_INTERVAL,
        "ref_floor_same_policy_s": 480,
        "traffic_split": "native router (smooth WRR), gate on its live histograms",
        # Where the reconcile-step time inside the canary went (operator
        # telemetry component histograms; remainder = state machine +
        # event emission + scheduler glue).  VERDICT r2 #10.
        "overhead_breakdown_ms": {
            **breakdown_ms,
            "reconcile_steps_total": step_total_ms,
            "other": round(
                step_total_ms - sum(breakdown_ms.values()), 1
            ),
        },
    }


# ---------------------------------------------------------------------------
# Remaining baseline configs (secondary)
# ---------------------------------------------------------------------------


def bench_iris() -> dict:
    jax = _setup_jax()
    from sklearn.datasets import load_iris
    from sklearn.linear_model import LogisticRegression

    from tpumlops.models import linear

    X, y = load_iris(return_X_y=True)
    sk = LogisticRegression(max_iter=500).fit(X, y)
    params, cfg = linear.from_sklearn(sk)
    x = jax.numpy.asarray(X[:32], jax.numpy.float32)

    def step(p, c):
        out = linear.predict(p, c, cfg)
        return _gate(c, out), out[0]

    # µs-scale body: long scans so the delta rises above RTT jitter.
    p = _scan_delta_timed(
        step, lambda i: x + 0.001 * i, n1=512, n2=8192, params=params
    )
    return {"p50_us": round(p[50] * 1e6, 1), "batch": 32,
            "method": p.get("method", "scan_delta")}


def bench_xgboost() -> dict:
    """Synthetic 200-tree depth-6 regression forest via the JSON path,
    lowered by tabular.lower_forest — normally the GEMM (matmul) form,
    ~11x the gather traversal on v5e; eval_form reports which ran."""
    jax = _setup_jax()
    import numpy as np

    from tpumlops.models import tabular

    rng = np.random.default_rng(0)
    n_feat, depth, n_trees = 16, 6, 200
    n_nodes = 2 ** (depth + 1) - 1
    n_internal = 2**depth - 1
    trees = []
    for _ in range(n_trees):
        left = [2 * i + 1 if i < n_internal else -1 for i in range(n_nodes)]
        right = [2 * i + 2 if i < n_internal else -1 for i in range(n_nodes)]
        trees.append(
            {
                "left_children": left,
                "right_children": right,
                "split_indices": rng.integers(0, n_feat, n_nodes).tolist(),
                "split_conditions": rng.normal(size=n_nodes).astype(float).tolist(),
                "default_left": [1] * n_nodes,
                "tree_param": {
                    "num_nodes": str(n_nodes),
                    "size_leaf_vector": "1",
                },
            }
        )
    model = {
        "learner": {
            "gradient_booster": {
                "model": {"trees": trees, "tree_info": [0] * n_trees},
                "name": "gbtree",
            },
            "learner_model_param": {
                "base_score": "0.0",
                "num_class": "0",
                "num_feature": str(n_feat),
            },
            "objective": {"name": "reg:squarederror"},
        }
    }
    arrs, _obj = tabular.from_xgboost_json(model)
    fn, form = tabular.lower_forest(arrs)
    x = jax.numpy.asarray(rng.normal(size=(256, n_feat)), jax.numpy.float32)

    def step(c):
        out = fn(c)
        return _gate(c, out), out.reshape(-1)[0]

    p = _scan_delta_timed(step, lambda i: x + 0.001 * i, n1=128, n2=1024)
    return {
        "p50_us": round(p[50] * 1e6, 1),
        "trees": n_trees,
        "batch": 256,
        "eval_form": form,
        "method": p.get("method", "scan_delta"),
    }


def bench_resnet() -> dict:
    """ResNet-50 batch ladder (VERDICT r2 #6): b8 is the latency point;
    b32/b128 are the throughput points where conv im2col tiles fill the
    MXU.  ``mfu`` uses ~4.1 GFLOP per 224x224 forward (fwd conv+fc MACs
    x2) against the v5e bf16 peak."""
    jax = _setup_jax()
    import jax.numpy as jnp

    from tpumlops.models import resnet

    cfg = resnet.ResNetConfig.resnet50()
    params = resnet.init(jax.random.key(0), cfg)
    FLOPS_PER_IMG = 4.1e9
    out = {"ladder": {}}
    best = None
    for batch, (n1, n2) in ((8, (8, 48)), (32, (4, 24)), (128, (2, 10))):
        x = jax.random.normal(
            jax.random.key(1), (batch, 224, 224, 3), jnp.bfloat16
        )

        def step(p, c):
            out = resnet.forward(p, c, cfg)
            return _gate(c, out), out[0, 0]

        p = _scan_delta_timed(
            step, lambda i: x + jnp.bfloat16(0.01) * i, n1=n1, n2=n2,
            params=params,
        )
        tflops = batch * FLOPS_PER_IMG / p[50] / 1e12
        entry = {
            "p50_ms": round(p[50] * 1000, 3),
            "img_per_s": round(batch / p[50], 1),
            "tflops": round(tflops, 1),
            "mfu": round(tflops / V5E_BF16_TFLOPS, 3),
        }
        out["ladder"][str(batch)] = entry
        if best is None or entry["img_per_s"] > best["img_per_s"]:
            best = entry
    out.update(best)
    out["vs_gpu_baseline"] = {
        "t4_int8_mlperf": round(best["img_per_s"] / GPU_ANCHORS["resnet50_t4_img_s"], 2),
        "a100_int8_mlperf": round(
            best["img_per_s"] / GPU_ANCHORS["resnet50_a100_img_s"], 2
        ),
    }
    return out


def _decode_device_loop(jax, params, cfg, slots: int, *, kv_quant: bool,
                        window: int, position: int, n1: int = 8,
                        n2: int = 40, chained_step: bool = False) -> float:
    """Seconds per decode step via the scan-delta methodology: the decode
    chain (token + cache feedback) runs entirely on device, so the only
    host contribution is the dispatch constant the two-length delta
    cancels.

    ``chained_step=True`` is the fallback when the SCAN form will not
    compile: the AOT compile helper does not credit the donated carry's
    input->output aliasing through a ``lax.scan``, so 7B at 32 slots
    prices at weights + 2x cache (~22 GiB > 16) and is rejected with an
    opaque HTTP 500, while the bare step compiles (aliasing credited,
    15.6 GiB).  The fallback times two chained SEQUENCES of bare-step
    dispatches (each call's carry is the previous call's output, final
    probe pulled through the data path) and differences the sequence
    lengths — per-dispatch enqueue cost that scales with length does
    NOT cancel, so the result is an upper bound on the step time;
    callers record the method."""
    import jax.numpy as jnp

    from tpumlops.models import llama

    def step(p, carry):
        toks, cache = carry
        logits, cache = llama.decode_ragged(
            p, toks, cache, cfg, window=window
        )
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
        return (nxt, cache), nxt[0, 0]

    def carry_at(i):
        # Fresh cache per call: the carry is DONATED into the scan so the
        # multi-GiB buffers live once, not twice (input + loop copy) —
        # at 7B geometry that double-buffering is what pushed 32 slots
        # past 16 GiB (round-3 slot_ladder["32"] compile failure).
        if kv_quant:
            cache = llama.QuantRaggedKVCache.create(cfg, slots)
        else:
            cache = llama.RaggedKVCache.create(cfg, slots, jnp.bfloat16)
        cache = cache._replace(
            lengths=jnp.full((slots,), position, jnp.int32)
        )
        toks = jnp.full((slots, 1), (7 + i) % 1000 + 1, jnp.int32)
        return (toks, cache)

    if chained_step:
        import numpy as np

        f = jax.jit(step, donate_argnums=(1,))

        def chain(i, m):
            # The replay probe is the SUM of every step's sampled token.
            # The per-step probes are only APPENDED to a host list inside
            # the timed window (free — no extra device op may enter the
            # loop: a per-step dispatch would scale with chain length and
            # NOT cancel in the delta); the one summing dispatch + sync
            # runs after t1.  Distinct chain lengths from distinct carries
            # must produce distinct sums, so a result-replaying tunnel
            # shows up as identical probes, not just as a near-zero wall
            # (ADVICE r5 #3 — the primary scan path has tainted-pair
            # detection; this carries the equivalent).
            carry = carry_at(i)
            plist = []
            t0 = time.perf_counter()
            for _ in range(m):
                carry, probe = f(params, carry)
                plist.append(probe)
            np.asarray(plist[-1])  # sync: the chain really ran to the end
            wall = time.perf_counter() - t0
            acc = int(np.asarray(jnp.stack(plist).sum()))
            return wall, acc

        chain(-11, 2)  # compile + warm
        samples, probes = [], []
        for r in range(5):  # 5 rounds, raw samples recorded for audit
            w1, a1 = chain(5000 + 2 * r, n1)
            w2, a2 = chain(5000 + 2 * r + 1, n2)
            samples.append(max(0.0, (w2 - w1) / (n2 - n1)))
            probes.append([a1, a2])
        # Auditability: _run_slot_ladder embeds these on chained points.
        _decode_device_loop.last_chained = {
            "raw_ms_per_step": [round(s * 1000, 3) for s in samples],
            "probe_sums": probes,
        }
        med = _percentiles(samples)[50]
        if med <= 0.0:
            raise RuntimeError(
                "chained-step fallback collapsed to zero — replay/elision"
            )
        if all(a1 == a2 for a1, a2 in probes):
            # n1- and n2-length chains from distinct carries summed to the
            # same value in EVERY round: the tunnel is replaying results.
            raise RuntimeError(
                "chained-step probe sums identical across chain lengths "
                "in all rounds — replay suspected"
            )
        return med

    p = _scan_delta_timed(
        step, carry_at, n1=n1, n2=n2, params=params, donate_carry=True
    )
    return p[50]


def _run_slot_ladder(
    jax, params, cfg, slot_counts, *, window: int, position: int,
    n1: int, n2: int,
) -> tuple[dict, tuple[int, dict] | None]:
    """Shared decode slot ladder: (ladder dict, best (slots, entry)).

    One bad point (e.g. OOM at the top slot count) records its error and
    must not void the rest of the curve."""
    from tpumlops.models import llama

    ladder: dict = {}
    best = None
    for slots in slot_counts:
        attn_impl = llama._decode_attn_impl()
        method = "scan_delta"
        try:
            dt = _decode_device_loop(
                jax, params, cfg, slots, kv_quant=True, window=window,
                position=position, n1=n1, n2=n2,
            )
        except Exception as e:
            err1 = f"{type(e).__name__}: {e}"[:160]
            # The scan form at 7B/32 slots is REJECTED by the AOT
            # compile helper regardless of attention impl: it does not
            # credit the donated cache's aliasing through the scan, so
            # the program prices at weights + 2x cache (~22 GiB > 16)
            # and the helper dies with an opaque HTTP 500, while the
            # BARE step compiles (15.6 GiB, aliasing credited).  Retry
            # on data-chained bare-step dispatches — an upper bound on
            # the step time (enqueue cost does not fully cancel), so the
            # method is recorded on the point.
            scan_error = err1
            try:
                dt = _decode_device_loop(
                    jax, params, cfg, slots, kv_quant=True, window=window,
                    position=position, n1=min(n1, 4), n2=min(n2, 16),
                    chained_step=True,
                )
                method = "chained_step (scan form failed)"
            except Exception as e2:
                ladder[str(slots)] = {
                    "error": err1,
                    "chained_retry_error": f"{type(e2).__name__}: {e2}"[:160],
                }
                continue
        else:
            scan_error = None
        # Plausibility floor: a decode step cannot beat streaming the
        # weights once from HBM.  The round-3 tunnel sometimes replays
        # cached results (or loads a poisoned compile-cache entry) and
        # "measures" physically impossible steps — reject, don't record.
        from tpumlops.models.quantization import quantized_bytes

        floor_dt = quantized_bytes(params) / (V5E_HBM_GBPS * 1e9)
        if dt < 0.5 * floor_dt:
            ladder[str(slots)] = {
                "error": f"implausible {dt * 1000:.2f} ms/step < 0.5x weight"
                         f"-stream floor {floor_dt * 1000:.2f} ms (tunnel "
                         "elision)"
            }
            continue
        gbps = _decode_hbm_bytes(params, cfg, slots, window, True) / dt / 1e9
        entry = {
            "tok_per_s": round(slots / dt, 1),
            "ms_per_step": round(dt * 1000, 2),
            "hbm_gb_per_s": round(gbps, 1),
            "bw_util": round(gbps / V5E_HBM_GBPS, 3),
            "attn_impl": attn_impl,
            "method": method,
        }
        if scan_error is not None:
            # Provenance: the primary methodology's actual failure, so a
            # chained-step point never claims a failure mode it didn't
            # have (compile rejection vs anti-elision guard vs OOM) —
            # plus the fallback's raw samples and probe sums for audit.
            entry["scan_error"] = scan_error
            audit = getattr(_decode_device_loop, "last_chained", None)
            if audit is not None:
                entry["chained_audit"] = audit
        ladder[str(slots)] = entry
        if best is None or entry["tok_per_s"] > best[1]["tok_per_s"]:
            best = (slots, entry)
    return ladder, best


def _decode_hbm_bytes(params, cfg, slots: int, window: int, kv_quant: bool) -> int:
    """HBM bytes one decode step must stream: all weights (as stored) +
    the attended KV window (k+v, + f32 scales when quantized)."""
    from tpumlops.models.quantization import quantized_bytes

    kv_elem = slots * window * cfg.num_kv_heads * cfg.head_dim * cfg.num_layers
    kv = 2 * kv_elem * (1 if kv_quant else 2)
    if kv_quant:  # per-(pos, head) f32 scale, head_dim amortized
        kv += 2 * kv_elem // cfg.head_dim * 4
    return quantized_bytes(params) + kv


def _device_cost_keys(
    params, cfg, slots: int, tok_per_s: float, kv_quant: bool = False
) -> dict:
    """The ``mfu`` / ``hbm_peak_bytes`` pair every serving scenario's
    compact output carries (server/device_telemetry.py cost model):
    ``mfu`` is model-forward tokens/s x 2 FLOPs/matmul-param against the
    device peak (the weight-stream term; attention adds a few percent at
    these shapes), ``hbm_peak_bytes`` the analytic ledger total (weights
    + KV cache + sampling state) for the scenario's engine geometry.  On
    the CPU dev tunnel mfu is honestly tiny; on chip it is the roofline
    position the scenario's headline number sits at."""
    from tpumlops.server.device_telemetry import (
        LlamaCostModel,
        build_hbm_ledger,
        detect_peaks,
        param_device_count,
    )

    peaks = detect_peaks().scaled(param_device_count(params))
    cost = LlamaCostModel.for_model(params, cfg, kv_quant=kv_quant)
    ledger = build_hbm_ledger(params, cfg, slots, kv_quant=kv_quant)
    mfu = min(
        1.0,
        max(0.0, float(tok_per_s)) * 2.0 * cost.matmul_params
        / peaks.flops_per_s,
    )
    return {
        "mfu": float(f"{mfu:.3g}"),
        "hbm_peak_bytes": ledger.device_total(),
    }


def bench_prefix_cache() -> dict:
    """Shared-prefix serving scenario: radix prefix KV cache
    (server/prefix_cache.py) at a small llama shape.

    Thousands of requests sharing one system prompt re-prefill it today;
    with the cache, the prefix's K/V is copied (one seed op) and only the
    unique suffix runs real prefill.  Reported: TTFT (submit -> first
    token through the real engine scheduler) cold vs warm, and the
    prefill-chunk-call counter per admission — the direct evidence that
    cached admits skip recomputation.  TTFT here rides this
    environment's per-dispatch tunnel cost (~65 ms/op), so the chunk
    counts are the environment-independent signal; on a real host the
    TTFT ratio approaches the chunk ratio."""
    import threading

    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.prefix_cache import PrefixCacheConfig

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=768,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    C = 128
    rng = np.random.default_rng(0)
    shared = rng.integers(1, cfg.vocab_size, size=512, dtype=np.int64)
    engine = GenerationEngine(
        params, cfg, max_slots=4, dtype=jnp.bfloat16,
        prefix_cache=PrefixCacheConfig(
            enabled=True, budget_bytes=64 * 2**20, chunk_tokens=C
        ),
    )
    engine.start(warmup=True)

    def one_request(suffix_seed: int) -> float:
        """Submit shared-prefix + unique-suffix; return TTFT seconds."""
        sfx = np.random.default_rng(1000 + suffix_seed).integers(
            1, cfg.vocab_size, size=32, dtype=np.int64
        )
        prompt = np.concatenate([shared, sfx]).tolist()
        first = threading.Event()
        t0 = time.perf_counter()
        fut = engine.submit(prompt, 4, on_token=lambda _t: first.set())
        assert first.wait(timeout=300), "no first token"
        ttft = time.perf_counter() - t0
        fut.result(timeout=300)
        return ttft

    try:
        chunks0 = engine.prefill_chunks_dispatched
        cold_ttft = one_request(1)
        chunks_cold = engine.prefill_chunks_dispatched - chunks0
        warm_ttfts = []
        warm_chunks = []  # per-admission: EVERY warm admit must shrink
        for i in range(4):
            before = engine.prefill_chunks_dispatched
            warm_ttfts.append(one_request(2 + i))
            warm_chunks.append(engine.prefill_chunks_dispatched - before)
        warm_ttft = sorted(warm_ttfts)[len(warm_ttfts) // 2]
        hits = engine.prefix_hits
        cached = engine.prefix_cached_tokens
        evictions = engine.prefix_evictions
    finally:
        engine.shutdown()
    # 544-token prompt, 128-token chunks: cold = 5 chunk calls, warm = 1
    # (512 cached) — the counter drop IS the skipped recomputation.  Every
    # warm admission is checked, not just the last: one silent miss would
    # otherwise hide behind its siblings.
    chunks_warm = max(warm_chunks)
    assert chunks_warm < chunks_cold, (warm_chunks, chunks_cold)
    assert hits >= 4 and cached >= 4 * 512, (hits, cached)
    prompt_tokens = 512 + 32
    return {
        "cold_ttft_ms": round(cold_ttft * 1000, 1),
        "warm_ttft_ms": round(warm_ttft * 1000, 1),
        "ttft_speedup": round(cold_ttft / warm_ttft, 2),
        # Admission throughput: prompt tokens made decode-ready per second
        # of TTFT (warm counts the cache-seeded 512 as served — they are).
        "prefill_tok_per_s_cold": round(prompt_tokens / cold_ttft, 1),
        "prefill_tok_per_s_warm": round(prompt_tokens / warm_ttft, 1),
        "chunks_cold": chunks_cold,
        "chunks_warm": chunks_warm,
        "chunks_per_warm_admit": warm_chunks,
        "cached_tokens_per_warm_hit": cached // hits,
        "hits": hits,
        "evictions": evictions,
        **_device_cost_keys(params, cfg, 4, prompt_tokens / warm_ttft),
        "note": (
            "engine-loop TTFT rides the dev tunnel's ~65 ms/dispatch; the "
            "chunk-call drop (cold 5 -> warm 1 per admission) is the "
            "environment-independent number"
        ),
    }


def bench_speculative() -> dict:
    """Self-speculative n-gram decoding through the real engine scheduler
    (server/speculative.py + models/llama.verify_ragged).

    Decode streams the full weight tree per tick; speculation verifies k
    drafted tokens in ONE forward, so accepted drafts multiply tokens
    per weight stream.  Two corpora bound the behavior:

    - ``repetitive``: prefixes of the model's own greedy rollouts.
      Untrained greedy trajectories collapse into short cycles, so the
      continuation re-emits spans already in the context — exactly the
      structure prompt-lookup drafting converts (stand-in for templated
      /extraction traffic on a trained model).
    - ``random``: uniform token prompts, the adversarial case — drafts
      rarely match and the adaptive controller parks slots back onto the
      plain single-token step.

    The environment-independent signal is ``forwards_per_token`` (decode
    dispatches / decode-emitted tokens): < 1 means the weight stream was
    amortized end-to-end.  Engine-loop tok/s rides this environment's
    ~65 ms/dispatch tunnel — which UNDERSTATES the on-host win less than
    it distorts raw latency, since speculation's whole effect is fewer
    dispatches per token."""
    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.speculative import SpeculativeConfig

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    N_REQ, PROMPT, NEW, DRAFT = 4, 64, 48, 4

    def run_corpora(engine, corpora, acc_pairs=None):
        out = {}
        for name, corpus in corpora.items():
            if acc_pairs is not None:
                acc_pairs.clear()
            f0, tk0 = engine.decode_forwards, engine.decode_tokens
            p0, a0 = engine.spec_proposed_tokens, engine.spec_accepted_tokens
            t0 = time.perf_counter()
            futs = [engine.submit(p, NEW) for p in corpus]
            toks = [np.asarray(f.result(timeout=600)).tolist() for f in futs]
            wall = time.perf_counter() - t0
            emitted = engine.decode_tokens - tk0
            forwards = engine.decode_forwards - f0
            proposed = engine.spec_proposed_tokens - p0
            accepted = engine.spec_accepted_tokens - a0
            hist: dict[int, int] = {}
            for _, a in acc_pairs or ():
                hist[a] = hist.get(a, 0) + 1
            out[name] = {
                "wall_s": round(wall, 2),
                "tok_per_s": round(N_REQ * NEW / wall, 1),
                "forwards": forwards,
                "emitted_tokens": emitted,
                "forwards_per_token": round(forwards / max(1, emitted), 3),
                "acceptance_rate": (
                    round(accepted / proposed, 3) if proposed else None
                ),
                "proposed": proposed,
                "accepted": accepted,
                "accepted_len_hist": {str(k): v for k, v in sorted(hist.items())},
                "outputs": toks,
            }
        return out

    # Corpus construction + the non-speculative baseline, one engine.
    base = GenerationEngine(params, cfg, max_slots=4, dtype=jnp.bfloat16)
    base.start(warmup=True)
    try:
        templated = []
        for i in range(N_REQ):
            roll = np.asarray(
                base.generate([17 + i], PROMPT + 30, timeout=600)
            ).tolist()
            templated.append(([17 + i] + roll)[:PROMPT])
        rng = np.random.default_rng(0)
        corpora = {
            "repetitive": templated,
            "random": [
                rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
                for _ in range(N_REQ)
            ],
        }
        plain = run_corpora(base, corpora)
    finally:
        base.shutdown()

    acc_pairs: list = []
    engine = GenerationEngine(
        params, cfg, max_slots=4, dtype=jnp.bfloat16,
        speculative=SpeculativeConfig(
            enabled=True, draft_tokens=DRAFT, ngram_min=1, ngram_max=4,
            adaptive=True,
        ),
        on_spec=lambda p, a: acc_pairs.append((p, a)),
    )
    engine.start(warmup=True)
    try:
        spec = run_corpora(engine, corpora, acc_pairs)
    finally:
        engine.shutdown()

    rep, rnd = spec["repetitive"], spec["random"]
    # The acceptance bar: on the repetitive corpus the weight stream must
    # be amortized END TO END — fewer decode forwards than emitted tokens.
    assert rep["forwards_per_token"] < 1.0, rep
    for name in corpora:
        # bf16 near-tie argmaxes can differ between the 1-token and
        # k+1-token programs; report agreement rather than assert it
        # (the f64 bit-identity proof lives in tests/test_speculative.py).
        a = [t for o in plain[name]["outputs"] for t in o]
        b = [t for o in spec[name]["outputs"] for t in o]
        spec[name]["token_agreement"] = round(
            float(np.mean([x == y for x, y in zip(a, b)])), 3
        )
        del plain[name]["outputs"], spec[name]["outputs"]

    return {
        "draft_tokens": DRAFT,
        "requests": N_REQ,
        "new_tokens_per_request": NEW,
        "rep_forwards_per_token": rep["forwards_per_token"],
        "rep_acceptance_rate": rep["acceptance_rate"],
        "rep_tok_per_s": rep["tok_per_s"],
        "rnd_forwards_per_token": rnd["forwards_per_token"],
        # Same batching on both sides, so the plain engine's ratio (1 /
        # active slots) is the baseline the speculative drop is read
        # against.
        "plain_forwards_per_token": plain["repetitive"]["forwards_per_token"],
        "speedup_vs_plain_repetitive": round(
            plain["repetitive"]["wall_s"] / rep["wall_s"], 2
        ),
        "speedup_vs_plain_random": round(
            plain["random"]["wall_s"] / rnd["wall_s"], 2
        ),
        **_device_cost_keys(params, cfg, 4, rep["tok_per_s"]),
        "plain": plain,
        "speculative": spec,
        "note": (
            "engine-loop walls ride the dev tunnel's ~65 ms/dispatch; "
            "forwards_per_token is the environment-independent number "
            "(each forward is one full HBM weight stream)"
        ),
    }


def bench_multistep() -> dict:
    """Fused multi-step decode through the real engine scheduler
    (server/generation.py decodeSteps): the same greedy serving run at
    K in {1, 2, 4, 8} — K=1 is the single-step tick loop byte-for-byte,
    K>1 dispatches ONE lax.scan program per tick that runs K decode
    steps with on-device sampling and an EOS latch, and harvests each
    tick's token block one tick behind (lag-1 async readback).

    The environment-independent number is DECODE DISPATCHES PER TOKEN:
    every dispatch is one host->device round trip plus (in this
    environment) the ~65 ms tunnel, and fusing collapses it ~K-fold —
    at 4 active slots K=1 pays 1/4 dispatch/token and K=4 ~1/16.  The
    acceptance bar is hard: K=4 must show >= 3x fewer decode dispatches
    per token than K=1 (padding at request tails eats the last of the
    4x), with token agreement 1.0 (the f64 bit-identity proof lives in
    tests/test_multistep.py).  ITL percentiles ride the tunnel but show
    the cadence shape a streaming client feels (tokens arrive in
    K-blocks)."""
    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    N_REQ, PROMPT, NEW, SLOTS = 4, 32, 64, 4
    rng = np.random.default_rng(0)
    # N_REQ == SLOTS so the queue drains at the first admit phase and
    # fused ticks engage immediately (a queued request suppresses
    # fusing by design — slots must free at single-step cadence then).
    prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_REQ)
    ]

    def run(k: int) -> dict:
        itls: list[float] = []
        engine = GenerationEngine(
            params, cfg, max_slots=SLOTS, dtype=jnp.bfloat16,
            decode_steps=k, on_itl=itls.append,
        )
        engine.start(warmup=True)
        try:
            f0 = engine.decode_forwards
            d0 = dict(engine.dispatches_total)
            t0 = time.perf_counter()
            futs = [engine.submit(p, NEW) for p in prompts]
            outs = [np.asarray(f.result(timeout=600)).tolist() for f in futs]
            wall = time.perf_counter() - t0
            forwards = engine.decode_forwards - f0
            tokens = engine.decode_tokens
            disp = {
                op: engine.dispatches_total.get(op, 0) - d0.get(op, 0)
                for op in engine.dispatches_total
            }
        finally:
            engine.shutdown()
        p = _percentiles([t * 1000 for t in itls]) if itls else {50: 0.0, 99: 0.0}
        return {
            "wall_s": wall,
            "tok_per_s": round(N_REQ * NEW / wall, 1),
            "decode_dispatches": forwards,
            "dispatches_per_token": round(forwards / max(1, tokens), 4),
            "dispatch_mix": disp,
            "itl_p50_ms": round(p[50], 2),
            "itl_p99_ms": round(p[99], 2),
            "outputs": outs,
        }

    ladder = {k: run(k) for k in (1, 2, 4, 8)}
    base = [t for o in ladder[1]["outputs"] for t in o]
    agreement = {}
    for k in (2, 4, 8):
        cur = [t for o in ladder[k]["outputs"] for t in o]
        agreement[k] = round(
            float(np.mean([x == y for x, y in zip(base, cur)])), 3
        )
        del ladder[k]["outputs"]
    del ladder[1]["outputs"]
    # The acceptance bar (ISSUE 10): >= 3x fewer decode dispatches per
    # token at K=4.  HARD assertion — a fusing regression must fail the
    # bench, not quietly ship a smaller ratio.
    assert (
        ladder[4]["dispatches_per_token"] * 3
        <= ladder[1]["dispatches_per_token"]
    ), (ladder[4]["dispatches_per_token"], ladder[1]["dispatches_per_token"])
    return {
        "requests": N_REQ,
        "new_tokens_per_request": NEW,
        "slots": SLOTS,
        "k1_dispatches_per_token": ladder[1]["dispatches_per_token"],
        "k4_dispatches_per_token": ladder[4]["dispatches_per_token"],
        "dispatch_reduction_k4": round(
            ladder[1]["dispatches_per_token"]
            / max(1e-9, ladder[4]["dispatches_per_token"]), 2
        ),
        "tok_per_s_k1": ladder[1]["tok_per_s"],
        "tok_per_s_k4": ladder[4]["tok_per_s"],
        "itl_p50_ms_k4": ladder[4]["itl_p50_ms"],
        "itl_p99_ms_k4": ladder[4]["itl_p99_ms"],
        "token_agreement": min(agreement.values()),
        "ladder": {str(k): v for k, v in ladder.items()},
        "agreement_by_k": {str(k): v for k, v in agreement.items()},
        **_device_cost_keys(params, cfg, SLOTS, ladder[4]["tok_per_s"]),
        "note": (
            "engine-loop walls ride the dev tunnel's ~65 ms/dispatch; "
            "decode dispatches per token is the environment-independent "
            "number (each dispatch is one host round trip the fused "
            "scan amortizes K ways)"
        ),
    }


def bench_superstep() -> dict:
    """Unified ragged super-step (spec.tpu.unifiedStep) vs the legacy
    per-role dispatch ladder, on the MIXED workload the fusion exists
    for: concurrent cold prefills, long decodes, and speculative-
    friendly repeats all in flight at once, at decodeSteps=4 with
    packed prefill and the n-gram draft enabled.

    The headline numbers are the ones the roadmap optimises:

    - COMPILE COUNT: the legacy engine warms one jit variant per
      (op x window-bucket) across decode/multistep/verify/packed; the
      unified engine warms one super-step per (window-bucket x
      sampling-mode).  The acceptance bar is hard: >= 3x fewer compiled
      variants (asserted here AND in the `make verify` compile-budget
      gate against COMPILE_BUDGET.json).
    - WARMUP WALL: fewer programs to trace+compile is the cold-start
      win a rollout feels (docs/SCALE.md snapshot geometry shrinks the
      same way).
    - DISPATCHES PER TOKEN: the super-step commits prefill chunks,
      decodes fused-K chains, and verifies drafts in ONE program, so a
      mixed tick is one host round trip instead of two or three.
    - INTERLEAVE STALL: in the legacy engine a prefill chunk tick
      stalls decoding rows for a full dispatch; fused, decode rows keep
      stepping while the chunk commits.  The ITL p99 delta during the
      admission phase is that stall made visible.

    The run is f32: the two engines compile DIFFERENT programs for the
    same math, and bf16's 8-bit mantissa lets fusion-order rounding
    flip argmax at near-ties (measured 0.93 agreement at bf16 — honest
    noise, not a scheduler bug); f32 keeps the trajectories identical
    so token_agreement pins at 1.0 here, and the f64 bit-identity
    proof (greedy, seeded sampling, speculative, packed, prefix-cache,
    int8kv, tp, multihost replay) lives in tests/test_superstep.py.
    Compile counts and dispatch ledgers are dtype-independent."""
    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.device_telemetry import DeviceTelemetry
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.speculative import SpeculativeConfig

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.float32)
    N_REQ, PROMPT, NEW, SLOTS, K = 6, 48, 48, 4, 4
    rng = np.random.default_rng(0)
    # Mixed-role pressure: N_REQ > SLOTS keeps cold prefills arriving
    # while earlier rows are mid-decode (the tick the super-step
    # fuses), and odd-indexed prompts repeat a short phrase so the
    # n-gram draft proposes speculative chains worth verifying.
    prompts = []
    for i in range(N_REQ):
        if i % 2 == 0:
            prompts.append(
                rng.integers(1, cfg.vocab_size, size=PROMPT).tolist())
        else:
            phrase = rng.integers(1, cfg.vocab_size, size=6).tolist()
            prompts.append((phrase * ((PROMPT + 5) // 6))[:PROMPT])

    # Every host->device round trip the tick loop pays for generation:
    # the legacy engine splits a mixed moment across decode/multistep/
    # verify programs PLUS packed-prefill chunk calls; the unified
    # engine folds all four roles into superstep dispatches.
    GEN_OPS = (
        "decode", "multistep", "verify", "packed-prefill", "superstep")

    def run(unified: bool) -> dict:
        telemetry = DeviceTelemetry()
        itls: list[float] = []
        engine = GenerationEngine(
            params, cfg, max_slots=SLOTS, dtype=jnp.float32,
            decode_steps=K,
            speculative=SpeculativeConfig(
                enabled=True, draft_tokens=2, ngram_min=1, ngram_max=4,
                adaptive=True,
            ),
            prefill_chunk=16, prefill_batch=4,
            unified_step=unified, telemetry=telemetry,
            on_itl=itls.append,
        )
        w0 = time.perf_counter()
        engine.start(warmup=True)
        warmup_s = time.perf_counter() - w0
        try:
            d0 = dict(engine.dispatches_total)
            t0 = time.perf_counter()
            futs = [engine.submit(p, NEW) for p in prompts]
            outs = [
                np.asarray(f.result(timeout=600)).tolist() for f in futs
            ]
            wall = time.perf_counter() - t0
            disp = {
                op: engine.dispatches_total.get(op, 0) - d0.get(op, 0)
                for op in engine.dispatches_total
            }
        finally:
            engine.shutdown()
        warm = telemetry.observatory.snapshot()["warmup"]
        gen_disp = sum(disp.get(op, 0) for op in GEN_OPS)
        p = (
            _percentiles([t * 1000 for t in itls])
            if itls else {50: 0.0, 99: 0.0}
        )
        return {
            "warmup_s": round(warmup_s, 2),
            "compiles": warm["compiles"],
            "variant_inventory": dict(warm.get("ops", {})),
            "wall_s": wall,
            "tok_per_s": round(N_REQ * NEW / wall, 1),
            "generate_dispatches": gen_disp,
            "dispatches_per_token": round(
                gen_disp / max(1, N_REQ * NEW), 4),
            "dispatch_mix": disp,
            "itl_p50_ms": round(p[50], 2),
            "itl_p99_ms": round(p[99], 2),
            "outputs": outs,
        }

    legacy = run(unified=False)
    unified = run(unified=True)
    base = [t for o in legacy.pop("outputs") for t in o]
    cur = [t for o in unified.pop("outputs") for t in o]
    agreement = round(
        float(np.mean([x == y for x, y in zip(base, cur)])), 3)
    # The acceptance bar (ISSUE 16): the unified warmup must compile
    # >= 3x fewer jit variants than the legacy cross-product.  HARD
    # assertion — a program-space regression must fail the bench, not
    # quietly ship a smaller collapse.
    assert unified["compiles"] * 3 <= legacy["compiles"], (
        unified["compiles"], legacy["compiles"])
    return {
        "requests": N_REQ,
        "new_tokens_per_request": NEW,
        "slots": SLOTS,
        "decode_steps": K,
        "legacy_compiles": legacy["compiles"],
        "unified_compiles": unified["compiles"],
        "compile_collapse_ratio": round(
            legacy["compiles"] / max(1, unified["compiles"]), 2),
        "legacy_warmup_s": legacy["warmup_s"],
        "unified_warmup_s": unified["warmup_s"],
        "legacy_dispatches_per_token": legacy["dispatches_per_token"],
        "unified_dispatches_per_token": unified["dispatches_per_token"],
        "tok_per_s_legacy": legacy["tok_per_s"],
        "tok_per_s_unified": unified["tok_per_s"],
        "itl_p99_ms_legacy": legacy["itl_p99_ms"],
        "itl_p99_ms_unified": unified["itl_p99_ms"],
        "interleave_stall_delta_ms": round(
            legacy["itl_p99_ms"] - unified["itl_p99_ms"], 2),
        "variant_inventory": unified["variant_inventory"],
        "token_agreement": agreement,
        "detail": {"legacy": legacy, "unified": unified},
        **_device_cost_keys(params, cfg, SLOTS, unified["tok_per_s"]),
        "note": (
            "compile count and dispatches/token are the environment-"
            "independent numbers.  On this CPU rig per-tick COMPUTE "
            "dominates (a fused K-step superstep program is a bigger "
            "program than a legacy verify tick), so unified tok/s and "
            "ITL read worse and the interleave-stall delta can go "
            "negative here; on a dispatch-bound rig (the ~65 ms/op "
            "dev tunnel, a real accelerator host) those walls track "
            "the dispatch ledger instead.  f64 token parity is pinned "
            "in tests/test_superstep.py."
        ),
    }


def bench_tensor_parallel() -> dict:
    """Tensor-parallel serving through the real engine scheduler
    (spec.tpu.meshShape): the same greedy serving run at tp in {1, 2, 4}
    on forced host devices — weights Megatron-split by the
    models/partition.py rule table, the ragged KV cache split on its
    heads axis, every engine program compiled with explicit shardings.

    The environment-independent numbers are the HARD gates: token
    agreement 1.0 across the ladder (sharding must not change a single
    emitted token) and per-token DISPATCH COUNTS unchanged (sharding
    must not add host round-trips — K/V commits, the sampling chain,
    and donated buffers stay device-resident and sharded across ticks).
    Per-chip HBM is the capacity story: weights bytes/chip drop ~1/tp
    (replicated norms keep the tail), which is what unlocks the 7B+
    tier on 16 GiB chips.  tok/s on the CPU dev mesh is honest but
    meaningless for speed (SPMD emulation overhead); on a real slice
    the ladder's tok/s shows the ICI-bound scaling curve."""
    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama, partition
    from tpumlops.server.device_telemetry import build_hbm_ledger
    from tpumlops.server.generation import GenerationEngine

    n_dev = len(jax.devices())
    if n_dev < 4:
        return {
            "skipped": (
                f"tp ladder needs >= 4 devices, have {n_dev} (run under "
                "--xla_force_host_platform_device_count or a multi-chip "
                "slice)"
            )
        }

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    N_REQ, PROMPT, NEW, SLOTS = 4, 32, 48, 4
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_REQ)
    ]

    def run(tp: int) -> dict:
        mesh_shape = {"dp": 1, "tp": tp}
        p = params
        if tp > 1:
            p = partition.shard_llama_params(
                params, partition.build_serving_mesh(mesh_shape)
            )
        engine = GenerationEngine(
            p, cfg, max_slots=SLOTS, dtype=jnp.bfloat16,
            mesh_shape=mesh_shape,
        )
        engine.start(warmup=True)
        try:
            t0 = time.perf_counter()
            futs = [engine.submit(pr, NEW) for pr in prompts]
            outs = [np.asarray(f.result(timeout=600)).tolist() for f in futs]
            wall = time.perf_counter() - t0
            disp = dict(engine.dispatches_total)
            tokens = engine.decode_tokens
        finally:
            engine.shutdown()
        ledger = build_hbm_ledger(p, cfg, SLOTS, tp=tp)
        per_chip = (
            ledger.per_chip.get("total") if tp > 1 else ledger.device_total()
        )
        decode_disp = sum(
            disp.get(k, 0) for k in ("decode", "verify", "multistep")
        )
        return {
            "tok_per_s": round(N_REQ * NEW / wall, 1),
            "wall_s": round(wall, 2),
            "dispatch_mix": disp,
            "dispatches_per_token": round(
                decode_disp / max(1, tokens), 4
            ),
            "per_chip_hbm_bytes": int(per_chip),
            "hbm_total_bytes": ledger.device_total(),
            "outputs": outs,
        }

    ladder = {tp: run(tp) for tp in (1, 2, 4)}
    base = [t for o in ladder[1]["outputs"] for t in o]
    agreement = 1.0
    for tp in (2, 4):
        cur = [t for o in ladder[tp]["outputs"] for t in o]
        agreement = min(
            agreement,
            float(np.mean([x == y for x, y in zip(base, cur)])),
        )
        # HARD gate (ISSUE 15): sharding must not add host round-trips —
        # the dispatch ledger (the tpumlops_engine_dispatches_total feed)
        # is identical at every tp.
        assert ladder[tp]["dispatch_mix"] == ladder[1]["dispatch_mix"], (
            tp, ladder[tp]["dispatch_mix"], ladder[1]["dispatch_mix"]
        )
        del ladder[tp]["outputs"]
    del ladder[1]["outputs"]
    # HARD gate: token-for-token across the whole ladder.
    assert agreement == 1.0, agreement

    # --- dp rung (PR 17): batch parallelism over the cache's row axis.
    # Same model, twice the burst: dp=1 keeps 4 rows resident (one
    # chip's worth of cache) and drains 8 requests in two waves — twice
    # the decode ticks; dp=2 holds 8 rows at the SAME 4 rows/chip and
    # serves the burst in one wave.  CPU-mesh tok/s stays emulation-
    # bound, so the environment-independent gates are token agreement
    # 1.0 and tokens-per-dispatch >= 1.8x (each decode dispatch carries
    # ~2x the rows; on a real slice that ratio IS the tok/s ratio at
    # equal per-tick latency, since dp adds no collectives).
    dp_prompts = prompts + [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_REQ)
    ]

    def run_dp(dp: int, slots: int) -> dict:
        mesh_shape = {"dp": dp} if dp > 1 else None
        p = params
        if dp > 1:
            p = partition.shard_llama_params(
                params, partition.build_serving_mesh(mesh_shape)
            )
        engine = GenerationEngine(
            p, cfg, max_slots=slots, dtype=jnp.bfloat16,
            mesh_shape=mesh_shape,
        )
        engine.start(warmup=True)
        try:
            t0 = time.perf_counter()
            futs = [engine.submit(pr, NEW) for pr in dp_prompts]
            outs = [np.asarray(f.result(timeout=600)).tolist() for f in futs]
            wall = time.perf_counter() - t0
            disp = dict(engine.dispatches_total)
            tokens = engine.decode_tokens
        finally:
            engine.shutdown()
        decode_disp = sum(
            disp.get(k, 0) for k in ("decode", "verify", "multistep")
        )
        return {
            "tok_per_s": round(len(dp_prompts) * NEW / wall, 1),
            "wall_s": round(wall, 2),
            "dispatch_mix": disp,
            "tokens_per_dispatch": round(tokens / max(1, decode_disp), 2),
            "outputs": outs,
        }

    dp1 = run_dp(1, SLOTS)
    dp2 = run_dp(2, 2 * SLOTS)
    flat1 = [t for o in dp1["outputs"] for t in o]
    flat2 = [t for o in dp2["outputs"] for t in o]
    dp_agreement = float(np.mean([x == y for x, y in zip(flat1, flat2)]))
    dp_ratio = round(
        dp2["tokens_per_dispatch"] / dp1["tokens_per_dispatch"], 2
    )
    del dp1["outputs"], dp2["outputs"]
    # HARD gates: row-sharding must not change a token, and each decode
    # dispatch must carry ~2x the rows (>= 1.8 leaves slack for ragged
    # final ticks).
    assert dp_agreement == 1.0, dp_agreement
    assert dp_ratio >= 1.8, (dp_ratio, dp1, dp2)
    ladder["dp1"] = dp1
    ladder["dp2"] = dp2
    return {
        "requests": N_REQ,
        "new_tokens_per_request": NEW,
        "slots": SLOTS,
        "tok_per_s_tp1": ladder[1]["tok_per_s"],
        "tok_per_s_tp2": ladder[2]["tok_per_s"],
        "tok_per_s_tp4": ladder[4]["tok_per_s"],
        "dispatches_per_token_tp1": ladder[1]["dispatches_per_token"],
        "dispatches_per_token_tp4": ladder[4]["dispatches_per_token"],
        "per_chip_hbm_bytes_tp1": ladder[1]["per_chip_hbm_bytes"],
        "per_chip_hbm_bytes_tp4": ladder[4]["per_chip_hbm_bytes"],
        "token_agreement": agreement,
        "tok_per_s_dp1": dp1["tok_per_s"],
        "tok_per_s_dp2": dp2["tok_per_s"],
        "dp_tokens_per_dispatch_ratio": dp_ratio,
        "dp_token_agreement": dp_agreement,
        "ladder": {str(k): v for k, v in ladder.items()},
        **_device_cost_keys(params, cfg, SLOTS, ladder[1]["tok_per_s"]),
        "note": (
            "CPU-mesh tok/s measures SPMD emulation, not chips; the "
            "gates are token agreement 1.0 and identical dispatch "
            "ledgers at every tp (no per-tick gather, no extra host "
            "round-trips).  per_chip_hbm_bytes counts sharded weights "
            "exactly (shard shapes) + heads/tp KV rows."
        ),
    }


def bench_long_context() -> dict:
    """Long-context serving: sp ring-attention prefill (spec.tpu.meshShape
    sp + spPrefillThreshold) — the 2k/8k/32k ladder, sp off/on.

    Measured rung (2k, real engine on the forced host mesh): one cold
    2048-token prompt per engine at sp off / {"sp": 1} / sp=2 / sp=4.
    Long prompts route through the ONE-dispatch ring prefill
    ('sp-prefill' in the ledger) instead of the serial chunk ladder;
    {"sp": 1} is the byte-for-byte pin — identical dispatch mix to the
    absent mesh, no sp program.  CPU TTFT measures SPMD emulation, so
    the hard gates are structural: routing fired, the pin held, tokens
    agreed (bf16 near-tie argmaxes reported, f64 bit-parity lives in
    tests/test_long_context.py).

    Analytic rungs (8k/32k, 7B-class GQA geometry, v5e constants): tp
    tops out at num_kv_heads=8, so sp is the only axis that puts more
    chips on ONE prompt — the ladder prices a 16-chip slice as {tp: 8}
    (best without sp, 8 chips on the prompt) vs {sp: 4, tp: 4} (all 16).
    The HBM gate: a one-pass 32k prefill materializes the H x (S/sp)^2
    f32 score block, 137 TB unsharded (cannot exist) vs ~8.6 GB at sp=4
    (fits beside the tp=4 weight shard) — the ring is what makes a
    single-dispatch 32k prefill PHYSICAL; est TTFT >= 2x from the chip
    ratio alone."""
    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import threading

    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama, partition
    from tpumlops.server.generation import GenerationEngine

    n_dev = len(jax.devices())
    if n_dev < 4:
        return {
            "skipped": (
                f"sp ladder needs >= 4 devices, have {n_dev} (run under "
                "--xla_force_host_platform_device_count or a multi-chip "
                "slice)"
            )
        }

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=2176,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    PROMPT, NEW, THRESH = 2048, 8, 512
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()

    def run(mesh_shape) -> dict:
        p = params
        if mesh_shape and partition.mesh_device_count(mesh_shape) > 1:
            p = partition.shard_llama_params(
                params, partition.build_serving_mesh(mesh_shape)
            )
        engine = GenerationEngine(
            p, cfg, max_slots=1, dtype=jnp.bfloat16,
            mesh_shape=mesh_shape, sp_prefill_threshold=THRESH,
        )
        engine.start(warmup=True)
        try:
            ttft: dict = {}
            ev = threading.Event()
            t0 = time.perf_counter()

            def cb(_tok):
                if "s" not in ttft:
                    ttft["s"] = time.perf_counter() - t0
                    ev.set()

            fut = engine.submit(prompt, NEW, on_token=cb)
            out = np.asarray(fut.result(timeout=600)).tolist()
            wall = time.perf_counter() - t0
            assert ev.wait(timeout=600)
            disp = dict(engine.dispatches_total)
        finally:
            engine.shutdown()
        return {
            "ttft_ms": round(ttft["s"] * 1000, 1),
            "wall_s": round(wall, 2),
            "dispatch_mix": disp,
            "output": out,
        }

    off = run(None)
    sp1 = run({"dp": 1, "sp": 1, "tp": 1})
    measured = {"off": off, "sp1": sp1}
    for sp in (2, 4):
        measured[f"sp{sp}"] = run({"sp": sp})
    # HARD gates, environment-independent:
    # {"sp": 1} is byte-for-byte the unsharded engine.
    assert sp1["dispatch_mix"] == off["dispatch_mix"], (
        sp1["dispatch_mix"], off["dispatch_mix"]
    )
    assert "sp-prefill" not in sp1["dispatch_mix"]
    assert sp1["output"] == off["output"]
    # A cold >= threshold prompt routes through ONE ring dispatch at
    # sp > 1 (vs the prompt/chunk-long serial ladder it replaces).
    for sp in (2, 4):
        assert measured[f"sp{sp}"]["dispatch_mix"].get("sp-prefill") == 1, (
            sp, measured[f"sp{sp}"]["dispatch_mix"]
        )
    base_out = off["output"]
    agreement = min(
        float(np.mean([
            x == y for x, y in zip(base_out, measured[f"sp{sp}"]["output"])
        ]))
        for sp in (2, 4)
    )
    for entry in measured.values():
        del entry["output"]

    # --- analytic 8k/32k rungs: 7B GQA geometry on a 16-chip v5e view.
    cfg7b = llama.LlamaConfig(
        vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, intermediate_size=14336, max_seq=32768,
    )
    wbytes = 2.0 * llama.matmul_param_count(cfg7b)  # bf16 tree
    hd = cfg7b.head_dim
    PEAK, HBM = 197e12, 16 * 2**30  # v5e bf16 flops / chip HBM
    EFF = 0.4  # sustained prefill MFU assumption
    CHIPS = 16

    def rung(s: int, sp: int, tp: int) -> dict:
        # One-pass prefill per-chip residency: weight shard + seq-major
        # K/V scratch (NKV over tp, seq over sp) + the H x (S/sp)^2 f32
        # ring score block + the ragged cache row (heads over tp).
        kv_scratch = (
            2.0 * s * cfg7b.num_kv_heads * hd * 2 * cfg7b.num_layers
        )
        scores = cfg7b.num_heads * (s / sp) ** 2 * 4.0
        per_chip = (
            wbytes / tp + kv_scratch / (tp * sp) + scores + kv_scratch / tp
        )
        flops = 2.0 * llama.matmul_param_count(cfg7b) * s
        flops += 4.0 * s * (s / 2.0) * cfg7b.num_heads * hd
        chips_on_prompt = sp * tp
        ttft = flops / (chips_on_prompt * PEAK * EFF)
        return {
            "per_chip_gb": round(per_chip / 1e9, 2),
            "fits_16gib_chip": bool(per_chip <= HBM),
            "score_block_gb": round(scores / 1e9, 2),
            "est_ttft_s": round(ttft, 2),
            "_ttft_raw": ttft,
            "chips_on_prompt": chips_on_prompt,
        }

    analytic = {}
    for s in (8192, 32768):
        # Best without sp: tp caps at num_kv_heads=8 -> 8 of 16 chips.
        analytic[f"{s}_sp1"] = rung(s, 1, 8)
        analytic[f"{s}_sp4"] = rung(s, 4, 4)
    # HARD gates: at 32k the unsharded one-pass score block cannot exist
    # on any chip, the sp=4 rung fits, and putting the idle half of the
    # slice on the prompt is >= 2x analytic TTFT.
    assert not analytic["32768_sp1"]["fits_16gib_chip"]
    assert analytic["32768_sp4"]["fits_16gib_chip"]
    ttft_gain = round(
        analytic["32768_sp1"]["_ttft_raw"]
        / analytic["32768_sp4"]["_ttft_raw"], 2
    )
    assert ttft_gain >= 2.0, ttft_gain
    for entry in analytic.values():
        del entry["_ttft_raw"]

    return {
        "prompt_tokens": PROMPT,
        "new_tokens": NEW,
        "sp_prefill_threshold": THRESH,
        "ttft_ms_sp_off": off["ttft_ms"],
        "ttft_ms_sp2": measured["sp2"]["ttft_ms"],
        "ttft_ms_sp4": measured["sp4"]["ttft_ms"],
        "sp_dispatches": 1,
        "chunk_dispatches_replaced": PROMPT // 512,
        "token_agreement": round(agreement, 3),
        "sp1_pin_identical_ledger": True,
        "fits_32k_sp1": analytic["32768_sp1"]["fits_16gib_chip"],
        "fits_32k_sp4": analytic["32768_sp4"]["fits_16gib_chip"],
        "est_ttft_s_32k_sp1": analytic["32768_sp1"]["est_ttft_s"],
        "est_ttft_s_32k_sp4": analytic["32768_sp4"]["est_ttft_s"],
        "est_ttft_gain_32k": ttft_gain,
        "measured_2k": measured,
        "analytic": analytic,
        **_device_cost_keys(
            params, cfg, 1, (PROMPT + NEW) / measured["sp4"]["wall_s"],
        ),
        "note": (
            "CPU-mesh TTFT measures SPMD emulation; the gates are the "
            "sp routing (one sp-prefill dispatch replaces the serial "
            "chunk ladder), the {'sp': 1} byte-for-byte ledger pin, and "
            "the analytic 32k rung: H x (S/sp)^2 f32 ring score block "
            "137 TB unsharded vs ~8.6 GB at sp=4 on 7B-GQA (nkv=8 caps "
            "tp at 8, so sp is the only route to all 16 chips; est "
            "TTFT assumes 40% sustained MFU, ring-permute overlapped)."
        ),
    }


def bench_packed_prefill() -> dict:
    """Packed multi-admission prefill through the real engine scheduler
    (server/generation.py prefillBatch): N concurrent COLD admissions of
    a 512-token prompt, serial (prefillBatch=1, today's one-at-a-time
    pipeline) vs packed (prefillBatch=N).

    Serial admission runs one batch-1 chunk forward per tick, each
    streaming the full weight tree, and every waiting prompt queues
    behind the in-flight admission — TTFT for the burst's tail is the
    whole burst's prefill, serialized.  Packed admission batches the N
    admissions' next chunks into ONE call per tick, so the burst's
    prefill collapses to prompt_len/chunk calls total and every request's
    TTFT approaches the head-of-line's.  Reported: per-request TTFT
    p50/p99 and the weight-streaming prefill call count, both modes.
    The call-count drop is the environment-independent signal (each call
    is one full HBM weight stream; TTFT here rides this environment's
    ~65 ms/dispatch tunnel, which the call-count drop converts almost
    1:1 into TTFT)."""
    import threading

    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=768,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    N_REQ, PROMPT, C, NEW = 8, 512, 128, 4
    rng = np.random.default_rng(0)
    # Distinct random prompts: COLD admissions, nothing for a prefix
    # cache to reuse (and none is configured) — this scenario isolates
    # the packing win from the caching win.
    prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_REQ)
    ]

    def run(prefill_batch: int) -> dict:
        fills: list[int] = []
        engine = GenerationEngine(
            params, cfg, max_slots=N_REQ, dtype=jnp.bfloat16,
            prefill_chunk=C, prefill_batch=prefill_batch,
            on_prefill_batch=fills.append,
        )
        engine.start(warmup=True)
        try:
            f0 = engine.prefill_forwards
            ttfts: list[float | None] = [None] * N_REQ
            done = [threading.Event() for _ in range(N_REQ)]
            t_sub = [0.0] * N_REQ

            def on_token_for(i):
                def cb(_tok):
                    if ttfts[i] is None:
                        ttfts[i] = time.perf_counter() - t_sub[i]
                        done[i].set()
                return cb

            futs = []
            t_burst = time.perf_counter()
            for i, p in enumerate(prompts):
                t_sub[i] = time.perf_counter()
                futs.append(engine.submit(p, NEW, on_token=on_token_for(i)))
            outs = [
                np.asarray(f.result(timeout=600)).tolist() for f in futs
            ]
            wall = time.perf_counter() - t_burst
            assert all(ev.wait(timeout=600) for ev in done)
            calls = engine.prefill_forwards - f0
        finally:
            engine.shutdown()
        p = _percentiles([t * 1000 for t in ttfts])
        return {
            "ttft_p50_ms": round(p[50], 1),
            "ttft_p99_ms": round(p[99], 1),
            "wall_s": wall,
            "chunk_calls": calls,
            "batch_fill_mean": (
                round(sum(fills) / len(fills), 2) if fills else None
            ),
            "outputs": outs,
        }

    serial = run(1)
    packed = run(N_REQ)
    # bf16 near-tie argmaxes can differ between the batch-1 and packed
    # programs; report agreement rather than assert it (the f64
    # bit-identity proof lives in tests/test_packed_prefill.py).
    a = [t for o in serial["outputs"] for t in o]
    b = [t for o in packed["outputs"] for t in o]
    agreement = round(float(np.mean([x == y for x, y in zip(a, b)])), 3)
    del serial["outputs"], packed["outputs"]
    # The acceptance bar: >= 2x fewer weight-streaming prefill calls and
    # a TTFT p50 win.  HARD assertions — a packing regression must fail
    # the bench, not quietly ship a smaller ratio.
    assert packed["chunk_calls"] * 2 <= serial["chunk_calls"], (
        packed["chunk_calls"], serial["chunk_calls"],
    )
    assert packed["ttft_p50_ms"] < serial["ttft_p50_ms"], (
        packed["ttft_p50_ms"], serial["ttft_p50_ms"],
    )
    return {
        "requests": N_REQ,
        "prompt_tokens": PROMPT,
        "prefill_chunk": C,
        "prefill_batch": N_REQ,
        "serial_ttft_p50_ms": serial["ttft_p50_ms"],
        "serial_ttft_p99_ms": serial["ttft_p99_ms"],
        "serial_chunk_calls": serial["chunk_calls"],
        "packed_ttft_p50_ms": packed["ttft_p50_ms"],
        "packed_ttft_p99_ms": packed["ttft_p99_ms"],
        "packed_chunk_calls": packed["chunk_calls"],
        "ttft_p50_speedup": round(
            serial["ttft_p50_ms"] / packed["ttft_p50_ms"], 2
        ),
        "chunk_call_reduction": round(
            serial["chunk_calls"] / max(1, packed["chunk_calls"]), 2
        ),
        "batch_fill_mean": packed["batch_fill_mean"],
        "token_agreement": agreement,
        **_device_cost_keys(
            params, cfg, N_REQ,
            N_REQ * (PROMPT + NEW) / packed["wall_s"],
        ),
        "note": (
            "engine-loop TTFT rides the dev tunnel's ~65 ms/dispatch; "
            "the weight-streaming prefill call count (serial "
            "N*prompt/chunk vs packed prompt/chunk) is the "
            "environment-independent number"
        ),
    }


def bench_observability() -> dict:
    """Flight-recorder overhead (server/flight_recorder.py): the same
    continuous-batching serving run with the recorder absent (the
    default — no recorder object exists, the engine loop is untouched)
    vs recording every tick and request lifecycle event into the
    bounded rings.

    The recorder's per-tick cost is one dict build + deque append under
    a lock, so the acceptance bar is tok/s overhead <= 2% with the ring
    on; decode-step wall (device dispatch, recorder work excluded by
    construction) should be unchanged.  Outputs must agree token-for-
    token: observation must not perturb scheduling."""
    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.flight_recorder import FlightRecorder, RequestTrace
    from tpumlops.server.generation import GenerationEngine

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    N_REQ, PROMPT, NEW, SLOTS = 8, 32, 64, 4
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_REQ)
    ]

    def run(recorder):
        step_walls: list[float] = []
        engine = GenerationEngine(
            params, cfg, max_slots=SLOTS, dtype=jnp.bfloat16,
            recorder=recorder,
            on_step=lambda a, s, q, adm: step_walls.append(s) if a else None,
        )
        engine.start(warmup=True)
        try:
            t0 = time.perf_counter()
            futs = [
                engine.submit(
                    p, NEW,
                    request_id=f"bench-{i}" if recorder else "",
                    trace=RequestTrace(f"bench-{i}") if recorder else None,
                )
                for i, p in enumerate(prompts)
            ]
            outs = [np.asarray(f.result(timeout=600)).tolist() for f in futs]
            wall = time.perf_counter() - t0
        finally:
            engine.shutdown()
        return {
            "wall_s": wall,
            "tok_per_s": N_REQ * NEW / wall,
            "decode_step_ms": (
                1e3 * sum(step_walls) / max(1, len(step_walls))
            ),
            "outputs": outs,
        }

    off = run(None)
    recorder = FlightRecorder(4096)
    on = run(recorder)
    snap = recorder.snapshot()
    trace_events = len(recorder.chrome_trace()["traceEvents"])
    agree = float(
        np.mean(
            [
                x == y
                for a, b in zip(off["outputs"], on["outputs"])
                for x, y in zip(a, b)
            ]
        )
    )
    overhead_pct = 100.0 * (1.0 - on["tok_per_s"] / off["tok_per_s"])
    return {
        "requests": N_REQ,
        "new_tokens_per_request": NEW,
        "slots": SLOTS,
        "trace_ring": recorder.capacity,
        "tok_per_s_off": round(off["tok_per_s"], 1),
        "tok_per_s_on": round(on["tok_per_s"], 1),
        # Negative = the recorder run was faster (run-to-run noise on a
        # shared host; the contract is "within noise of 0, <= 2%").
        "overhead_pct": round(overhead_pct, 2),
        "decode_step_ms_off": round(off["decode_step_ms"], 3),
        "decode_step_ms_on": round(on["decode_step_ms"], 3),
        "ring_ticks": snap["ticks_recorded"],
        "ring_events": snap["events_recorded"],
        "ring_requests": snap["traces_recorded"],
        "trace_events": trace_events,
        "token_agreement": round(agree, 3),
        **_device_cost_keys(params, cfg, SLOTS, on["tok_per_s"]),
        "note": (
            "recorder work is host-side ring appends between device "
            "dispatches; decode_step_ms (pure dispatch wall) isolates "
            "the device from the journaling cost"
        ),
    }


def bench_anomaly_observability() -> dict:
    """Fleet anomaly observatory (server/timeseries.py +
    operator/anomaly.py): two claims in one scenario.

    (1) Ring overhead: the same continuous-batching serving run with the
    per-second timeseries ring absent (the default — no ring object, the
    engine callbacks are None) vs fanned onto every metric hook.  The
    ring's per-event cost is a lock + capped list append, so the bar is
    the flight recorder's: tok/s within noise, token-for-token output
    agreement (observation must not perturb scheduling).

    (2) Detection: a 4-replica fleet of REAL rings is fed from the ON
    run's measured inter-token latencies — three healthy replicas carry
    the measured stream with small deterministic skews (x1.0 / x1.03 /
    x0.97: realistic inter-host spread), the fourth carries it slowed
    6x (the injected straggler) — spread over per-second buckets with a
    fake clock.  ``detect()`` at default ``AnomalySpec`` thresholds must
    flag the slow replica and ONLY the slow replica: the acceptance bar
    is straggler_flagged = 1 with false_positives = 0.  The signal is
    real serving jitter; only the slowdown is injected — the fully-live
    version (ChaosProxy delay, operator polling HTTP rings) runs in
    tests/test_e2e_localplane.py."""
    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.operator import anomaly
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.timeseries import TimeseriesRing
    from tpumlops.utils.config import AnomalySpec

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    N_REQ, PROMPT, NEW, SLOTS = 8, 32, 64, 4
    RING = 64
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_REQ)
    ]
    itl_stream: "list[float]" = []

    def run(ring):
        def on_itl(seconds):
            itl_stream.append(float(seconds))
            ring.observe_itl(seconds)

        engine = GenerationEngine(
            params, cfg, max_slots=SLOTS, dtype=jnp.bfloat16,
            on_step=ring.observe_decode_step if ring else None,
            on_itl=on_itl if ring else None,
            on_tick=ring.observe_tick if ring else None,
            on_shed=ring.inc_shed if ring else None,
        )
        engine.start(warmup=True)
        try:
            t0 = time.perf_counter()
            futs = [engine.submit(p, NEW) for p in prompts]
            outs = [np.asarray(f.result(timeout=600)).tolist() for f in futs]
            wall = time.perf_counter() - t0
        finally:
            engine.shutdown()
        return {"tok_per_s": N_REQ * NEW / wall, "outputs": outs}

    off = run(None)
    ring = TimeseriesRing(RING)
    on = run(ring)
    ring_samples = len(ring.snapshot()["samples"])
    agree = float(
        np.mean(
            [
                x == y
                for a, b in zip(off["outputs"], on["outputs"])
                for x, y in zip(a, b)
            ]
        )
    )
    overhead_pct = 100.0 * (1.0 - on["tok_per_s"] / off["tok_per_s"])

    # -- detection half: replay the measured ITL stream into a fleet ----
    SKEWS = {"r0": 1.0, "r1": 1.03, "r2": 0.97, "r-slow": 6.0}
    QUEUE = {"r0": 2, "r1": 3, "r2": 2, "r-slow": 9}
    SECONDS = 12
    fake = {"t": 1_000_000.0}
    rings = {
        name: TimeseriesRing(RING, clock=lambda: fake["t"]) for name in SKEWS
    }
    itl = itl_stream or [0.005] * SECONDS  # engine always produces ITL
    per_sec = max(1, len(itl) // SECONDS)
    for sec in range(SECONDS):
        fake["t"] = 1_000_000.0 + sec + 0.5
        chunk = itl[sec * per_sec : (sec + 1) * per_sec] or itl[-per_sec:]
        for name, skew in SKEWS.items():
            for s in chunk:
                rings[name].observe_itl(s * skew)
            rings[name].observe_decode_step(
                SLOTS, 0.0, queue_depth=QUEUE[name]
            )
    fake["t"] += 2.0  # close the last bucket
    spec = AnomalySpec(enabled=True)
    windows = {
        name: anomaly.replica_series(r.snapshot(), spec.window_s)
        for name, r in rings.items()
    }
    verdicts = anomaly.detect(windows, spec)
    stragglers = sorted({v.replica for v in verdicts if v.kind == "straggler"})
    false_positives = sum(1 for v in verdicts if v.replica != "r-slow")
    slow_verdicts = [v for v in verdicts if v.replica == "r-slow"]
    return {
        "requests": N_REQ,
        "new_tokens_per_request": NEW,
        "slots": SLOTS,
        "timeseries_ring": RING,
        "tok_per_s_off": round(off["tok_per_s"], 1),
        "tok_per_s_on": round(on["tok_per_s"], 1),
        # Negative = the ring run was faster (run-to-run noise on a
        # shared host; the contract is "within noise of 0").
        "overhead_pct": round(overhead_pct, 2),
        "ring_samples": ring_samples,
        "itl_samples": len(itl_stream),
        "replicas": len(SKEWS),
        "injected_slowdown_x": SKEWS["r-slow"],
        "mad_threshold": spec.mad_threshold,
        "straggler_flagged": int(stragglers == ["r-slow"]),
        "straggler_series": sorted(v.series for v in slow_verdicts),
        "max_z": round(
            max((abs(v.z) for v in slow_verdicts if v.z is not None), default=0.0), 1
        ),
        "false_positives": false_positives,
        "token_agreement": round(agree, 3),
        **_device_cost_keys(params, cfg, SLOTS, on["tok_per_s"]),
        "note": (
            "detection replays the ON run's measured ITL stream into 4 "
            "per-second rings (3 healthy skews + one 6x slow) and runs "
            "detect() at default thresholds; the live-HTTP version is "
            "the e2e test"
        ),
    }


def bench_device_telemetry() -> dict:
    """Device telemetry layer (server/device_telemetry.py): the same
    continuous-batching run with telemetry absent (the default — no
    ledger, no cost model, no wrapped jits) vs fully on.

    Three claims gated here: (1) tok/s with telemetry on is within noise
    of off — the per-tick cost is a handful of float multiplies plus the
    thread-local set/unset around each dispatch; (2) the analytic HBM
    ledger agrees with ``device.memory_stats()`` within 10% where the
    platform reports it (the CPU dev environment reports None — the
    check is live on TPU); (3) per-tick MFU / bandwidth utilization land
    in (0, 1] for the decode and prefill tick kinds.  Outputs agree
    token-for-token: observation must not perturb scheduling."""
    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.device_telemetry import DeviceTelemetry
    from tpumlops.server.generation import GenerationEngine

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    N_REQ, PROMPT, NEW, SLOTS = 8, 32, 64, 4
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_REQ)
    ]

    def run(telemetry):
        engine = GenerationEngine(
            params, cfg, max_slots=SLOTS, dtype=jnp.bfloat16,
            telemetry=telemetry,
        )
        engine.start(warmup=True)
        try:
            t0 = time.perf_counter()
            futs = [engine.submit(p, NEW) for p in prompts]
            outs = [np.asarray(f.result(timeout=600)).tolist() for f in futs]
            wall = time.perf_counter() - t0
        finally:
            engine.shutdown()
        return {
            "wall_s": wall,
            "tok_per_s": N_REQ * NEW / wall,
            "outputs": outs,
        }

    off = run(None)
    telemetry = DeviceTelemetry()
    on = run(telemetry)
    snap = telemetry.snapshot()
    hbm = snap["hbm"]
    util = snap["utilization"]
    agree = float(
        np.mean(
            [
                x == y
                for a, b in zip(off["outputs"], on["outputs"])
                for x, y in zip(a, b)
            ]
        )
    )
    # Utilization contract: decode and prefill tick kinds produced
    # ratios in (0, 1].  HARD assertions — a cost-model regression
    # (negative bytes, >1 MFU) must fail the bench.
    for kind in ("decode", "prefill"):
        assert kind in util, util
        assert 0.0 < util[kind]["mfu"] <= 1.0, (kind, util[kind])
        assert 0.0 < util[kind]["hbm_bw_util"] <= 1.0, (kind, util[kind])
    # Ledger-vs-measured: live only where memory_stats() reports.
    if hbm.get("ledger_vs_measured_pct") is not None:
        assert abs(hbm["ledger_vs_measured_pct"]) <= 10.0, hbm
    overhead_pct = 100.0 * (1.0 - on["tok_per_s"] / off["tok_per_s"])
    return {
        "requests": N_REQ,
        "new_tokens_per_request": NEW,
        "slots": SLOTS,
        "tok_per_s_off": round(off["tok_per_s"], 1),
        "tok_per_s_on": round(on["tok_per_s"], 1),
        # Negative = the telemetry run was faster (run-to-run noise on a
        # shared host; the contract is "within noise of 0").
        "overhead_pct": round(overhead_pct, 2),
        "hbm_ledger_total_bytes": hbm["device_total_bytes"],
        "ledger_vs_measured_pct": hbm.get("ledger_vs_measured_pct"),
        "kv_bytes_per_row": hbm["kv_bytes_per_row"],
        "max_cache_rows": hbm["max_cache_rows"],
        "decode_mfu": util["decode"]["mfu"],
        "decode_hbm_bw_util": util["decode"]["hbm_bw_util"],
        "prefill_mfu": util["prefill"]["mfu"],
        "warmup_compiles": snap["compile"]["warmup"].get("compiles", 0),
        "warmup_compile_s": round(
            snap["compile"]["warmup"].get("seconds", 0.0), 2
        ),
        "token_agreement": round(agree, 3),
        **_device_cost_keys(params, cfg, SLOTS, on["tok_per_s"]),
        "note": (
            "telemetry work is host-side arithmetic between device "
            "dispatches; ledger_vs_measured is None off-TPU "
            "(memory_stats unavailable) and the 10%-agreement gate "
            "arms itself where the platform reports"
        ),
    }


def bench_cold_start() -> dict:
    """Scale-to-zero cold-start ladder (server/snapshot.py): the same
    model served three ways — cold HF-checkpoint load (transformers →
    torch → JAX convert → device quantize), cold native-artifact load
    (streamed npz + on-arrival int8 quantize), and snapshot restore
    (pre-baked post-quantize device tree, zero transform work).

    The 7B measurement that motivates this (BENCH_7B_FULL.json): 102 s
    to first-servable, 92 s of it reading 12.55 GiB of bf16 to produce
    6.4 GiB of int8.  The snapshot stores the int8 result, so the
    restore reads ~2x fewer bytes and skips quantize entirely; here the
    ladder is measured at a small shape with the SAME code paths, and
    the output-parity gate proves the restored tree decodes
    token-for-token what the cold-loaded tree decodes."""
    jax = _setup_jax()
    import gc
    import tempfile

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.loader import (
        load_predictor,
        release_predictor,
        save_native_model,
    )

    dims = dict(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    cfg = llama.LlamaConfig(**dims)
    tmp = tempfile.mkdtemp(prefix="tpumlops-coldstart-")
    native = f"{tmp}/native"
    snapdir = f"{tmp}/snaps"
    save_native_model(
        native, "llama-generate",
        llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16),
        config=dims,
    )

    # -- rung 1: the cold HF path (what a bare checkpoint URI costs) ----
    hf_cold_s = None
    hf_error = None
    try:
        from transformers import LlamaConfig as HFLlamaConfig
        from transformers import LlamaForCausalLM

        hf_dir = f"{tmp}/hf"
        hfm = LlamaForCausalLM(
            HFLlamaConfig(
                vocab_size=dims["vocab_size"],
                hidden_size=dims["hidden_size"],
                num_hidden_layers=dims["num_layers"],
                num_attention_heads=dims["num_heads"],
                num_key_value_heads=dims["num_kv_heads"],
                intermediate_size=dims["intermediate_size"],
                max_position_embeddings=dims["max_seq"],
            )
        )
        hfm.save_pretrained(hf_dir)
        del hfm
        gc.collect()
        t0 = time.perf_counter()
        pred_hf = load_predictor(hf_dir, quantize="int8")
        hf_cold_s = time.perf_counter() - t0
        release_predictor(pred_hf)
        del pred_hf
    except Exception as e:  # no transformers/torch in this env: rung absent
        hf_error = f"{type(e).__name__}: {e}"[:120]

    # -- rung 2: cold native load (streamed npz, on-arrival quantize),
    #    PURE — no snapshot_dir, so the rung measures only the load
    #    path it names; the bake is timed separately below -------------
    cold_stats: dict = {}
    t0 = time.perf_counter()
    pred_cold = load_predictor(
        native, quantize="int8", load_stats=cold_stats,
    )
    native_cold_s = time.perf_counter() - t0

    # The one-time bake (write-once after a cold load in production):
    # its own number, charged to neither the cold rung nor the restore.
    from tpumlops.server import snapshot as _snap

    t0 = time.perf_counter()
    _snap.write_snapshot(
        snapdir,
        pred_cold.causal_lm["params"],
        identity=_snap.snapshot_identity(native, "int8", None),
        flavor="llama-generate",
        config=dims,
    )
    bake_s = time.perf_counter() - t0

    prompt = list(
        np.random.default_rng(0).integers(1, dims["vocab_size"], size=24)
    )

    def greedy_tokens(pred) -> list:
        engine = GenerationEngine(
            pred.causal_lm["params"], pred.causal_lm["cfg"],
            max_slots=2, dtype=jnp.bfloat16,
        )
        engine.start(warmup=False)
        try:
            return [int(t) for t in engine.submit(prompt, 16).result(300)]
        finally:
            engine.shutdown()

    tokens_cold = greedy_tokens(pred_cold)

    # -- rung 3: snapshot restore (the scale-to-zero wake path).  The
    #    old tree is released FIRST — the warm-reload OOM fix under test
    #    — then the clock times ONLY the restore itself: each rung
    #    measures its load path, and neither cold rung paid a release.
    release_predictor(pred_cold)
    del pred_cold
    snap_stats: dict = {}
    t0 = time.perf_counter()
    pred_snap = load_predictor(
        native, quantize="int8", load_stats=snap_stats,
        snapshot_dir=snapdir,
    )
    snapshot_restore_s = time.perf_counter() - t0
    assert snap_stats.get("restore_s") is not None, (
        f"snapshot restore did not engage: {snap_stats}"
    )
    tokens_snap = greedy_tokens(pred_snap)
    agreement = 1.0 if tokens_snap == tokens_cold else 0.0
    assert agreement == 1.0, (tokens_cold, tokens_snap)

    params = pred_snap.causal_lm["params"]
    cold_read = cold_stats.get("read_gib") or 0.0
    snap_read = snap_stats.get("read_gib") or 0.0
    out = {
        "hf_cold_s": round(hf_cold_s, 2) if hf_cold_s is not None else None,
        "native_cold_s": round(native_cold_s, 2),
        "snapshot_bake_s": round(bake_s, 3),
        "snapshot_restore_s": round(snapshot_restore_s, 3),
        "restore_speedup_vs_native": round(
            native_cold_s / snapshot_restore_s, 1
        ),
        "restore_speedup_vs_hf": (
            round(hf_cold_s / snapshot_restore_s, 1)
            if hf_cold_s is not None
            else None
        ),
        "cold_read_gib": cold_read,
        "snapshot_read_gib": snap_read,
        "bytes_reduction": (
            round(cold_read / snap_read, 2) if snap_read else None
        ),
        "cold_breakdown_s": cold_stats,
        "restore_breakdown_s": snap_stats,
        "token_agreement": agreement,
        **_device_cost_keys(params, cfg, 2, 16 / max(snapshot_restore_s, 1e-9)),
        "note": (
            "restore streams the post-quantize device tree verbatim — "
            "no quantize_s stage, ~2x fewer bytes than the bf16 "
            "artifact; at 7B the same ratio applies to a 92 s disk "
            "stage"
        ),
    }
    if hf_error is not None:
        out["hf_error"] = hf_error
    # Acceptance gate: snapshot restore >= 3x faster than the cold HF
    # load of the same model (when the HF rung could run here).
    if hf_cold_s is not None:
        assert hf_cold_s / snapshot_restore_s >= 3.0, out
    release_predictor(pred_snap)
    return out


def bench_admission_control() -> dict:
    """Admission control under 2x-capacity overload (server/generation.py
    admission_queue_budget): the same burst with an unbounded queue vs a
    bounded one that sheds with 429-mapped :class:`EngineOverloaded`.

    Unbounded, every request is accepted and the tail of the burst
    queues behind the whole head — admitted p99 TTFT is the burst's
    entire serial backlog.  Bounded, requests past the estimated-token
    budget shed at the door (clients retry on another replica; here they
    are simply counted), so every ADMITTED request sees a short, bounded
    queue and the p99 TTFT of what the replica actually serves drops.
    That conversion — overload into cheap sheds instead of an unbounded
    tail — is what makes horizontal scale-out safe: the autoscaler reads
    the shed counter + queue depth and boots replicas while no admitted
    user's latency explodes."""
    import threading

    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.server.generation import EngineOverloaded, GenerationEngine
    from tpumlops.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    SLOTS, PROMPT, NEW = 4, 32, 48
    # 2x capacity: twice as many concurrent requests as decode slots.
    N_REQ = 2 * SLOTS * 2
    # Budget sized to roughly one extra slot-generation of queued work:
    # the engine runs SLOTS concurrently; about SLOTS more may queue.
    BUDGET = SLOTS * (PROMPT + NEW)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_REQ)
    ]

    def run(budget: int) -> dict:
        engine = GenerationEngine(
            params, cfg, max_slots=SLOTS, dtype=jnp.bfloat16,
            admission_queue_budget=budget,
        )
        engine.start(warmup=True)
        try:
            ttfts: list[float | None] = [None] * N_REQ
            t_sub = [0.0] * N_REQ
            done = [threading.Event() for _ in range(N_REQ)]

            def on_token_for(i):
                def cb(_tok):
                    if ttfts[i] is None:
                        ttfts[i] = time.perf_counter() - t_sub[i]
                        done[i].set()
                return cb

            futs, shed = [], 0
            t_burst = time.perf_counter()
            for i, p in enumerate(prompts):
                t_sub[i] = time.perf_counter()
                try:
                    futs.append(
                        (i, engine.submit(p, NEW, on_token=on_token_for(i)))
                    )
                except EngineOverloaded:
                    shed += 1
                    done[i].set()
            outs = [f.result(timeout=600) for _, f in futs]
            wall = time.perf_counter() - t_burst
            assert all(ev.wait(timeout=600) for ev in done)
            admitted_ttft = [
                ttfts[i] * 1000 for i, _ in futs if ttfts[i] is not None
            ]
        finally:
            engine.shutdown()
        p = _percentiles(admitted_ttft)
        return {
            "admitted": len(futs),
            "shed": shed,
            "completed_ok": len(outs),
            "ttft_p50_ms": round(p[50], 1),
            "ttft_p99_ms": round(p[99], 1),
            "wall_s": wall,
        }

    unbounded = run(0)
    bounded = run(BUDGET)
    # The acceptance bar: overload actually sheds, nothing admitted is
    # lost, and the admitted tail tightens.  HARD assertions — a shed
    # path that silently stops engaging must fail the bench.
    assert unbounded["shed"] == 0 and unbounded["admitted"] == N_REQ
    assert bounded["shed"] > 0, bounded
    assert bounded["admitted"] + bounded["shed"] == N_REQ
    assert bounded["completed_ok"] == bounded["admitted"]
    assert bounded["ttft_p99_ms"] <= unbounded["ttft_p99_ms"], (
        bounded["ttft_p99_ms"], unbounded["ttft_p99_ms"],
    )
    return {
        "requests": N_REQ,
        "slots": SLOTS,
        "budget_tokens": BUDGET,
        "shed": bounded["shed"],
        "shed_rate": round(bounded["shed"] / N_REQ, 3),
        "completed_ok": bounded["completed_ok"],
        "admitted_ttft_p99_ms_unbounded": unbounded["ttft_p99_ms"],
        "admitted_ttft_p99_ms_bounded": bounded["ttft_p99_ms"],
        "admitted_ttft_p50_ms_unbounded": unbounded["ttft_p50_ms"],
        "admitted_ttft_p50_ms_bounded": bounded["ttft_p50_ms"],
        "ttft_p99_improvement": round(
            unbounded["ttft_p99_ms"] / max(1e-9, bounded["ttft_p99_ms"]), 2
        ),
        **_device_cost_keys(
            params, cfg, SLOTS,
            bounded["completed_ok"] * NEW / bounded["wall_s"],
        ),
        "note": (
            "2x-capacity burst; bounded mode converts the overload tail "
            "into counted 429 sheds (clients retry on another replica) "
            "so admitted-request TTFT stays bounded while the "
            "autoscaler boots capacity"
        ),
    }


def bench_llama_decode() -> dict:
    """Continuous-batching decode at a 1.35B shape: int8 weights + int8 KV
    cache + windowed attention, slots laddered 8..64 (VERDICT r2 #2).

    Decode is HBM-bound — every step re-reads all weights, so tok/s rises
    with slot count until the KV-cache traffic (which grows with slots)
    dominates; the ladder locates that knee and ``bw_util`` reports each
    point against the v5e ~819 GB/s roofline.  int8kv numerics are gated
    by a teacher-forced logit-parity fixture vs the bf16 cache (VERDICT
    r2 #4).
    """
    jax = _setup_jax()
    # HBM hygiene: by this point BERT/ResNet weights and their
    # executable-pinned buffers are still resident on the one chip, and
    # the ladder's p50s measured 40-90% above the same points on an
    # empty chip (r5: 5.43 ms recorded vs 2.8-3.8 in the clean-process
    # A/B).  Same courtesy the 7B subprocess gets.
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.models.quantization import quantize_llama

    cfg = llama.LlamaConfig(
        vocab_size=32000,
        hidden_size=2048,
        num_layers=24,
        num_heads=16,
        num_kv_heads=16,
        intermediate_size=5632,
        # 768, not 1024: headroom for the 64-slot ladder point (the carry
        # is donated and aliases in-place, but compile-time temporaries
        # still spike); the attended window (512) is unchanged, so tok/s
        # is unaffected.
        max_seq=768,
    )
    params = quantize_llama(llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16))

    # --- int8kv greedy-parity fixture (small capacity bounds compile) ---
    cfg_p = llama.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers, num_heads=cfg.num_heads,
        num_kv_heads=cfg.num_kv_heads, intermediate_size=cfg.intermediate_size,
        max_seq=64,
    )
    # Teacher-forced: BOTH cache types see the identical token stream, so
    # the per-step logit error isolates KV rounding alone.  (Greedy
    # continuations diverge chaotically under random-init weights — the
    # logit gap between top tokens is ~bf16 noise — so token-match is not
    # a falsifiable test here; per-step logit error is.)
    fixture = np.asarray(
        [[1, 42, 7, 99, 1234, 567, 31999, 2, 13, 17] + list(range(100, 116))],
        np.int32,
    )

    def forced_logits(kv_quant: bool):
        if kv_quant:
            cache = llama.QuantRaggedKVCache.create(cfg_p, 1)
        else:
            cache = llama.RaggedKVCache.create(cfg_p, 1, jnp.bfloat16)

        @jax.jit
        def step(params, toks, cache):
            logits, cache = llama.decode_ragged(params, toks, cache, cfg_p)
            return logits[:, -1].astype(jnp.float32), cache

        outs = []
        for i in range(fixture.shape[1]):
            logits, cache = step(params, fixture[:, i : i + 1], cache)
            outs.append(np.asarray(logits))
        return np.concatenate(outs, axis=0)  # [T, vocab]

    logits_bf16 = forced_logits(kv_quant=False)
    logits_q8 = forced_logits(kv_quant=True)
    rel_err = float(
        np.max(np.abs(logits_q8 - logits_bf16)) / (np.max(np.abs(logits_bf16)) + 1e-9)
    )
    argmax_agree = float(np.mean(logits_q8.argmax(-1) == logits_bf16.argmax(-1)))
    kv_parity = {
        "teacher_forced_steps": int(fixture.shape[1]),
        "max_rel_logit_err": round(rel_err, 4),
        "argmax_agreement": round(argmax_agree, 3),
    }
    assert rel_err < 0.05, (
        f"int8 KV rel logit error {rel_err:.4f} vs bf16 KV exceeds 5%"
    )

    # --- slot ladder: device-loop tok/s at position ~256, window 512 ----
    WINDOW, POS = 512, 256
    ladder, best = _run_slot_ladder(
        jax, params, cfg, (8, 16, 32, 64), window=WINDOW, position=POS,
        n1=6, n2=30,
    )
    if best is None:
        return {"error": "all ladder points failed", "slot_ladder": ladder,
                "int8kv_parity_vs_bf16kv": kv_parity}

    return {
        "device_tok_per_s": best[1]["tok_per_s"],
        "ms_per_step": best[1]["ms_per_step"],
        "slots": best[0],
        "slot_ladder": ladder,
        "bw_util_at_best": best[1]["bw_util"],
        "params_b": 1.35,
        "numerics": "int8 weights + int8 kv + windowed decode (window=512)",
        "int8kv_parity_vs_bf16kv": kv_parity,
        "bw_util_note": (
            "at num_heads == num_kv_heads (G=1) decode attention is a "
            "[1,W]x[W,D] matvec per (slot, head); the MXU tiling floor "
            "(~4 passes x 128 cycles regardless of the 1-row M) costs "
            "~17 us/slot/layer — ~7x the window's actual HBM traffic — "
            "so bw_util falls as slots grow even at the matvec floor. "
            "Four implementations measured on chip (scripts/"
            "ab_attention.py): XLA batched-dot 14.8 ms/step @32 slots "
            "= the floor; pallas MXU per-slot 36.4, slot-batched 34.2, "
            "VPU mul+reduce 34.1.  XLA is the serving default."
        ),
        "note": (
            "engine-loop tok/s is not reported from this dev environment: "
            "the per-tick host read rides a ~65 ms device tunnel "
            "(BENCH_r02 measured 70.7 tok/s engine vs 787.6 device for "
            "identical compute) — the device loop is the chip number."
        ),
    }


def bench_llama_7b_decode() -> dict:
    """BASELINE config[4] in a KILLABLE subprocess: the remote-compile
    tunnel in this environment sometimes wedges indefinitely on very
    large programs (zero CPU, blocked socket) — a timeout + fresh process
    contains that, and per-point progress lines let the parent salvage a
    partial ladder."""
    import subprocess

    # The subprocess shares the ONE physical chip with this parent, and
    # by this point the parent has run BERT/ResNet/1.35B/serve-path in
    # process — several GiB of weights, caches, and executable-pinned
    # buffers still resident.  7B needs ~9 GiB of the 16; round 4's
    # first clean run OOMed every ladder point exactly this way (the
    # identical points pass on an empty chip).  Drop everything the
    # parent can legally free before handing the chip over.
    import gc

    try:
        import jax

        gc.collect()
        jax.clear_caches()
        gc.collect()
    except Exception:
        pass

    # 2400, not 900: a fresh-compile-cache run needs ~6 scan compiles
    # (3 slot counts x 2 lengths) at ~2-4 min each through the remote
    # tunnel, plus the load.  The partial-salvage path below still
    # captures every finished point if the ceiling hits.
    timeout_s = float(os.environ.get("BENCH_7B_TIMEOUT_S", "2400"))
    code = "import bench; bench._llama_7b_inner()"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        stdout = proc.stdout or ""
    except subprocess.TimeoutExpired as e:
        stdout = (
            e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        )
        partial: dict = {}
        loadinfo: dict = {}
        for line in stdout.splitlines():
            try:
                if line.startswith("7BPOINT "):
                    partial.update(json.loads(line[len("7BPOINT "):]))
                elif line.startswith("7BLOAD "):
                    loadinfo = json.loads(line[len("7BLOAD "):])
            except json.JSONDecodeError:
                pass
        return {
            "error": f"timeout after {timeout_s:.0f}s "
                     "(partial ladder salvaged from progress lines)",
            "slot_ladder": partial or None,
            **loadinfo,
        }
    for line in reversed(stdout.splitlines()):
        if line.startswith("7BRESULT "):
            return json.loads(line[len("7BRESULT "):])
    return {
        "error": "subprocess produced no result",
        "rc": proc.returncode,
        "tail": (proc.stderr or "")[-300:],
    }


def _llama_7b_inner() -> None:
    """Subprocess body for :func:`bench_llama_7b_decode`: Llama-2-7B
    geometry, int8 weights streamed from the 13 GiB checkpoint
    (docs/SCALE.md), int8 KV, decode on the single v5e chip."""
    import tempfile

    jax = _setup_jax()
    # Fresh compile cache: a cache entry written by a previous WEDGED
    # compile attempt can load as an executable that returns instantly
    # with garbage (observed round 3) — never reuse one for the number
    # of record.
    jax.config.update(
        "jax_compilation_cache_dir", tempfile.mkdtemp(prefix="jaxcache7b")
    )
    import os.path

    def emit(result: dict) -> None:
        print("7BRESULT " + json.dumps(result), flush=True)

    ckpt = os.environ.get("BENCH_7B_CKPT", "/root/ckpt7b")
    if not os.path.isdir(ckpt):
        emit({"skipped": f"7B checkpoint not found at {ckpt} "
                         "(generate with scripts/gen_7b_checkpoint.py)"})
        return

    # BENCH_7B_SLOTS: comma list override (e.g. "32" to probe one point
    # in a fresh process, where no prior ladder executables crowd HBM).
    # Parsed BEFORE the multi-minute checkpoint load so a malformed
    # value fails in milliseconds, not after 13 GiB of streaming.
    try:
        slot_counts = tuple(
            int(s)
            for s in os.environ.get("BENCH_7B_SLOTS", "8,16,32").split(",")
            if s.strip()
        ) or (8, 16, 32)
    except ValueError:
        emit({"error": "unparseable BENCH_7B_SLOTS="
                       f"{os.environ.get('BENCH_7B_SLOTS')!r}"})
        return

    from tpumlops.server.loader import load_predictor

    t_begin = time.perf_counter()
    load_stats: dict = {}
    t0 = time.perf_counter()
    pred = load_predictor(ckpt, quantize="int8", load_stats=load_stats)
    load_s = time.perf_counter() - t0
    # Progress line the parent can salvage on timeout: the load numbers
    # must survive a ceiling hit during the (later, longer) ladder.
    print("7BLOAD " + json.dumps(
        {"load_s": round(load_s, 1), "load_breakdown_s": load_stats}
    ), flush=True)
    params = pred.causal_lm["params"]
    cfg = pred.causal_lm["cfg"]
    # Bound the KV capacity so weights (6.4 GiB int8) + cache fit the
    # 16 GiB chip across the ladder: 768 positions x 32 slots of int8
    # k+v at 7B geometry is ~6.6 GiB.
    import dataclasses

    cfg = dataclasses.replace(cfg, max_seq=768)

    from tpumlops.models.quantization import quantized_bytes

    WINDOW, POS = 512, 256
    # Round-3's slot_ladder["32"] compile failure was the cache living
    # TWICE (input + loop copy, 2 x ~6.8 GiB + 6.4 GiB weights > 16 GiB);
    # the decode loop now DONATES the carry (like the production engine's
    # donate_argnums), so one copy lives and 32 slots fits.  Any residual
    # failure is recorded as the documented ceiling.
    ladder = {}
    best = None
    for slots in slot_counts:
        # Per-point capacity: 32 slots x 768 positions of int8 k+v+scales
        # (~6.2 GiB) + 6.4 GiB weights + ~3 GiB attention temps exceeds
        # the chip's ~15 GiB usable even with the carry donated (probed
        # in a fresh process: RESOURCE_EXHAUSTED at runtime).  Shrinking
        # IDLE capacity to 640 keeps the measurement geometry identical —
        # the attended window (512) and position are unchanged; only
        # unwritten cache rows shrink — and fits: 6.4 + 5.2 + 3.0.
        cfg_pt = cfg if slots <= 16 else dataclasses.replace(cfg, max_seq=640)
        point, point_best = _run_slot_ladder(
            jax, params, cfg_pt, (slots,), window=WINDOW, position=POS,
            n1=4, n2=24,
        )
        if isinstance(point.get(str(slots)), dict):
            point[str(slots)]["max_seq"] = cfg_pt.max_seq
        ladder.update(point)
        print("7BPOINT " + json.dumps(point), flush=True)
        if point_best is not None and (
            best is None or point_best[1]["tok_per_s"] > best[1]["tok_per_s"]
        ):
            best = point_best
    if best is None:
        emit({"error": "all ladder points failed", "slot_ladder": ladder,
              "load_s": round(load_s, 1)})
        return

    # Warm restart: reload with the page cache (and any OS read-ahead)
    # hot.  The delta vs cold attributes environment flakiness — a real
    # rollout's canary restart pays THIS number, not the cold one, when
    # the node kept its image/artifact (VERDICT r3 weak #3 / item #7).
    warm_stats: dict = {}
    warm_s = None
    warm_error = None
    wbytes = quantized_bytes(params)
    budget_s = float(os.environ.get("BENCH_7B_TIMEOUT_S", "2400"))
    spent_s = time.perf_counter() - t_begin
    if spent_s + 1.5 * load_s > budget_s * 0.95:
        # A warm load costs about one cold load minus the disk term; if
        # it can't fit before the parent's kill, skip it EXPLICITLY —
        # dying mid-warm-load would discard these fields from the record
        # (round 4 lost them to exactly that).
        warm_error = (
            f"skipped: {spent_s:.0f}s spent of {budget_s:.0f}s budget, "
            f"warm load (~{load_s:.0f}s) would not fit"
        )
    elif os.environ.get("BENCH_7B_WARM", "1") != "0":
        # Failure here must NOT discard the already-measured ladder —
        # losing a measured record to a tail step is the exact failure
        # mode this round removes (BENCH_r03 parsed=null).
        try:
            # release_first deletes the old device tree's buffers AND
            # clears the executable caches pinning them BEFORE the
            # replacement streams — the r5 "warm" reload into a near-full
            # HBM measured 1204 s of allocator pathology (vs 154 s fresh)
            # and later runs died RESOURCE_EXHAUSTED outright
            # (BENCH_7B_FULL.json warm_load_error); loader.py now owns
            # that ordering so every in-place swap gets it.
            del params  # the tree itself is freed via release_first
            old_pred, pred = pred, None
            t0 = time.perf_counter()
            pred = load_predictor(
                ckpt, quantize="int8", load_stats=warm_stats,
                release_first=old_pred,
            )
            del old_pred
            warm_s = time.perf_counter() - t0
            params = pred.causal_lm["params"]
        except Exception as e:
            warm_error = f"{type(e).__name__}: {e}"[:120]

    best_tok = best[1]["tok_per_s"]
    # Per-GB/s-of-HBM comparison: one v5e chip has 819 GB/s vs an
    # A100-80G's ~2039; decode is bandwidth-bound, so parity per GB/s
    # (ratio ~1.0) means the TPU path extracts as much from its memory
    # system as vLLM/A100 does (VERDICT r3 weak #5).  Top-level so the
    # compact driver line carries it (_COMPACT_KEYS).
    per_gbps = round(
        (best_tok / V5E_HBM_GBPS)
        / (GPU_ANCHORS["llama7b_a100_80g_tok_s"] / 2039.0),
        2,
    )
    emit({
        "device_tok_per_s": best_tok,
        "ms_per_step": best[1]["ms_per_step"],
        "slots": best[0],
        "slot_ladder": ladder,
        "bw_util_at_best": best[1]["bw_util"],
        "params_b": 6.74,
        "weight_bytes_gib": round(wbytes / 2**30, 2),
        "load_s": round(load_s, 1),
        "load_breakdown_s": load_stats,
        "warm_load_s": round(warm_s, 1) if warm_s is not None else None,
        "warm_load_breakdown_s": warm_stats or None,
        "warm_load_error": warm_error,
        "numerics": "int8 weights + int8 kv + windowed decode (window=512)",
        "vs_gpu_per_gbps": per_gbps,
        "vs_gpu_baseline": {
            "a100_80g_fp16_vllm": round(
                best_tok / GPU_ANCHORS["llama7b_a100_80g_tok_s"], 2
            ),
            "a100_80g_per_gbps": per_gbps,
        },
    })


# ---------------------------------------------------------------------------
# Scenario registry (CLI selection + --dry-run schema contract)
# ---------------------------------------------------------------------------

def bench_disaggregated() -> dict:
    """Disaggregated prefill/decode fleet vs independent replicas
    (server/kv_transfer.py + the router's prefix-affinity relay).

    The fleet problem: N independent replicas each prefill the shared
    system prompt ONCE PER REPLICA, so fleet-wide cache hit rate decays
    1/N and warm TTFT regresses to cold whenever the router's spray
    lands a repeat prefix on a replica that has not seen it.  The
    disaggregated shape prefills once on the prefill pool, hands the
    serialized K/V to every decode replica (radix-chunk wire format,
    int8kv-compact), and affinity-routes repeats — so the whole decode
    pool serves warm.

    Measured at 2 decode replicas under a mixed shared-prefix load:
    per-request TTFT through the real engine scheduler, round-robin
    (baseline: independent replicas, each pays its own cold prefill)
    vs handoff-seeded (fleet: one cold prefill on the prefill engine +
    one import per decode replica, then every request warm).  Handoff
    wall (export + wire round-trip + import) reported at p99 alongside
    the blob size; token_agreement pins the f64-proven parity at bf16
    greedy (identical token ids both ways)."""
    import threading

    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server import kv_transfer
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.prefix_cache import PrefixCacheConfig

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=768,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    C = 128
    REPLICAS = 2
    N_REQ = 8
    rng = np.random.default_rng(0)
    shared = rng.integers(1, cfg.vocab_size, size=512, dtype=np.int64)

    def make_engine():
        e = GenerationEngine(
            params, cfg, max_slots=4, dtype=jnp.bfloat16,
            prefix_cache=PrefixCacheConfig(
                enabled=True, budget_bytes=64 * 2**20, chunk_tokens=C
            ),
        )
        e.start(warmup=True)
        return e

    def one_request(engine, suffix_seed: int):
        sfx = np.random.default_rng(1000 + suffix_seed).integers(
            1, cfg.vocab_size, size=32, dtype=np.int64
        )
        prompt = np.concatenate([shared, sfx]).tolist()
        first = threading.Event()
        t0 = time.perf_counter()
        fut = engine.submit(prompt, 4, on_token=lambda _t: first.set())
        assert first.wait(timeout=300), "no first token"
        ttft = time.perf_counter() - t0
        return ttft, fut.result(timeout=300).tolist()

    def run_fleet(seed_handoff: bool):
        decode = [make_engine() for _ in range(REPLICAS)]
        handoff_walls, handoff_bytes = [], 0
        try:
            if seed_handoff:
                prefill = make_engine()
                try:
                    probe = np.concatenate(
                        [shared, [1]]
                    ).astype(np.int32)
                    prefill.generate(probe, 1)  # the one cold prefill
                    for d in decode:
                        t0 = time.perf_counter()
                        matched, chunks = prefill.export_prefix_kv(probe)
                        blob = kv_transfer.serialize_chunks(
                            C, probe, chunks
                        )
                        header, wire = kv_transfer.deserialize_chunks(blob)
                        d.import_prefix_kv(
                            kv_transfer.chunk_token_ids(header), wire
                        )
                        handoff_walls.append(time.perf_counter() - t0)
                        handoff_bytes = len(blob)
                finally:
                    prefill.shutdown()
            ttfts, outs = [], []
            for i in range(N_REQ):
                ttft, out = one_request(decode[i % REPLICAS], i)
                ttfts.append(ttft * 1000)
                outs.append(out)
            hits = sum(d.prefix_hits for d in decode)
            lookups = sum(
                d._prefix_cache.lookups for d in decode
            )
        finally:
            for d in decode:
                d.shutdown()
        ttfts.sort()
        return {
            "ttft_p50_ms": ttfts[len(ttfts) // 2],
            "ttft_p99_ms": ttfts[-1],
            "hit_rate": hits / max(lookups, 1),
            "handoff_walls": handoff_walls,
            "handoff_bytes": handoff_bytes,
            "outs": outs,
        }

    baseline = run_fleet(seed_handoff=False)
    fleet = run_fleet(seed_handoff=True)
    handoff_p99_ms = (
        sorted(fleet["handoff_walls"])[-1] * 1000
        if fleet["handoff_walls"]
        else None
    )
    agreement = float(baseline["outs"] == fleet["outs"])
    return {
        "requests": N_REQ,
        "replicas": REPLICAS,
        "prompt_tokens": 544,
        "prefill_chunk": C,
        "baseline_ttft_p50_ms": round(baseline["ttft_p50_ms"], 1),
        "baseline_ttft_p99_ms": round(baseline["ttft_p99_ms"], 1),
        "fleet_ttft_p50_ms": round(fleet["ttft_p50_ms"], 1),
        "fleet_ttft_p99_ms": round(fleet["ttft_p99_ms"], 1),
        "ttft_p99_speedup": round(
            baseline["ttft_p99_ms"] / max(fleet["ttft_p99_ms"], 1e-9), 2
        ),
        "affinity_hit_rate": round(fleet["hit_rate"], 3),
        "baseline_hit_rate": round(baseline["hit_rate"], 3),
        "handoff_p99_ms": (
            round(handoff_p99_ms, 1) if handoff_p99_ms is not None else None
        ),
        "handoff_bytes": fleet["handoff_bytes"],
        "token_agreement": agreement,
        "note": "baseline = independent replicas each cold-prefilling "
                "the shared 512-token prefix; fleet = one prefill + KV "
                "handoff into every decode replica (wire round-trip "
                "included), then the same round-robin load serves warm.",
        **_device_cost_keys(params, cfg, 4, 544 / max(
            fleet["ttft_p50_ms"] / 1000, 1e-9)),
    }


# Cost-ordered under the wall budget (measured end-to-end run: ~55 min
# cold): cheap entries and the 1.35B ladder land first; the 7B goes LAST
# because its checkpoint load alone has taken 1-12 min in this
# environment and it carries its own subprocess timeout
# (BENCH_7B_TIMEOUT_S) either way.
# Names, not function objects: resolved via getattr at run time so test
# stubs (and future monkeypatching) that setattr a bench_* replacement
# are honored — a registry of bound callables would silently pin the
# originals.
def bench_chaos() -> dict:
    """Failure containment end to end: kill/restart a live replica under
    sustained load (native router, health probes + failover on).

    Two real tiny-llama servers behind the compiled router; three client
    threads drive /generate continuously.  Mid-load, one replica is
    HARD-killed (ChaosProxy severs its listener and every established
    connection — the dead-pod shape), later restarted on the same
    address.  The scenario gates the ISSUE's acceptance numbers: ZERO
    bare 502s and zero hangs (every request resolves 200 or typed with
    Retry-After), ejection within the failure threshold, and half-open
    re-admission bounded by 2x the capped probe interval."""
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import numpy as np  # noqa: F401  (parity with sibling scenarios)

    from tpumlops.clients.chaos import ChaosProxy
    from tpumlops.clients.router import RouterProcess
    from tpumlops.clients.localplane import free_port, start_model_server
    from tpumlops.models import llama
    from tpumlops.server.loader import save_native_model
    from tpumlops.utils.config import TpuSpec

    jax = _setup_jax()

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    art = tempfile.mkdtemp() + "/llm"
    save_native_model(
        art,
        "llama-generate",
        llama.init(jax.random.key(3), cfg),
        config={
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "num_kv_heads": cfg.num_kv_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_seq": cfg.max_seq,
        },
    )
    tpu = TpuSpec.from_spec(
        {"meshShape": {"tp": 1}, "maxBatchSize": 2, "maxSlots": 2}
    )
    pa, pb = free_port(), free_port()
    ha = start_model_server(
        art, "a", pa, model_name="llm", namespace="bench", tpu=tpu,
        warmup=False,
    )
    hb = start_model_server(
        art, "b", pb, model_name="llm", namespace="bench", tpu=tpu,
        warmup=False,
    )
    chaos = ChaosProxy(pb)
    PROBE_S = 0.3
    THRESHOLD = 3
    router = RouterProcess(
        port=free_port(),
        backends={
            "a": ("127.0.0.1", pa, 50),
            "b": ("127.0.0.1", chaos.port, 50),
        },
        namespace="bench",
        deployment="llm",
        health_probes=True,
        health_threshold=THRESHOLD,
        probe_interval_s=PROBE_S,
        failover_retries=2,
    ).start()

    body = json.dumps(
        {"prompt_ids": [5, 9, 2], "max_new_tokens": 2}
    ).encode()
    url = f"http://127.0.0.1:{router.port}/v2/models/llm/generate"
    results: list = []  # (code|None, typed: bool, retry_after: bool)
    stop_load = threading.Event()

    def one(timeout=30.0):
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                resp.read()
                return (resp.status, True, True)
        except urllib.error.HTTPError as e:
            raw = e.read() or b""
            try:
                typed = bool(json.loads(raw).get("reason"))
            except json.JSONDecodeError:
                typed = False
            return (e.code, typed, e.headers.get("Retry-After") is not None)
        except Exception:
            return (None, False, False)

    def loader():
        while not stop_load.is_set():
            results.append(one())

    def fleet_health():
        return {
            b["name"]: b["healthy"]
            for b in router.admin.fleet()["backends"]
        }

    def wait_until(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return time.monotonic()
            time.sleep(0.02)
        raise TimeoutError(what)

    try:
        for _ in range(6):  # prime lazy compiles on both replicas
            code, _, _ = one(timeout=300.0)
            assert code == 200
        loaders = [
            threading.Thread(target=loader, daemon=True) for _ in range(3)
        ]
        for t in loaders:
            t.start()
        time.sleep(1.0)

        t_kill = time.monotonic()
        chaos.stop()
        t_eject = wait_until(
            lambda: not fleet_health()["b"], 20, "ejection"
        ) - t_kill
        time.sleep(0.5)  # single-replica window under load

        t_restart = time.monotonic()
        chaos.restart()
        t_readmit = wait_until(
            lambda: fleet_health()["b"], 2 * PROBE_S * 8 + 5, "re-admission"
        ) - t_restart
        time.sleep(1.0)
        stop_load.set()
        for t in loaders:
            t.join(timeout=60)

        fleet = router.admin.fleet()
        b_rec = next(x for x in fleet["backends"] if x["name"] == "b")
        n = len(results)
        ok = sum(1 for c, _, _ in results if c == 200)
        hangs = sum(1 for c, _, _ in results if c is None)
        bare = sum(
            1
            for c, typed, _ in results
            if c is not None and c != 200 and not typed
        )
        typed_errors = n - ok - hangs - bare
        # The acceptance gates — a regression here FAILS the bench.
        assert hangs == 0, f"{hangs} hung/transport-failed requests"
        assert bare == 0, f"{bare} non-typed client errors"
        assert t_readmit < 2 * PROBE_S * 8, t_readmit
        return {
            "requests": n,
            "ok": ok,
            "typed_errors": typed_errors,
            "bare_502": bare,
            "hangs": hangs,
            "availability_pct": round(100.0 * ok / max(1, n), 2),
            "eject_s": round(t_eject, 3),
            "readmit_s": round(t_readmit, 3),
            "probe_interval_s": PROBE_S,
            "health_threshold": THRESHOLD,
            "failover_total": fleet["failovers"],
            "circuit_open_total": b_rec["circuit_opened"],
        }
    finally:
        stop_load.set()
        router.stop()
        chaos.stop()
        ha.stop()
        hb.stop()


def bench_fleet_trace() -> dict:
    """Fleet trace plane overhead + stitched-trace validity gate.

    One live tiny-llama server behind the compiled router.  The same
    request mix runs twice — journey ring OFF (the byte-for-byte
    default) then ON via the runtime /router/config knob — and the
    scenario reports the tok/s delta (acceptance: within noise) plus a
    HARD gate on trace coherence: every traced request id must appear
    in BOTH the router journey chrome track and the replica's
    flight-recorder track once stitched onto one timeline, with
    token-for-token identical outputs between the two phases."""
    import tempfile
    import threading
    import urllib.request

    from tpumlops.clients.localplane import free_port, start_model_server
    from tpumlops.clients.router import RouterProcess
    from tpumlops.models import llama
    from tpumlops.server.loader import save_native_model
    from tpumlops.utils.config import TpuSpec
    from tpumlops.utils.trace_stitch import (
        fetch_source,
        request_ids_by_pid,
        stitch_chrome_traces,
    )

    jax = _setup_jax()

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    art = tempfile.mkdtemp() + "/llm"
    save_native_model(
        art,
        "llama-generate",
        llama.init(jax.random.key(3), cfg),
        config={
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "num_kv_heads": cfg.num_kv_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_seq": cfg.max_seq,
        },
    )
    tpu = TpuSpec.from_spec(
        {
            "meshShape": {"tp": 1},
            "maxBatchSize": 2,
            "maxSlots": 2,
            "observability": {"traceRing": 1024},
        }
    )
    RING = 256
    N_REQ = 48
    NEW_TOKENS = 16
    port = free_port()
    handle = start_model_server(
        art, "v1", port, model_name="llm", namespace="bench", tpu=tpu,
        warmup=False,
    )
    router = RouterProcess(
        port=free_port(),
        backends={"v1": ("127.0.0.1", port, 100)},
        namespace="bench",
        deployment="llm",
    ).start()
    url = f"http://127.0.0.1:{router.port}/v2/models/llm/generate"

    def one(i: int, rid: "str | None" = None, timeout=300.0):
        body = json.dumps(
            {
                "prompt_ids": [5, 9, 2, (i % 7) + 1],
                "max_new_tokens": NEW_TOKENS,
            }
        ).encode()
        headers = {"Content-Type": "application/json"}
        if rid is not None:
            headers["X-Request-Id"] = rid
        req = urllib.request.Request(url, data=body, headers=headers)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())["outputs"][0]["data"]

    def phase(tag: str):
        outs, t0 = [], time.perf_counter()
        for i in range(N_REQ):
            outs.append(one(i, rid=f"{tag}-{i}" if tag == "on" else None))
        wall = time.perf_counter() - t0
        tokens = sum(len(o) for o in outs)
        return outs, tokens / wall

    try:
        for _ in range(4):  # prime lazy compiles off the clock
            one(0)
        outs_off, tps_off = phase("off")
        # Flip the trace plane on at RUNTIME — the same knob RouterSync
        # drives from the manifest annotation.
        router.admin.set_config(
            [{"name": "v1", "host": "127.0.0.1", "port": port,
              "weight": 100}],
            journey_ring=RING,
        )
        outs_on, tps_on = phase("on")

        journeys = router.admin.journeys()
        merged = stitch_chrome_traces(
            [
                fetch_source(
                    "router", f"http://127.0.0.1:{router.port}", "router"
                ),
                fetch_source("v1", f"http://127.0.0.1:{port}", "replica"),
            ]
        )
        by_pid = request_ids_by_pid(merged)
        traced = {f"on-{i}" for i in range(N_REQ)}
        shared = traced & by_pid.get(1, set()) & by_pid.get(2, set())
        # HARD gates: coherent stitching + token parity.
        assert shared == traced, (
            f"only {len(shared)}/{len(traced)} ids shared across tracks"
        )
        agreement = float(outs_off == outs_on)
        assert agreement == 1.0, "journey ring changed generated tokens"
        overhead_pct = 100.0 * (tps_off - tps_on) / max(tps_off, 1e-9)
        return {
            "requests": 2 * N_REQ,
            "new_tokens_per_request": NEW_TOKENS,
            "journey_ring": RING,
            "tok_per_s_off": round(tps_off, 1),
            "tok_per_s_on": round(tps_on, 1),
            "overhead_pct": round(overhead_pct, 2),
            "journeys_recorded": journeys["recorded"],
            "stitched_events": len(merged["traceEvents"]),
            "stitched_components": len(by_pid),
            "stitched_shared_ids": len(shared),
            "token_agreement": agreement,
            "note": "overhead = same mix through the router with the "
                    "journey ring off vs on (headers minted + "
                    "propagated, ring append per request); stitched "
                    "gate = every traced id present in BOTH the router "
                    "journey track and the replica flight-recorder "
                    "track on one timeline.",
        }
    finally:
        router.stop()
        handle.stop()


def bench_multi_model() -> dict:
    """Serverless multi-model multiplexing: M=4 tiny models share R=2
    warm-pool replicas (operator/multiplexer.py bin-packer + the
    router's model-aware pick) vs one dedicated replica per model.

    The fleet problem: one CR per model pins a whole chip for the long
    tail of rarely-hit models.  The multiplexed shape keeps M models on
    R < M warm-pool replicas — a model with traffic holds a replica, a
    cold model holds NOTHING (its requests park at the router; the
    parked gauge's model label is the wake signal), and the packer
    swaps models in via snapshot restore on the existing /admin/attach
    endpoint.

    Measured: the same hot-model request mix through the mux router
    against 4 dedicated replicas (baseline, 4 chips) and against the
    2-replica shared pool (2 chips) — chips_saved at equal p99 is the
    headline.  The swap ladder times the scale-from-zero path
    (park -> pump/attach -> release -> 200) for a cold model arriving
    mid-load.  HARD gates: zero lost requests (every parked request
    completes 200), chips_saved >= 1.5 at equal p99 (3x + 250 ms noise
    bound), token_agreement 1.0 (each model serves identical tokens
    from either topology)."""
    import asyncio
    import tempfile
    import threading
    import urllib.request

    from tpumlops.clients.localplane import free_port, start_model_server
    from tpumlops.clients.router import RouterProcess
    from tpumlops.models import llama
    from tpumlops.operator.multiplexer import Multiplexer, MuxReplica
    from tpumlops.server.app import build_server
    from tpumlops.server.loader import save_native_model
    from tpumlops.utils.config import ServerConfig, TpuSpec

    jax = _setup_jax()

    M, R = 4, 2
    cfg = llama.LlamaConfig.tiny(max_seq=64)
    root = tempfile.mkdtemp()
    snap_dir = f"{root}/snaps"
    dims = {
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "num_layers": cfg.num_layers,
        "num_heads": cfg.num_heads,
        "num_kv_heads": cfg.num_kv_heads,
        "intermediate_size": cfg.intermediate_size,
        "max_seq": cfg.max_seq,
    }
    uris = {}
    for i in range(M):
        art = f"{root}/m{i}"
        save_native_model(
            art, "llama-generate",
            llama.init(jax.random.key(10 + i), cfg), config=dims,
        )
        uris[f"m{i}"] = art
    uri_to_model = {u: n for n, u in uris.items()}
    tpu = TpuSpec.from_spec(
        {
            "meshShape": {"tp": 1},
            "maxBatchSize": 2,
            "maxSlots": 2,
            "snapshot": {"enabled": True, "dir": snap_dir},
        }
    )

    totals = {"requests": 0, "ok": 0}

    def one(router_port: int, model: str, timeout: float = 300.0):
        """One generate through the router; (wall_ms, tokens)."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{router_port}/v2/models/{model}/generate",
            data=json.dumps(
                {"prompt_ids": [5, 9, 2], "max_new_tokens": 4}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        totals["requests"] += 1
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = json.loads(resp.read())
        totals["ok"] += 1
        wall_ms = (time.perf_counter() - t0) * 1000.0
        return wall_ms, body["outputs"][0]["data"]

    N_HOT = 16  # timed hot-phase requests per topology (m0/m1 mix)

    # -- baseline: one dedicated replica per model (M chips).  Booting
    # with snapshots enabled also BAKES each model's snapshot, which is
    # exactly what the shared pool restores from.
    dedicated = {}
    ded_router = None
    ded_tokens = {}
    try:
        for name, uri in uris.items():
            port = free_port()
            dedicated[name] = (
                start_model_server(
                    uri, "llama-generate", port, model_name=name,
                    namespace="bench", tpu=tpu, warmup=False,
                ),
                port,
            )
        ded_router = RouterProcess(
            port=free_port(),
            backends={
                name: ("127.0.0.1", port, 25)
                for name, (_h, port) in dedicated.items()
            },
            namespace="bench",
            deployment="llm",
            mux_models=1,
        ).start()
        ded_router.admin.set_config(
            [
                {"name": name, "host": "127.0.0.1", "port": port,
                 "weight": 25, "model": name}
                for name, (_h, port) in dedicated.items()
            ],
            namespace="bench", deployment="llm", mux_models=1,
        )
        for name in uris:  # prime lazy compiles; canonical tokens
            _w, toks = one(ded_router.port, name)
            ded_tokens[name] = toks
        ded_walls = []
        for i in range(N_HOT):
            w, _t = one(ded_router.port, f"m{i % 2}")
            ded_walls.append(w)
        ded_walls.sort()
        dedicated_p99_ms = ded_walls[-1]
    finally:
        if ded_router is not None:
            ded_router.stop()
        for handle, _port in dedicated.values():
            handle.stop()

    # -- shared pool: R warm-pool replicas (no weights until attach),
    # the mux router parking cold-model requests, and the real packer
    # executing its plan through /admin/attach.
    def start_warm_replica(port: int):
        server = build_server(
            ServerConfig(
                model_name="llm", model_uri=uris["m0"], tpu=tpu,
                warm_pool=True,
            ),
            warmup=False,
        )
        loop = asyncio.new_event_loop()

        def run():
            asyncio.set_event_loop(loop)
            from aiohttp import web

            runner = web.AppRunner(server.build_app())
            loop.run_until_complete(runner.setup())
            loop.run_until_complete(
                web.TCPSite(runner, "127.0.0.1", port).start()
            )
            loop.run_forever()

        threading.Thread(target=run, daemon=True).start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/livez", timeout=1
                )
                break
            except Exception:
                time.sleep(0.05)
        return server, loop

    pool_ports = {"rA": free_port(), "rB": free_port()}
    pool = {n: start_warm_replica(p) for n, p in pool_ports.items()}
    router = RouterProcess(
        port=free_port(),
        backends={
            n: ("127.0.0.1", p, 50) for n, p in pool_ports.items()
        },
        namespace="bench",
        deployment="llm",
        park_buffer=16,
        park_timeout_s=120.0,
        mux_models=1,
    ).start()
    mux = Multiplexer(
        pool="bench-pool",
        replicas=[
            MuxReplica(n, url=f"http://127.0.0.1:{p}")
            for n, p in sorted(pool_ports.items())
        ],
        parked=lambda: router.admin.parked().get("models") or {},
    )
    for name, uri in uris.items():
        mux.register(name, uri=uri)

    def sync_router():
        """What RouterSync does in production: publish the packer's
        attached-model table so the router routes + releases parks."""
        held = {
            r.name: uri_to_model.get(r.attached_uri, "")
            for r in mux.replicas
        }
        router.admin.set_config(
            [
                {"name": n, "host": "127.0.0.1", "port": p,
                 "weight": 50, "model": held.get(n, "")}
                for n, p in pool_ports.items()
            ],
            namespace="bench", deployment="llm", mux_models=1,
        )

    def parked_requests(models, results):
        """Fire one request per model on threads; they PARK (no holder
        yet) until the packer attaches and the router config commits."""
        threads = []
        for i, m in enumerate(models):
            def send(i=i, m=m):
                try:
                    results[i] = one(router.port, m)
                except Exception as e:
                    results[i] = e
            t = threading.Thread(target=send, daemon=True)
            t.start()
            threads.append(t)
        return threads

    def wait_parked(n: int):
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            models = router.admin.parked().get("models") or {}
            if sum(models.values()) >= n:
                return
            time.sleep(0.02)
        raise TimeoutError("requests never parked")

    try:
        # Phase 1 — wake: the first m0/m1 requests find NO holder (the
        # pool starts empty: scale-to-zero is the default state), park,
        # and are released by the packer's attach.
        res: dict = {}
        threads = parked_requests(["m0", "m1"], res)
        wait_parked(2)
        t0 = time.perf_counter()
        mux.pump(force=True)
        sync_router()
        wake_attach_ms = (time.perf_counter() - t0) * 1000.0
        for t in threads:
            t.join(timeout=300)
        assert all(
            isinstance(v, tuple) for v in res.values()
        ), f"wake requests failed: {res}"

        # Phase 2 — hot steady state: the SAME mix the baseline timed.
        shared_tokens = {}
        for m in ("m0", "m1"):  # prime post-attach compiles off-clock
            _w, toks = one(router.port, m)
            shared_tokens[m] = toks
        shared_walls = []
        for i in range(N_HOT):
            w, _t = one(router.port, f"m{i % 2}")
            shared_walls.append(w)
        shared_walls.sort()
        shared_p99_ms = shared_walls[-1]

        # Phase 3 + 4 — cold-model swaps: m2 then m3 arrive with zero
        # holders; each parks, the packer REPLACES the lowest-scored
        # attachment (snapshot restore), the park releases, 200.
        swap_attach_walls, swap_e2e_walls = [], []
        for m in ("m2", "m3"):
            res = {}
            threads = parked_requests([m], res)
            wait_parked(1)
            t0 = time.perf_counter()
            recs = mux.pump(force=True)
            sync_router()
            swap_attach_walls.append(
                (time.perf_counter() - t0) * 1000.0
            )
            assert any(
                r.action in ("attach", "replace") and r.model == m
                for r in recs
            ), [r.as_dict() for r in recs]
            for t in threads:
                t.join(timeout=300)
            assert isinstance(res[0], tuple), f"swap {m} failed: {res}"
            swap_e2e_walls.append(res[0][0])
            shared_tokens[m] = res[0][1]

        # The surviving hot model was never displaced by the swaps.
        _w, toks = one(router.port, "m1")
        assert toks == ded_tokens["m1"]

        holds_total = sum(
            1 for rs in mux._pending.values() for r in rs
            if r.action == "hold"
        )
        agreement = float(
            all(shared_tokens[n] == ded_tokens[n] for n in uris)
        )
        lost = totals["requests"] - totals["ok"]
        chips_saved = round(M / R, 2)  # tp=1: one chip per replica
        # The acceptance gates — a regression here FAILS the bench.
        assert lost == 0, f"{lost} lost requests"
        assert agreement == 1.0, "token disagreement between topologies"
        assert chips_saved >= 1.5, chips_saved
        assert shared_p99_ms <= 3.0 * dedicated_p99_ms + 250.0, (
            shared_p99_ms, dedicated_p99_ms,
        )
        assert mux.moves_total >= 4, mux.moves_total  # 2 wakes + 2 swaps
        return {
            "models": M,
            "shared_replicas": R,
            "dedicated_replicas": M,
            "requests": totals["requests"],
            "ok": totals["ok"],
            "lost": lost,
            "dedicated_chips": M,
            "shared_chips": R,
            "chips_saved": chips_saved,
            "dedicated_p99_ms": round(dedicated_p99_ms, 1),
            "shared_p99_ms": round(shared_p99_ms, 1),
            "p99_ratio": round(
                shared_p99_ms / max(dedicated_p99_ms, 1e-9), 2
            ),
            "wake_attach_ms": round(wake_attach_ms, 1),
            "swap_attach_ms": round(max(swap_attach_walls), 1),
            "swap_e2e_p99_ms": round(max(swap_e2e_walls), 1),
            "swaps_total": mux.moves_total,
            "holds_total": holds_total,
            "token_agreement": agreement,
            "note": "baseline = 4 dedicated replicas (4 chips) behind "
                    "the same mux router; shared = the 2-replica warm "
                    "pool (2 chips) with the real bin-packer executing "
                    "attach/replace via snapshot restore; swap ladder = "
                    "cold model parks -> pump attaches -> park releases "
                    "-> 200, measured end to end.",
        }
    finally:
        router.stop()
        for server, loop in pool.values():
            server.shutdown()
            loop.call_soon_threadsafe(loop.stop)


def bench_priority_preemption() -> dict:
    """Interactive TTFT under a 2x best-effort flood, mid-decode
    preemption off vs on (server/generation.py ``preemption=True``,
    ISSUE 18).

    Flood: 2x as many long best-effort generations as decode slots, so
    every slot is busy and a queue exists.  Interactive requests then
    arrive.  Without preemption they hold queue PRIORITY but still wait
    for a best-effort stream to finish — TTFT is someone else's decode
    tail.  With preemption the engine evicts a best-effort slot at the
    next tick boundary (KV spilled through the prefix cache), admits the
    interactive request immediately, and restores the evicted stream
    afterward with NO lost work: the restore re-seeds from cached KV +
    the PRNG carry, so the preempted stream's tokens are bit-identical
    to the un-preempted run's.

    HARD gates: interactive TTFT p99 improves >= 2x; zero lost work
    (token callbacks never re-fire across evict/restore); best-effort
    outputs identical between the two modes (token_agreement 1.0)."""
    import threading

    jax = _setup_jax()
    import gc

    gc.collect()
    jax.clear_caches()
    gc.collect()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.prefix_cache import PrefixCacheConfig

    cfg = llama.LlamaConfig(
        vocab_size=4000, hidden_size=256, num_layers=4, num_heads=4,
        num_kv_heads=4, intermediate_size=704, max_seq=256,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    SLOTS, PROMPT, NEW_BE, NEW_I = 4, 32, 64, 8
    N_BE = 2 * SLOTS  # the 2x flood
    N_I = 6
    rng = np.random.default_rng(0)
    be_prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_BE)
    ]
    ia_prompts = [
        rng.integers(1, cfg.vocab_size, size=PROMPT).tolist()
        for _ in range(N_I)
    ]

    def run(preemption: bool) -> dict:
        engine = GenerationEngine(
            params, cfg, max_slots=SLOTS, dtype=jnp.bfloat16,
            prefix_cache=PrefixCacheConfig(
                enabled=True, budget_bytes=1 << 24, chunk_tokens=16
            ),
            preemption=preemption,
        )
        engine.start(warmup=True)
        try:
            be_callbacks = [0] * N_BE
            flood_rolling = threading.Event()

            def be_cb_for(i):
                def cb(_tok):
                    be_callbacks[i] += 1
                    if sum(be_callbacks) >= 2 * SLOTS:
                        flood_rolling.set()
                return cb

            t0 = time.perf_counter()
            be_futs = [
                engine.submit(
                    p, NEW_BE, on_token=be_cb_for(i),
                    slo_class="best-effort",
                )
                for i, p in enumerate(be_prompts)
            ]
            assert flood_rolling.wait(600), "flood never produced tokens"

            ttfts = [None] * N_I
            t_sub = [0.0] * N_I
            first = [threading.Event() for _ in range(N_I)]

            def ia_cb_for(i):
                def cb(_tok):
                    if ttfts[i] is None:
                        ttfts[i] = time.perf_counter() - t_sub[i]
                        first[i].set()
                return cb

            ia_futs = []
            for i, p in enumerate(ia_prompts):
                t_sub[i] = time.perf_counter()
                ia_futs.append(engine.submit(
                    p, NEW_I, on_token=ia_cb_for(i),
                    slo_class="interactive",
                ))
                first[i].wait(600)
            ia_outs = [np.asarray(f.result(600)).tolist() for f in ia_futs]
            be_outs = [np.asarray(f.result(600)).tolist() for f in be_futs]
            wall = time.perf_counter() - t0
            assert all(ev.is_set() for ev in first)
            return {
                "be_outs": be_outs,
                "ia_outs": ia_outs,
                "ttfts_ms": [t * 1000 for t in ttfts],
                "be_callbacks": list(be_callbacks),
                "preemptions": engine.preemptions,
                "restores": engine.preempt_restores,
                "tok_per_s": (N_BE * NEW_BE + N_I * NEW_I) / wall,
            }
        finally:
            engine.shutdown()

    off = run(False)
    on = run(True)
    p_off = _percentiles(off["ttfts_ms"])
    p_on = _percentiles(on["ttfts_ms"])
    # Zero lost work: every best-effort token was produced exactly once
    # in BOTH modes — a restore that replayed (or dropped) tokens would
    # re-fire (or starve) the per-token callback.
    expected = N_BE * NEW_BE
    work_lost = (sum(on["be_callbacks"]) - expected) + (
        sum(off["be_callbacks"]) - expected
    )
    flat_on = [t for o in on["be_outs"] for t in o]
    flat_off = [t for o in off["be_outs"] for t in o]
    agreement = float(
        len(flat_on) == len(flat_off)
        and all(a == b for a, b in zip(flat_on, flat_off))
    )
    speedup = p_off[99] / max(1e-9, p_on[99])
    # HARD gates (the ISSUE 18 acceptance bar).
    assert on["preemptions"] >= 1 and on["restores"] >= 1, on
    assert off["preemptions"] == 0, off
    assert work_lost == 0, (on["be_callbacks"], off["be_callbacks"])
    assert agreement == 1.0, "preemption changed best-effort tokens"
    assert speedup >= 2.0, (p_off, p_on)
    return {
        "slots": SLOTS,
        "best_effort_requests": N_BE,
        "interactive_requests": N_I,
        "new_tokens_best_effort": NEW_BE,
        "new_tokens_interactive": NEW_I,
        "interactive_ttft_p50_ms_off": round(p_off[50], 1),
        "interactive_ttft_p99_ms_off": round(p_off[99], 1),
        "interactive_ttft_p50_ms_on": round(p_on[50], 1),
        "interactive_ttft_p99_ms_on": round(p_on[99], 1),
        "ttft_p99_speedup": round(speedup, 2),
        "preemptions": on["preemptions"],
        "restores": on["restores"],
        "work_lost_tokens": work_lost,
        "token_agreement": agreement,
        **_device_cost_keys(params, cfg, SLOTS, on["tok_per_s"]),
        "note": (
            "2x best-effort flood holds every slot; interactive "
            "arrivals with preemption off wait out a stranger's decode "
            "tail (queue priority alone), with preemption on they evict "
            "a best-effort slot at the tick boundary and its stream "
            "restores later bit-identically (zero lost work)"
        ),
    }


SCENARIOS: "tuple[tuple[str, str], ...]" = (
    ("time_to_100pct_traffic", "bench_time_to_100"),
    ("iris_sklearn_linear", "bench_iris"),
    ("xgboost_forest", "bench_xgboost"),
    ("resnet50", "bench_resnet"),
    ("prefix_cache_serving", "bench_prefix_cache"),
    ("speculative_serving", "bench_speculative"),
    ("multistep_serving", "bench_multistep"),
    ("superstep_serving", "bench_superstep"),
    ("tensor_parallel_serving", "bench_tensor_parallel"),
    ("long_context_serving", "bench_long_context"),
    ("packed_prefill_serving", "bench_packed_prefill"),
    ("admission_control_serving", "bench_admission_control"),
    ("observability_serving", "bench_observability"),
    ("anomaly_observability_serving", "bench_anomaly_observability"),
    ("device_telemetry_serving", "bench_device_telemetry"),
    ("cold_start_serving", "bench_cold_start"),
    ("disaggregated_serving", "bench_disaggregated"),
    ("chaos_serving", "bench_chaos"),
    ("multi_model_serving", "bench_multi_model"),
    ("fleet_trace_serving", "bench_fleet_trace"),
    ("priority_preemption_serving", "bench_priority_preemption"),
    ("llama_1p35b_decode", "bench_llama_decode"),
    ("serve_path_http", "bench_serve_path"),
    ("llama_7b_decode", "bench_llama_7b_decode"),
)

# The JSON-schema contract per scenario: keys a successful run MUST carry
# (error/skipped shapes are exempt).  ``--dry-run`` prints this without
# touching a device, so tests/test_bench_contract.py can pin the shape —
# drift between a bench function and its published schema fails locally
# instead of surfacing as a missing field in the round's record.
SCENARIO_SCHEMAS: dict = {
    "tensor_parallel_serving": (
        "requests", "new_tokens_per_request", "slots",
        "tok_per_s_tp1", "tok_per_s_tp2", "tok_per_s_tp4",
        "dispatches_per_token_tp1", "dispatches_per_token_tp4",
        "per_chip_hbm_bytes_tp1", "per_chip_hbm_bytes_tp4",
        "tok_per_s_dp1", "tok_per_s_dp2",
        "dp_tokens_per_dispatch_ratio", "dp_token_agreement",
        "token_agreement", "mfu", "hbm_peak_bytes",
    ),
    "long_context_serving": (
        "prompt_tokens", "new_tokens", "sp_prefill_threshold",
        "ttft_ms_sp_off", "ttft_ms_sp2", "ttft_ms_sp4",
        "sp_dispatches", "chunk_dispatches_replaced",
        "token_agreement", "sp1_pin_identical_ledger",
        "fits_32k_sp1", "fits_32k_sp4",
        "est_ttft_s_32k_sp1", "est_ttft_s_32k_sp4", "est_ttft_gain_32k",
        "mfu", "hbm_peak_bytes",
    ),
    "packed_prefill_serving": (
        "requests", "prompt_tokens", "prefill_chunk", "prefill_batch",
        "serial_ttft_p50_ms", "serial_ttft_p99_ms", "serial_chunk_calls",
        "packed_ttft_p50_ms", "packed_ttft_p99_ms", "packed_chunk_calls",
        "ttft_p50_speedup", "chunk_call_reduction", "batch_fill_mean",
        "token_agreement", "mfu", "hbm_peak_bytes",
    ),
    "prefix_cache_serving": (
        "cold_ttft_ms", "warm_ttft_ms", "ttft_speedup",
        "chunks_cold", "chunks_warm", "hits", "evictions",
        "mfu", "hbm_peak_bytes",
    ),
    "speculative_serving": (
        "rep_forwards_per_token", "rep_acceptance_rate",
        "rnd_forwards_per_token", "plain_forwards_per_token",
        "speedup_vs_plain_repetitive", "mfu", "hbm_peak_bytes",
    ),
    "multistep_serving": (
        "requests", "new_tokens_per_request", "slots",
        "k1_dispatches_per_token", "k4_dispatches_per_token",
        "dispatch_reduction_k4", "tok_per_s_k1", "tok_per_s_k4",
        "itl_p50_ms_k4", "itl_p99_ms_k4", "token_agreement",
        "mfu", "hbm_peak_bytes",
    ),
    "superstep_serving": (
        "requests", "new_tokens_per_request", "slots", "decode_steps",
        "legacy_compiles", "unified_compiles", "compile_collapse_ratio",
        "legacy_warmup_s", "unified_warmup_s",
        "legacy_dispatches_per_token", "unified_dispatches_per_token",
        "tok_per_s_legacy", "tok_per_s_unified",
        "itl_p99_ms_legacy", "itl_p99_ms_unified",
        "interleave_stall_delta_ms", "variant_inventory",
        "token_agreement", "mfu", "hbm_peak_bytes",
    ),
    "observability_serving": (
        "tok_per_s_off", "tok_per_s_on", "overhead_pct",
        "decode_step_ms_off", "decode_step_ms_on",
        "ring_ticks", "trace_events", "token_agreement",
        "mfu", "hbm_peak_bytes",
    ),
    "anomaly_observability_serving": (
        "requests", "new_tokens_per_request", "slots", "timeseries_ring",
        "tok_per_s_off", "tok_per_s_on", "overhead_pct",
        "ring_samples", "replicas", "injected_slowdown_x",
        "mad_threshold", "straggler_flagged", "false_positives",
        "token_agreement", "mfu", "hbm_peak_bytes",
    ),
    "device_telemetry_serving": (
        "tok_per_s_off", "tok_per_s_on", "overhead_pct",
        "hbm_ledger_total_bytes", "ledger_vs_measured_pct",
        "kv_bytes_per_row", "max_cache_rows",
        "decode_mfu", "decode_hbm_bw_util", "prefill_mfu",
        "warmup_compiles", "warmup_compile_s", "token_agreement",
        "mfu", "hbm_peak_bytes",
    ),
    "admission_control_serving": (
        "requests", "slots", "budget_tokens", "shed", "shed_rate",
        "completed_ok",
        "admitted_ttft_p99_ms_unbounded", "admitted_ttft_p99_ms_bounded",
        "admitted_ttft_p50_ms_unbounded", "admitted_ttft_p50_ms_bounded",
        "ttft_p99_improvement", "mfu", "hbm_peak_bytes",
    ),
    "cold_start_serving": (
        "hf_cold_s", "native_cold_s", "snapshot_bake_s",
        "snapshot_restore_s",
        "restore_speedup_vs_hf", "restore_speedup_vs_native",
        "cold_read_gib", "snapshot_read_gib", "bytes_reduction",
        "cold_breakdown_s", "restore_breakdown_s",
        "token_agreement", "mfu", "hbm_peak_bytes",
    ),
    "disaggregated_serving": (
        "requests", "replicas", "prompt_tokens", "prefill_chunk",
        "baseline_ttft_p50_ms", "baseline_ttft_p99_ms",
        "fleet_ttft_p50_ms", "fleet_ttft_p99_ms", "ttft_p99_speedup",
        "affinity_hit_rate", "baseline_hit_rate",
        "handoff_p99_ms", "handoff_bytes",
        "token_agreement", "mfu", "hbm_peak_bytes",
    ),
    "chaos_serving": (
        "requests", "ok", "typed_errors", "bare_502", "hangs",
        "availability_pct", "eject_s", "readmit_s",
        "probe_interval_s", "health_threshold",
        "failover_total", "circuit_open_total",
    ),
    "multi_model_serving": (
        "models", "shared_replicas", "dedicated_replicas",
        "requests", "ok", "lost",
        "dedicated_chips", "shared_chips", "chips_saved",
        "dedicated_p99_ms", "shared_p99_ms", "p99_ratio",
        "wake_attach_ms", "swap_attach_ms", "swap_e2e_p99_ms",
        "swaps_total", "holds_total", "token_agreement",
    ),
    "fleet_trace_serving": (
        "requests", "new_tokens_per_request", "journey_ring",
        "tok_per_s_off", "tok_per_s_on", "overhead_pct",
        "journeys_recorded", "stitched_events", "stitched_components",
        "stitched_shared_ids", "token_agreement",
    ),
    "priority_preemption_serving": (
        "slots", "best_effort_requests", "interactive_requests",
        "new_tokens_best_effort", "new_tokens_interactive",
        "interactive_ttft_p50_ms_off", "interactive_ttft_p99_ms_off",
        "interactive_ttft_p50_ms_on", "interactive_ttft_p99_ms_on",
        "ttft_p99_speedup", "preemptions", "restores",
        "work_lost_tokens", "token_agreement", "mfu", "hbm_peak_bytes",
    ),
}


def _unknown_scenario_error(names: "list[str]") -> str:
    valid = ", ".join(name for name, _ in SCENARIOS)
    bad = ", ".join(repr(n) for n in names)
    return f"unknown scenario(s) {bad}; valid scenarios: {valid}"


def parse_args(argv: "list[str] | None" = None):
    import argparse

    ap = argparse.ArgumentParser(
        "bench", description="Benchmark of record (driver contract: "
        "prints ONE JSON line; full record in BENCH_DETAIL.json)."
    )
    ap.add_argument(
        "scenarios", nargs="*",
        help="secondary scenarios to run (default: all); unknown names "
        "exit 2 with the valid set listed",
    )
    ap.add_argument(
        "--dry-run", action="store_true",
        help="validate scenario names and print the selected scenarios' "
        "JSON schema contract without touching a device",
    )
    return ap.parse_args(argv)


def _validate_scenarios(names: "list[str]") -> None:
    known = {name for name, _ in SCENARIOS}
    bad = [n for n in names if n not in known]
    if bad:
        # One line, no traceback: a typo'd scenario name must name the
        # valid set, not die in a KeyError stack.
        print(_unknown_scenario_error(bad), file=sys.stderr)
        sys.exit(2)


def dry_run(names: "list[str]") -> None:
    selected = names or [name for name, _ in SCENARIOS]
    out = {
        "dry_run": True,
        "scenarios": {
            name: sorted(SCENARIO_SCHEMAS.get(name, ())) for name in selected
        },
    }
    print(json.dumps(out))


# ---------------------------------------------------------------------------
# Driver-line compaction (VERDICT r3 #1)
# ---------------------------------------------------------------------------

# The driver captures only the last ~2 KB of stdout; round 3's final line
# outgrew that (slot ladders + prose notes) and the official record lost
# the round's headline (BENCH_r03.json "parsed": null).  The full record
# now goes to BENCH_DETAIL.json and stderr; stdout carries one compact,
# size-guarded headline line.
COMPACT_BUDGET_BYTES = 1500

# Per-secondary allowlist of the keys that belong on the headline line.
# Everything else (ladders, parity fixtures, notes, breakdowns) lives in
# BENCH_DETAIL.json.
_COMPACT_KEYS = {
    "time_to_100pct_traffic": (
        "measured_s", "policy_floor_s", "operator_overhead_s"),
    "iris_sklearn_linear": ("p50_us",),
    "xgboost_forest": ("p50_us", "eval_form"),
    "resnet50": ("img_per_s", "p50_ms", "mfu"),
    "llama_1p35b_decode": (
        "device_tok_per_s", "slots", "bw_util_at_best"),
    "prefix_cache_serving": (
        "cold_ttft_ms", "warm_ttft_ms", "chunks_cold", "chunks_warm",
        "mfu", "hbm_peak_bytes"),
    "speculative_serving": (
        "rep_forwards_per_token", "plain_forwards_per_token",
        "rep_acceptance_rate", "speedup_vs_plain_repetitive",
        "mfu", "hbm_peak_bytes"),
    "multistep_serving": (
        "k1_dispatches_per_token", "k4_dispatches_per_token",
        "dispatch_reduction_k4", "tok_per_s_k1", "tok_per_s_k4",
        "token_agreement", "mfu", "hbm_peak_bytes"),
    "superstep_serving": (
        "legacy_compiles", "unified_compiles", "compile_collapse_ratio",
        "unified_dispatches_per_token",
        "token_agreement", "mfu", "hbm_peak_bytes"),
    "tensor_parallel_serving": (
        "tok_per_s_tp1", "tok_per_s_tp4",
        "dispatches_per_token_tp4", "per_chip_hbm_bytes_tp4",
        "dp_tokens_per_dispatch_ratio", "dp_token_agreement",
        "token_agreement", "mfu", "hbm_peak_bytes"),
    "long_context_serving": (
        "ttft_ms_sp_off", "ttft_ms_sp4", "chunk_dispatches_replaced",
        "fits_32k_sp4", "est_ttft_gain_32k",
        "token_agreement", "mfu", "hbm_peak_bytes"),
    "packed_prefill_serving": (
        "serial_ttft_p50_ms", "packed_ttft_p50_ms",
        "serial_chunk_calls", "packed_chunk_calls",
        "chunk_call_reduction", "mfu", "hbm_peak_bytes"),
    "observability_serving": (
        "tok_per_s_off", "tok_per_s_on", "overhead_pct",
        "mfu", "hbm_peak_bytes"),
    "anomaly_observability_serving": (
        "tok_per_s_off", "tok_per_s_on", "overhead_pct",
        "straggler_flagged", "false_positives",
        "mfu", "hbm_peak_bytes"),
    "device_telemetry_serving": (
        "overhead_pct", "decode_mfu", "ledger_vs_measured_pct",
        "mfu", "hbm_peak_bytes"),
    "admission_control_serving": (
        "shed_rate", "admitted_ttft_p99_ms_unbounded",
        "admitted_ttft_p99_ms_bounded", "ttft_p99_improvement",
        "mfu", "hbm_peak_bytes"),
    "cold_start_serving": (
        "hf_cold_s", "native_cold_s", "snapshot_restore_s",
        "restore_speedup_vs_hf", "bytes_reduction", "token_agreement"),
    "disaggregated_serving": (
        "baseline_ttft_p99_ms", "fleet_ttft_p99_ms", "ttft_p99_speedup",
        "affinity_hit_rate", "handoff_p99_ms", "token_agreement",
        "mfu", "hbm_peak_bytes"),
    "chaos_serving": (
        "availability_pct", "bare_502", "hangs",
        "eject_s", "readmit_s", "failover_total"),
    "multi_model_serving": (
        "chips_saved", "dedicated_p99_ms", "shared_p99_ms",
        "swap_e2e_p99_ms", "lost", "token_agreement"),
    "fleet_trace_serving": (
        "tok_per_s_off", "tok_per_s_on", "overhead_pct",
        "stitched_shared_ids", "token_agreement"),
    "priority_preemption_serving": (
        "interactive_ttft_p99_ms_off", "interactive_ttft_p99_ms_on",
        "ttft_p99_speedup", "work_lost_tokens", "token_agreement",
        "mfu", "hbm_peak_bytes"),
    "serve_path_http": (
        "server_queue_mean_ms", "server_device_run_mean_ms",
        "server_pipeline_wait_mean_ms", "server_observed_mean_ms",
        "router_overhead_p50_ms", "router_overhead_p99_ms",
        "batch_fill_mean"),
    "llama_7b_decode": (
        "device_tok_per_s", "slots", "bw_util_at_best", "load_s",
        "warm_load_s", "vs_gpu_per_gbps"),
}

# Top-level keys dropped one by one (least headline-y first) if the
# compact line still exceeds the budget after secondary compaction.
# p99_raw_ms sheds LAST before the secondaries (ADVICE r5 #2): the
# untrimmed tail is the guard that keeps a masked >15% sustained
# regression visible on the driver-visible line, so every cosmetic field
# goes before it (the bf16 raw99 still goes early — the headline raw99
# is the guard of record).
_SHED_ORDER = (
    "bf16_p99_raw_ms", "numerics", "hardware",
    "parity_vs_bf16_erf", "bf16_tflops",
    "bf16_mfu", "baseline_cpu_p99_ms", "throughput_seq_per_s",
    "bf16_p99_ms", "tflops", "vs_gpu_baseline", "device_p99_ms",
    "p99_raw_ms", "secondary",
)


def compact_line(full: dict) -> dict:
    """Shrink the full bench record to a driver-parseable headline.

    Deterministic and total: any secondary entry (including error /
    skipped shapes) compacts to a few scalars; the result is re-checked
    against ``COMPACT_BUDGET_BYTES`` and sheds optional fields in
    ``_SHED_ORDER`` until it fits.  The driver contract keys (metric /
    value / unit / vs_baseline) are never shed.
    """
    line = {k: v for k, v in full.items() if k != "secondary"}
    sec = {}
    for name, entry in (full.get("secondary") or {}).items():
        if not isinstance(entry, dict):
            sec[name] = entry
            continue
        keep = {}
        for k in _COMPACT_KEYS.get(name, ()):
            if k in entry:
                keep[k] = entry[k]
        for k in ("error", "skipped"):
            if k in entry and not keep:
                # One-line reason, control chars stripped (the r03 tail
                # carried raw ANSI escapes from a compile-helper 500).
                msg = "".join(
                    ch for ch in str(entry[k]) if ch.isprintable()
                )[:80]
                keep[k] = msg
        if not keep:  # unknown shape: first few scalars, stable order
            for k, v in entry.items():
                if isinstance(v, (int, float)) and len(keep) < 3:
                    keep[k] = v
        sec[name] = keep
    line["secondary"] = sec
    line["detail"] = "BENCH_DETAIL.json"

    for victim in _SHED_ORDER:
        if len(json.dumps(line)) <= COMPACT_BUDGET_BYTES:
            break
        line.pop(victim, None)
    return line


_DETAIL_PATH = os.environ.get(
    "BENCH_DETAIL_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json"),
)

# The record-in-progress.  main() keeps it current after every completed
# phase so the SIGTERM/SIGINT handler (and any late failure path) can
# flush whatever has been measured so far — round 3 lost its record to a
# stdout-tail overflow, round 4 lost it to an external wall-clock kill
# landing before the single end-of-run print (VERDICT r4 missing #1).
_CURRENT: dict | None = None

# Absolute monotonic deadline derived from BENCH_BUDGET_S; benches with
# internal waits consult _remaining() so a slow warm-up cannot eat the
# wall past the point where the record would be lost.
_DEADLINE: float | None = None


def _remaining(default: float = 1e9) -> float:
    if _DEADLINE is None:
        return default
    return max(0.0, _DEADLINE - time.monotonic())


def _write_detail(full: dict) -> None:
    """Rewrite BENCH_DETAIL.json (atomically) with the current record.

    Called after EVERY completed phase, not once at the end: an external
    kill between secondaries must leave the last completed state on
    disk, never a stale or torn file (round 4 committed a pre-fix stale
    one, VERDICT r4 missing #2)."""
    try:
        tmp = _DETAIL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(full, f, indent=1)
            f.write("\n")
        os.replace(tmp, _DETAIL_PATH)
    except OSError as e:
        print(f"could not write {_DETAIL_PATH}: {e}", file=sys.stderr)


def _print_compact(full: dict) -> None:
    """Print the compact driver line to stdout, flushed immediately."""
    out = json.dumps(compact_line(full))
    if len(out) > COMPACT_BUDGET_BYTES + 200:
        # Never crash before printing (a missing line is a total record
        # loss): fall back to the bare driver contract.
        out = json.dumps(
            {k: full.get(k) for k in ("metric", "value", "unit", "vs_baseline")}
            | {"truncated": True, "detail": "BENCH_DETAIL.json"}
        )
    print(out, flush=True)


def emit_record(full: dict) -> None:
    """Persist the full record, then print the compact driver line.

    The driver parses the LAST parseable stdout line; the full record
    goes to ``BENCH_DETAIL.json`` next to this file and to stderr."""
    _write_detail(full)
    print("FULL " + json.dumps(full), file=sys.stderr, flush=True)
    _print_compact(full)


def _flush_on_signal(signum, frame) -> None:
    """Last-gasp flush: persist + print whatever has been measured.

    Installed for SIGTERM/SIGINT in main().  ``timeout(1)`` and the
    driver both deliver SIGTERM before any SIGKILL escalation; emitting
    the current record here turns an external kill into a truncated but
    PARSEABLE run (remaining secondaries read "skipped")."""
    full = _CURRENT
    if full is None:
        # Nothing measured yet (killed during the headline phase) or the
        # final emission already happened: die with conventional signal
        # status so the wrapper sees a killed run, NOT a successful
        # empty one — exit 0 with no record would be a silent loss.
        os._exit(128 + signum)
    for name, entry in (full.get("secondary") or {}).items():
        if entry is None:
            full["secondary"][name] = {
                "skipped": f"killed by signal {signum} mid-bench"
            }
    emit_record(full)
    # os._exit: a jax dispatch may be wedged on the tunnel socket in the
    # main thread's C frame; normal interpreter teardown could block
    # behind it and eat the grace period before SIGKILL.
    os._exit(0)


def main(argv: "list[str] | None" = None) -> None:
    global _CURRENT, _DEADLINE
    import signal

    args = parse_args(argv)
    _validate_scenarios(args.scenarios)
    if args.dry_run:
        dry_run(args.scenarios)
        return
    selected = set(args.scenarios)

    # Wall budget measured from PROCESS START, headline phase included
    # (round 4's default only metered the secondaries and exceeded the
    # driver's kill point).  1100 s default: comfortably under the
    # observed ~20-40 min external ceilings, enough for the headline +
    # cheap secondaries cold; a full-record run sets BENCH_BUDGET_S
    # explicitly.
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1100"))
    t_start = time.monotonic()
    _DEADLINE = t_start + budget_s
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _flush_on_signal)
        except (ValueError, OSError):
            pass  # non-main thread / platform quirk: flush-on-kill is
            # best-effort, the early emission below still stands

    this_module = sys.modules[__name__]
    bench_order = tuple(
        (name, getattr(this_module, attr)) for name, attr in SCENARIOS
    )

    b = bench_bert()
    tpu = b["int8"]
    try:
        ref = bench_torch_cpu()
        vs_baseline = ref[99] / tpu[99]
        baseline_ms = ref[99] * 1000
    except Exception as e:  # torch baseline is best-effort
        print(f"baseline measurement failed: {e}", file=sys.stderr)
        vs_baseline = None
        baseline_ms = None

    line = {
        "metric": "bert_base_b32_s128_p99_batch_latency_per_chip",
        "value": round(tpu[99] * 1000, 3),
        "unit": "ms",
        "vs_baseline": round(vs_baseline, 2) if vs_baseline else None,
        "p50_ms": round(tpu[50] * 1000, 3),
        "p99_raw_ms": round(tpu.get("raw99", tpu[99]) * 1000, 3),
        "numerics": (
            "int8 acts+weights on the MXU s8 path, tanh-GELU (the int8 "
            "serving default; bf16 erf comparison in bf16_p99_ms)"
        ),
        "parity_vs_bf16_erf": b["parity"],
        "bf16_p99_ms": round(b["bf16"][99] * 1000, 3),
        "bf16_p99_raw_ms": round(
            b["bf16"].get("raw99", b["bf16"][99]) * 1000, 3
        ),
        "throughput_seq_per_s": round(BATCH / tpu[50], 1),
        "tflops": round(b["tflops_int8"], 1),
        "mfu_vs_s8_peak": round(b["mfu_int8"], 3),
        "bf16_tflops": round(b["tflops_bf16"], 1),
        "bf16_mfu": round(b["mfu_bf16"], 3),
        "baseline_cpu_p99_ms": round(baseline_ms, 1) if baseline_ms else None,
        # Published GPU anchors (BASELINE.md): >1 = faster than the anchor.
        "vs_gpu_baseline": {
            "t4_int8": round(
                GPU_ANCHORS["bert_b32_s128_t4_int8_ms"] / (tpu[99] * 1000), 2
            ),
            "a100": round(
                GPU_ANCHORS["bert_b32_s128_a100_ms"] / (tpu[99] * 1000), 2
            ),
        },
        "hardware": "TPU v5e (1 chip)",
        "secondary": {name: None for name, _ in bench_order},
    }
    _CURRENT = line

    # FIRST emission, the moment the headline exists: even if every
    # secondary is lost to a kill harder than SIGTERM, this parseable
    # line (BERT p99 + MFU + vs_baseline) is already in the stdout tail.
    emit_record(line)

    for name, fn in bench_order:
        if selected and name not in selected:
            line["secondary"][name] = {"skipped": "not selected"}
            _write_detail(line)
            continue
        if time.monotonic() >= _DEADLINE:
            line["secondary"][name] = {
                "skipped": f"wall budget {budget_s:.0f}s spent"
            }
            _write_detail(line)
            continue
        if name == "llama_7b_decode" and "BENCH_7B_TIMEOUT_S" not in os.environ:
            # The 7B subprocess must die (salvaging its partial ladder)
            # before the overall deadline, not at its own 2400 s default.
            # Under ~3 min of budget there is no point even starting (the
            # load alone exceeds that) and a floor would overshoot the
            # deadline — skip explicitly instead.
            if _remaining() < 180.0:
                line["secondary"][name] = {
                    "skipped": f"{_remaining():.0f}s of budget left, "
                               "under the 7B load cost"
                }
                _write_detail(line)
                continue
            os.environ["BENCH_7B_TIMEOUT_S"] = str(round(_remaining() - 60.0))
        try:
            line["secondary"][name] = fn()
        except Exception as e:
            line["secondary"][name] = {"error": f"{type(e).__name__}: {e}"}
            print(f"secondary bench {name} failed: {e}", file=sys.stderr)
        _write_detail(line)  # incremental: a kill loses at most ONE bench

    line["wall_s"] = round(time.monotonic() - t_start, 1)
    _CURRENT = None
    # FINAL emission: the driver takes the last parseable line.
    emit_record(line)


if __name__ == "__main__":
    main()
