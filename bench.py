"""Benchmark of record (driver contract: prints ONE JSON line).

Headline metric — BERT-base batched-inference p99 latency per chip
(BASELINE.md north star; acceptance config 3), served int8 on the MXU's
native s8 path (models/quantization.dense_q8; bf16 comparison included).
``vs_baseline`` compares against the reference's data plane: the reference
serves models through Seldon's CPU ``MLFLOW_SERVER`` pods (its manifests
request no GPU — ``mlflow_operator.py:193-222``), so the baseline is the
same BERT-base batch on torch/CPU, measured live in this process.  Values
> 1 mean the TPU path is faster.

``secondary`` covers the rest of BASELINE.json's configs and the second
north star:

- ``serve_path_http``  — p50/p99 per REQUEST through the real aiohttp
  server + dynamic batcher (and through the native router in front), not
  raw jit calls: the number the promotion gate actually judges.
- ``time_to_100pct_traffic`` — wall time for a full canary 10%→100% on
  the REAL local data plane (two live servers, C++ router split, gate fed
  by the router's actual histograms) at an accelerated step interval,
  with the policy-sleep floor separated out so the operator overhead is
  visible.  The reference's floor for its default policy is 480 s
  (``mlflow_operator.py:291-296``); ours is policy-bound the same way —
  the overhead line is what the rebuild adds on top (≈0 means parity).
- ``iris_sklearn_linear`` / ``xgboost_forest`` — µs-scale tabular configs.
- ``resnet50_b8`` — image batch latency.
- ``llama_1p35b_decode`` — continuous-batching decode throughput, int8
  weights + windowed attention (models/llama.py, server/generation.py).

Run on the real TPU chip: ``python bench.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _percentiles(samples: list[float], ps=(50, 99)) -> dict[int, float]:
    xs = sorted(samples)
    out = {}
    for p in ps:
        idx = min(len(xs) - 1, max(0, round(p / 100 * (len(xs) - 1))))
        out[p] = xs[idx]
    return out


BATCH = 32
SEQ = 128
PIPELINE = 64  # batches in flight per timed run (amortizes host<->device RTT)
RUNS = 8


def _timed(f, *args, runs: int = 6, inner: int = 100) -> dict[int, float]:
    """Compile, then time ``inner`` pipelined dispatches per sample —
    the shared methodology for every jit-level number here (single-call
    block_until_ready would measure the host<->device tunnel RTT)."""
    f(*args).block_until_ready()
    samples = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = None
        for _ in range(inner):
            out = f(*args)
        out.block_until_ready()
        samples.append((time.perf_counter() - t0) / inner)
    return _percentiles(samples)


def _setup_jax():
    import jax

    try:  # persistent compile cache across rounds
        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    except Exception:
        pass
    return jax


def bench_bert() -> dict:
    """Per-batch latency with PIPELINE batches in flight, int8 and bf16.

    Single-call block_until_ready timing would measure the host<->device
    round trip (65+ ms through a tunnel in dev environments), not the chip.
    A serving process keeps the dispatch queue full, so per-batch latency
    under pipelining is the number that governs throughput and the
    Prometheus histograms the gate reads.

    Numerics: int8 is the headline (dense_q8 feeds the MXU true s8
    operands — compiled HLO shows the packed (4,1) s8 convolution; ~8%
    over bf16 end-to-end, bounded by Amdahl: attention einsums, norms and
    the activation-quant overhead stay bf16/VPU).  Variants measured on
    chip and REJECTED for the bf16 path (b32/s128, p50 per batch): XLA
    einsum attention 7.47 ms beats both a prefolded fused-QKV matmul
    (7.89 ms — XLA already merges the three projections) and the Pallas
    flash kernel (9.56 ms — at s=128 the whole KV fits one block; flash
    wins at 8k, see ops/flash_attention.py).
    """
    jax = _setup_jax()
    import jax.numpy as jnp

    from tpumlops.models import bert
    from tpumlops.models.quantization import quantize_bert

    cfg = bert.BertConfig.base()
    params = bert.init(jax.random.key(0), cfg)
    qparams = quantize_bert(params)
    ids = jax.random.randint(jax.random.key(1), (BATCH, SEQ), 0, cfg.vocab_size)
    mask = jnp.ones((BATCH, SEQ), jnp.int32)

    f = jax.jit(
        lambda p, i, m: bert.classify(p, i, m, cfg=cfg, dtype=jnp.bfloat16)
    )
    q8 = _timed(f, qparams, ids, mask, runs=RUNS, inner=PIPELINE)
    bf16 = _timed(f, params, ids, mask, runs=RUNS, inner=PIPELINE)
    return {"int8": q8, "bf16": bf16}


def bench_torch_cpu(iters: int = 3) -> dict[int, float]:
    import torch
    from transformers import BertConfig as HFConfig
    from transformers import BertForSequenceClassification

    model = BertForSequenceClassification(HFConfig())
    model.eval()
    ids = torch.randint(0, 30000, (BATCH, SEQ))
    with torch.no_grad():
        model(input_ids=ids)  # warmup
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            model(input_ids=ids)
            samples.append(time.perf_counter() - t0)
    return _percentiles(samples)


# ---------------------------------------------------------------------------
# Serve path: HTTP through the real server (+ router), per-request latency
# ---------------------------------------------------------------------------


def bench_serve_path() -> dict:
    """p50/p99 per single-sequence REQUEST through aiohttp + the dynamic
    batcher (BERT-base int8), then the same through the native router —
    the full Seldon-executor-analogue path the gate's PromQL measures."""
    import concurrent.futures
    import tempfile
    import urllib.request

    import numpy as np

    from tpumlops.clients.localplane import free_port, start_model_server
    from tpumlops.models import bert
    from tpumlops.server.loader import save_native_model
    from tpumlops.utils.config import TpuSpec

    jax = _setup_jax()

    cfg = bert.BertConfig.base()
    params = bert.init(jax.random.key(0), cfg)
    art = tempfile.mkdtemp() + "/bert"
    save_native_model(
        art,
        "bert-classifier",
        params,
        config={
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_layers,
            "num_heads": cfg.num_heads,
            "intermediate_size": cfg.intermediate_size,
            "max_position_embeddings": cfg.max_position_embeddings,
            "num_labels": cfg.num_labels,
        },
        # Fixed-length bench traffic: skip the variable-length ladder so
        # server startup warms only the batch buckets at s=128 (the
        # ladder is exercised by tests and the seq-pad drive script).
        builder_kwargs={"seq_len": SEQ, "seq_buckets": False},
    )
    port = free_port()
    handle = start_model_server(
        art,
        "v1",
        port,
        model_name="bert",
        namespace="bench",
        tpu=TpuSpec.from_spec(
            {
                "meshShape": {"tp": 1},
                # 8, not BATCH: each warmed batch bucket is a full XLA
                # compile, and this dev env's remote-compile tunnel does
                # not hit the persistent cache — 4 buckets bound server
                # startup while 8 concurrent clients still fill batches.
                "maxBatchSize": 8,
                "maxBatchDelayMs": 2,
                "quantize": "int8",
            }
        ),
    )

    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, SEQ))
    # Both inputs, matching the engine's warmup examples: the batcher
    # groups by the full input-name/shape key, so an input_ids-only
    # request would form a new group and pay a live XLA compile.
    body = json.dumps(
        {
            "inputs": [
                {
                    "name": "input_ids",
                    "shape": [1, SEQ],
                    "datatype": "INT32",
                    "data": ids.ravel().tolist(),
                },
                {
                    "name": "attention_mask",
                    "shape": [1, SEQ],
                    "datatype": "INT32",
                    "data": [1] * SEQ,
                },
            ]
        }
    ).encode()

    def fire(url: str, n: int, timeout: float = 30.0) -> list[float]:
        lat = []
        for _ in range(n):
            t0 = time.perf_counter()
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": "application/json"}
            )
            urllib.request.urlopen(req, timeout=timeout).read()
            lat.append(time.perf_counter() - t0)
        return lat

    def measure(url: str, clients: int = 8, per_client: int = 12) -> dict:
        # generous first-request timeout: a cold compile cache may still
        # be building an executable
        fire(url, 5, timeout=300.0)
        with concurrent.futures.ThreadPoolExecutor(clients) as ex:
            futs = [ex.submit(fire, url, per_client) for _ in range(clients)]
            lats = [t for f in futs for t in f.result()]
        p = _percentiles(lats)
        return {
            "p50_ms": round(p[50] * 1000, 2),
            "p99_ms": round(p[99] * 1000, 2),
            "requests": len(lats),
        }

    router = None
    try:
        direct = measure(f"http://127.0.0.1:{port}/v2/models/bert/infer")

        # Same requests through the native router (the Istio-split stand-in).
        from tpumlops.clients.router import RouterProcess

        router = RouterProcess(
            port=free_port(),
            backends={"v1": ("127.0.0.1", port, 100)},
            namespace="bench",
        ).start()
        routed = measure(
            f"http://127.0.0.1:{router.port}/v2/models/bert/infer"
        )
    finally:
        if router is not None:
            router.stop()
        handle.stop()
    return {
        "direct": direct,
        "via_router": routed,
        "router_overhead_p50_ms": round(
            routed["p50_ms"] - direct["p50_ms"], 2
        ),
        "clients": 8,
        "batch_per_request": 1,
        "numerics": "int8",
        "note": (
            "this dev environment reaches the chip through a device "
            "tunnel (~65 ms RTT per dispatch) which dominates these "
            "absolutes; on a TPU host the compute floor is the headline "
            "per-batch latency. router_overhead is the env-independent "
            "signal here."
        ),
    }


# ---------------------------------------------------------------------------
# Time-to-100%-traffic on the real local plane
# ---------------------------------------------------------------------------


def bench_time_to_100() -> dict:
    """Full unscripted canary on the local plane: two live iris servers,
    C++ router split, gate reading the router's real histograms.  The
    step interval is accelerated (0.5 s vs the reference's 60 s); the
    policy floor scales with it, so the reported overhead — measured
    minus floor — is interval-independent."""
    import tempfile
    import threading

    from tpumlops.clients.base import ObjectRef
    from tpumlops.clients.fakes import FakeRegistry
    from tpumlops.clients.localplane import (
        SyncingKube,
        TrafficGenerator,
        free_port,
        relaxed_gate_spec,
        start_model_server,
        train_iris_pair,
    )
    from tpumlops.clients.router import (
        RouterMetricsSource,
        RouterProcess,
        RouterSync,
    )
    from tpumlops.operator.runtime import OperatorRuntime
    from tpumlops.utils.clock import SystemClock

    STEP_INTERVAL = 0.5
    root = tempfile.mkdtemp()
    handles = []
    ports = {}
    router = None
    rt = None
    gens = []
    try:
        for tag, uri in train_iris_pair(root).items():
            port = free_port()
            handles.append(
                start_model_server(uri, f"v{tag}", port, namespace="bench")
            )
            ports[f"v{tag}"] = port

        router = RouterProcess(
            port=free_port(), backends={}, namespace="bench"
        ).start()
        sync = RouterSync(router.admin, lambda pred: ("127.0.0.1", ports[pred]))
        kube = SyncingKube(sync)
        registry = FakeRegistry()
        registry.register("iris", "1", "mlflow-artifacts:/1/aaa/artifacts/model")
        registry.set_alias("iris", "prod", "1")
        rt = OperatorRuntime(
            kube,
            registry,
            metrics=RouterMetricsSource(router.admin),
            clock=SystemClock(),
            sync_interval_s=0.05,
        )
        CRREF = ObjectRef(
            namespace="bench",
            name="iris",
            group="mlflow.nizepart.com",
            version="v1alpha1",
            plural="mlflowmodels",
        )
        # Reference POLICY shape: 10% steps from a 90/10 start.
        spec = relaxed_gate_spec(
            step=10,
            stepInterval=STEP_INTERVAL,
            maxAttempts=200,
            initialTraffic=10,
        )
        kube.create(
            CRREF,
            {"metadata": {"name": "iris", "namespace": "bench"}, "spec": spec},
        )

        threading.Thread(target=rt.serve, daemon=True).start()
        for _ in range(4):
            gen = TrafficGenerator(router.port)
            gen.__enter__()
            gens.append(gen)

        def status():
            return kube.get(CRREF).get("status") or {}

        deadline = time.monotonic() + 60
        while status().get("phase") != "Stable" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert status().get("phase") == "Stable", status()

        # Canary: flip the alias, time to Stable at 100%.
        registry.register("iris", "2", "mlflow-artifacts:/1/bbb/artifacts/model")
        registry.set_alias("iris", "prod", "2")
        t0 = time.monotonic()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            s = status()
            if s.get("phase") == "Stable" and s.get("currentModelVersion") == "2":
                break
            time.sleep(0.05)
        measured = time.monotonic() - t0
        s = status()
        assert s.get("phase") == "Stable" and s.get("currentModelVersion") == "2", s
    finally:
        for gen in gens:
            gen.__exit__()
        if rt is not None:
            rt.stop()
        if router is not None:
            router.stop()
        for h in handles:
            h.stop()

    # 9 gate passes take the split 10->100; the first fires immediately,
    # the rest wait out STEP_INTERVAL: floor = 8 * STEP_INTERVAL (+ one
    # monitoringInterval for the alias poll to notice the flip).
    floor = 8 * STEP_INTERVAL + 0.2
    return {
        "measured_s": round(measured, 2),
        "policy_floor_s": round(floor, 2),
        "operator_overhead_s": round(measured - floor, 2),
        "step_interval_s": STEP_INTERVAL,
        "ref_floor_same_policy_s": 480,
        "traffic_split": "native router (smooth WRR), gate on its live histograms",
    }


# ---------------------------------------------------------------------------
# Remaining baseline configs (secondary)
# ---------------------------------------------------------------------------


def bench_iris() -> dict:
    jax = _setup_jax()
    from sklearn.datasets import load_iris
    from sklearn.linear_model import LogisticRegression

    from tpumlops.models import linear

    X, y = load_iris(return_X_y=True)
    sk = LogisticRegression(max_iter=500).fit(X, y)
    params, cfg = linear.from_sklearn(sk)
    x = jax.numpy.asarray(X[:32], jax.numpy.float32)
    p = _timed(jax.jit(lambda x: linear.predict(params, x, cfg)), x, inner=200)
    return {"p50_us": round(p[50] * 1e6, 1), "batch": 32}


def bench_xgboost() -> dict:
    """Synthetic 200-tree depth-6 regression forest via the JSON path,
    lowered by tabular.lower_forest — normally the GEMM (matmul) form,
    ~11x the gather traversal on v5e; eval_form reports which ran."""
    jax = _setup_jax()
    import numpy as np

    from tpumlops.models import tabular

    rng = np.random.default_rng(0)
    n_feat, depth, n_trees = 16, 6, 200
    n_nodes = 2 ** (depth + 1) - 1
    n_internal = 2**depth - 1
    trees = []
    for _ in range(n_trees):
        left = [2 * i + 1 if i < n_internal else -1 for i in range(n_nodes)]
        right = [2 * i + 2 if i < n_internal else -1 for i in range(n_nodes)]
        trees.append(
            {
                "left_children": left,
                "right_children": right,
                "split_indices": rng.integers(0, n_feat, n_nodes).tolist(),
                "split_conditions": rng.normal(size=n_nodes).astype(float).tolist(),
                "default_left": [1] * n_nodes,
                "tree_param": {
                    "num_nodes": str(n_nodes),
                    "size_leaf_vector": "1",
                },
            }
        )
    model = {
        "learner": {
            "gradient_booster": {
                "model": {"trees": trees, "tree_info": [0] * n_trees},
                "name": "gbtree",
            },
            "learner_model_param": {
                "base_score": "0.0",
                "num_class": "0",
                "num_feature": str(n_feat),
            },
            "objective": {"name": "reg:squarederror"},
        }
    }
    arrs, _obj = tabular.from_xgboost_json(model)
    fn, form = tabular.lower_forest(arrs)
    x = jax.numpy.asarray(rng.normal(size=(256, n_feat)), jax.numpy.float32)
    p = _timed(jax.jit(fn), x)
    return {
        "p50_us": round(p[50] * 1e6, 1),
        "trees": n_trees,
        "batch": 256,
        "eval_form": form,
    }


def bench_resnet() -> dict:
    jax = _setup_jax()
    import jax.numpy as jnp

    from tpumlops.models import resnet

    cfg = resnet.ResNetConfig.resnet50()
    params = resnet.init(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (8, 224, 224, 3), jnp.bfloat16)
    p = _timed(jax.jit(lambda p, x: resnet.forward(p, x, cfg)), params, x, inner=32)
    return {
        "p50_ms": round(p[50] * 1000, 3),
        "img_per_s": round(8 / p[50], 1),
        "batch": 8,
    }


def bench_llama_decode() -> dict:
    """Continuous-batching decode tok/s at a 1.35B shape: int8 weights +
    windowed attention (the round-1 on-chip recipe), 8 active slots at
    position ~256, capacity 1024."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from tpumlops.models import llama
    from tpumlops.server.generation import GenerationEngine

    cfg = llama.LlamaConfig(
        vocab_size=32000,
        hidden_size=2048,
        num_layers=24,
        num_heads=16,
        num_kv_heads=16,
        intermediate_size=5632,
        max_seq=1024,
    )
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    from tpumlops.models.quantization import quantize_llama

    params = quantize_llama(params)

    step_samples: list[tuple[int, float]] = []
    engine = GenerationEngine(
        params,
        cfg,
        max_slots=8,
        dtype=jnp.bfloat16,
        on_step=lambda active, dt: step_samples.append((active, dt)),
    )
    engine.start(warmup=True)
    try:
        prompt = np.ones((256,), np.int32).tolist()
        futs = [engine.submit(prompt, 60) for _ in range(8)]
        for f in futs:
            f.result(timeout=600)
    finally:
        engine.shutdown()
    full = [(a, dt) for a, dt in step_samples if a == 8]
    toks = sum(a for a, _ in full)
    secs = sum(dt for _, dt in full)
    engine_tok_s = round(toks / secs, 1) if secs else None

    # Device decode throughput: chained decode steps with NO host sync
    # between ticks.  The engine number above includes a host round trip
    # per tick (it must read the token to schedule) — through this dev
    # environment's device tunnel that RTT is ~60 ms and dominates; on a
    # real TPU host it is microseconds, so the device-loop number is the
    # production-relevant one and matches round 1's methodology.
    cache = llama.RaggedKVCache.create(cfg, 8, jnp.bfloat16)
    cache = cache._replace(lengths=jnp.full((8,), 256, jnp.int32))
    toks0 = jnp.ones((8, 1), jnp.int32)

    @jax.jit
    def step(params, toks, cache):
        logits, cache = llama.decode_ragged(
            params, toks, cache, cfg, window=512
        )
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
        return nxt, cache

    t, c = step(params, toks0, cache)  # compile
    t.block_until_ready()
    N = 100
    t0 = time.perf_counter()
    for _ in range(N):
        t, c = step(params, t, c)
    t.block_until_ready()
    dev_secs = (time.perf_counter() - t0) / N
    return {
        "device_tok_per_s": round(8 / dev_secs, 1),
        "ms_per_step": round(dev_secs * 1000, 2),
        "engine_tok_per_s_tunnel_rtt_bound": engine_tok_s,
        "slots": 8,
        "params_b": 1.35,
        "numerics": "int8 weights + windowed decode (window=512)",
        "full_batch_steps": len(full),
    }


def main() -> None:
    b = bench_bert()
    tpu = b["int8"]
    try:
        ref = bench_torch_cpu()
        vs_baseline = ref[99] / tpu[99]
        baseline_ms = ref[99] * 1000
    except Exception as e:  # torch baseline is best-effort
        print(f"baseline measurement failed: {e}", file=sys.stderr)
        vs_baseline = None
        baseline_ms = None

    # Cheap first, compile-heavy last, under a wall budget: this dev
    # env's remote-compile tunnel misses the persistent cache, so every
    # warmed bucket is a real compile and the expensive benches can eat
    # tens of minutes cold.  Past the budget the remaining entries are
    # marked skipped — the headline line must always print.
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "900"))
    t_start = time.monotonic()
    secondary = {}
    for name, fn in (
        ("time_to_100pct_traffic", bench_time_to_100),
        ("iris_sklearn_linear", bench_iris),
        ("xgboost_forest", bench_xgboost),
        ("resnet50_b8", bench_resnet),
        ("llama_1p35b_decode", bench_llama_decode),
        ("serve_path_http", bench_serve_path),
    ):
        if time.monotonic() - t_start > budget_s:
            secondary[name] = {"skipped": f"wall budget {budget_s:.0f}s spent"}
            continue
        try:
            secondary[name] = fn()
        except Exception as e:
            secondary[name] = {"error": f"{type(e).__name__}: {e}"}
            print(f"secondary bench {name} failed: {e}", file=sys.stderr)

    line = {
        "metric": "bert_base_b32_s128_p99_batch_latency_per_chip",
        "value": round(tpu[99] * 1000, 3),
        "unit": "ms",
        "vs_baseline": round(vs_baseline, 2) if vs_baseline else None,
        "p50_ms": round(tpu[50] * 1000, 3),
        "numerics": "int8 (MXU s8 path; bf16 comparison in bf16_p99_ms)",
        "bf16_p99_ms": round(b["bf16"][99] * 1000, 3),
        "throughput_seq_per_s": round(BATCH / tpu[50], 1),
        "baseline_cpu_p99_ms": round(baseline_ms, 1) if baseline_ms else None,
        "hardware": "TPU v5e (1 chip)",
        "secondary": secondary,
    }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
