"""Short import alias for the framework package.

``import tpumlops`` (and any submodule, e.g. ``tpumlops.operator.state``)
resolves to the *same module objects* as
``research_and_development_of_kubernetes_operator_for_machine_learning_pipelines_tpu``:
the top level is aliased via ``sys.modules`` and submodules via a meta-path
finder, so enum/class identity and module-level state are shared between the
two names.
"""

import importlib
import importlib.abc
import importlib.util
import sys

_SHORT = __name__
_REAL = "research_and_development_of_kubernetes_operator_for_machine_learning_pipelines_tpu"


class _AliasLoader(importlib.abc.Loader):
    def __init__(self, real_name: str):
        self._real = real_name
        self._orig_spec = None
        self._orig_path = None
        self._had_path = False

    def create_module(self, spec):
        # Returning the already-imported real module makes the import system
        # bind the alias name to the identical object.
        mod = importlib.import_module(self._real)
        self._orig_spec = getattr(mod, "__spec__", None)
        self._had_path = hasattr(mod, "__path__")
        self._orig_path = getattr(mod, "__path__", None)
        return mod

    def exec_module(self, module):
        # The import system stamps the alias spec (and, because the alias
        # spec claims is_package, an empty __path__) onto the module;
        # restore both so the real module stays internally consistent.
        if self._orig_spec is not None:
            module.__spec__ = self._orig_spec
        if self._had_path:
            module.__path__ = self._orig_path
        elif hasattr(module, "__path__"):
            del module.__path__


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if fullname.startswith(_SHORT + "."):
            real = _REAL + fullname[len(_SHORT):]
            if fullname.rsplit(".", 1)[-1] == "__main__":
                # runpy (``python -m tpumlops.server``) needs a loader with
                # get_code(); hand it the real module's own source spec —
                # identity aliasing is irrelevant for an entrypoint script.
                real_spec = importlib.util.find_spec(real)
                if real_spec is not None:
                    return importlib.util.spec_from_file_location(
                        fullname, real_spec.origin
                    )
                return None
            return importlib.util.spec_from_loader(
                fullname, _AliasLoader(real), is_package=True
            )
        return None


if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
    sys.meta_path.insert(0, _AliasFinder())

_pkg = importlib.import_module(_REAL)
sys.modules[_SHORT] = _pkg
