# Build + deploy entry points.  The reference ships one prebuilt image
# (nizepart/mlflow-operator:latest, README.md:32); this framework builds
# its three first-party images from source.

# bash, not sh: the verify recipe needs pipefail/PIPESTATUS (dash has
# neither and dies on `set -o pipefail`).
SHELL    := /bin/bash

PKG      := research_and_development_of_kubernetes_operator_for_machine_learning_pipelines_tpu
REGISTRY ?= tpumlops
TAG      ?= latest
DOCKER   ?= docker

.PHONY: images operator-image server-image router-image router-bin \
        install uninstall test test-fast test-e2e test-all lint \
        bench-contract metrics-contract compile-budget plan-contract \
        bench-history metrics-catalog verify bench

images: operator-image server-image router-image

operator-image:
	$(DOCKER) build -f $(PKG)/deploy/docker/Dockerfile.operator \
	  -t $(REGISTRY)/operator:$(TAG) .

server-image:
	$(DOCKER) build -f $(PKG)/deploy/docker/Dockerfile.server \
	  -t $(REGISTRY)/jax-server:$(TAG) .

router-image:
	$(DOCKER) build -f $(PKG)/deploy/docker/Dockerfile.router \
	  -t $(REGISTRY)/router:$(TAG) .

# Local (no docker): compile the native router with the system toolchain.
router-bin:
	mkdir -p build
	g++ -O2 -std=c++17 -Wall -o build/router $(PKG)/native/router.cc

# Cluster install, mirroring the reference's README steps (:25-64):
# CRD -> RBAC -> operator Deployment.  Assumes the mlflow-creds secret
# exists in tpumlops-system (MLFLOW_TRACKING_URI + credentials).
install:
	kubectl apply -f $(PKG)/deploy/crd.yaml
	kubectl apply -f $(PKG)/deploy/rbac.yaml
	kubectl apply -f $(PKG)/deploy/operator-deployment.yaml

uninstall:
	kubectl delete -f $(PKG)/deploy/operator-deployment.yaml --ignore-not-found
	kubectl delete -f $(PKG)/deploy/rbac.yaml --ignore-not-found
	kubectl delete -f $(PKG)/deploy/crd.yaml --ignore-not-found

# Cost tranches (VERDICT r3 #10): `test-fast` is the unit core (~3 min);
# `test-all` adds the e2e (live servers / envtest apiserver) and slow
# (compile- and subprocess-heavy) tranches — the full suite exceeds a
# 10-minute wall in remote-compile environments.
test: test-fast

test-fast:
	python -m pytest tests/ -x -q -m "not e2e and not slow"

test-e2e:
	python -m pytest tests/ -x -q -m "e2e or slow"

test-all:
	python -m pytest tests/ -x -q

# Ruff (config in pyproject.toml [tool.ruff]): pyflakes/pycodestyle
# error classes over the first-party tree.  Soft dependency — the
# serving image does not bake a linter, so environments without ruff
# skip with a notice instead of failing verify (CI images install it:
# `pip install ruff`).
lint:
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check .; \
	else \
	  echo "lint: ruff not installed; skipping (pip install ruff)"; \
	fi

# Bench driver-contract gate: a --dry-run invocation (validates the
# scenario registry and prints the schema contract without touching a
# device) plus the contract tests that pin it — scenario schema drift
# fails HERE, locally, instead of surfacing as a missing field in a
# round's official record.
bench-contract:
	python bench.py --dry-run > /dev/null
	python -m pytest tests/test_bench_contract.py -q

# Metric-identity contract gate (SURVEY §7 hard part 4): the promotion
# gate's PromQL — and every dashboard/alert — reads these exact family
# names and label sets.  An accidental rename must fail HERE, locally,
# not as a gate query silently reading 0 through its vector(0) fallback.
metrics-contract:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_metrics_contract.py -q

# The EXACT tier-1 command from ROADMAP.md (the driver's acceptance
# gate) chained behind lint + the bench contract: not-slow tranche,
# collection errors tolerated, 870 s wall cap, DOTS_PASSED echoed from
# the captured dot lines.
# Compile-budget regression gate (ISSUE 16): the unified super-step
# engine's whole point is a small program space.  Runs both warmup
# sweeps on the tiny model with the compile observatory attached and
# fails if the unified jit-variant count, the legacy/unified collapse
# ratio, or the compile-seconds total regresses past the committed
# budget in COMPILE_BUDGET.json.
compile-budget:
	env JAX_PLATFORMS=cpu python scripts/check_compile_budget.py

# Plan-contract gate (ISSUE 18): the offline SLO planner's output is a
# pure function of (trace, objective, cost model, grid) — re-planning
# the committed fixture trace must reproduce the committed plan JSON
# byte-for-byte.  Cost-model drift fails HERE, locally, instead of
# silently re-shaping fleets the next time a CR's planner runs.
plan-contract:
	env JAX_PLATFORMS=cpu python scripts/plan.py --dry-run \
	  --expect tests/fixtures/journey_plan.json > /dev/null

# Bench regression sentinel (ISSUE 20): every committed BENCH_*.json's
# headline keys versus their last BENCH_HISTORY.jsonl revision — a
# silent tok/s or collapse-ratio regression fails here, in the diff.
bench-history:
	python scripts/check_bench_history.py

# Metrics-catalog lint (ISSUE 20): the three OBSERVABILITY.md series
# tables must enumerate EXACTLY the families the server / operator /
# router planes export — both directions.
metrics-catalog:
	env JAX_PLATFORMS=cpu python scripts/check_metrics_catalog.py

verify: lint bench-contract metrics-contract compile-budget plan-contract \
        bench-history metrics-catalog
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 1150 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

bench:
	python bench.py
