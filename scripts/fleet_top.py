"""fleet_top — live terminal view of the fleet anomaly observatory.

Renders ``GET /debug/fleet-overview`` from the operator's metrics
listener (``--metrics-port`` + ``--fleet-trace-sources``): one row per
fleet source with a sparkline of its recent ring samples, the latest
headline numbers, the operator's anomaly verdict for that replica, and
the router's circuit view of each backend.  A dark source renders as a
``DARK`` row — with the observatory, a replica that stops answering IS
the finding, not a rendering error.

No dependencies beyond the standard library (urllib + ANSI escapes), so
it runs anywhere the operator port is reachable:

    python scripts/fleet_top.py --url http://127.0.0.1:8080
    python scripts/fleet_top.py --url ... --once          # one frame
    python scripts/fleet_top.py --url ... --once --json   # raw payload

Sparklines show the newest ``--width`` ring buckets oldest→newest,
scaled to the row's own max (the number printed beside the line).
Replica rows plot per-second ITL p99 ms; router backend rows plot
proxy-leg p99 ms.  ``·`` marks a second with no samples.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(vals: list, width: int) -> str:
    """Newest ``width`` values, None = no-sample dot, scaled to max."""
    vals = vals[-width:]
    present = [v for v in vals if v is not None]
    top = max(present) if present else 0.0
    out = []
    for v in vals:
        if v is None:
            out.append("·")
        elif top <= 0:
            out.append(BLOCKS[0])
        else:
            out.append(BLOCKS[min(len(BLOCKS) - 1, int(v / top * (len(BLOCKS) - 1)))])
    return "".join(out).ljust(width, " ")


def replica_row(snapshot: dict | None, width: int) -> tuple[str, str]:
    """(sparkline, headline) for a server ring snapshot."""
    if not snapshot:
        return "·" * width, "ring off"
    samples = snapshot.get("samples") or []
    itl = [
        (s["itl"]["p99_ms"] if s.get("itl", {}).get("n") else None) for s in samples
    ]
    last = samples[-1] if samples else {}
    parts = []
    present = [v for v in itl if v is not None]
    if present:
        parts.append(f"itl p99 {present[-1]:.1f}ms (max {max(present):.1f})")
    if last.get("mfu") is not None:
        parts.append(f"mfu {last['mfu']:.2f}")
    if last.get("queue_depth") is not None:
        parts.append(f"q {last['queue_depth']}")
    shed = sum(s.get("shed", 0) for s in samples)
    if shed:
        parts.append(f"shed {shed}")
    return sparkline(itl, width), "  ".join(parts) or "idle"


def backend_rows(snapshot: dict | None, width: int) -> list[tuple[str, str, str]]:
    """[(backend, sparkline, headline)] for a router ring snapshot."""
    if not snapshot:
        return []
    rows = []
    for name, ring in sorted((snapshot.get("backends") or {}).items()):
        samples = ring.get("samples") or []
        legs = [(s["p99_ms"] if s.get("n") else None) for s in samples]
        parts = []
        present = [v for v in legs if v is not None]
        if present:
            parts.append(f"leg p99 {present[-1]:.1f}ms (max {max(present):.1f})")
        errors = sum(s.get("errors", 0) for s in samples)
        failovers = sum(s.get("failovers", 0) for s in samples)
        if errors:
            parts.append(f"err {errors}")
        if failovers:
            parts.append(f"fo {failovers}")
        rows.append((name, sparkline(legs, width), "  ".join(parts) or "idle"))
    return rows


def verdict_index(overview: dict) -> dict[str, list[str]]:
    """replica/backend name -> compact verdict strings, across models."""
    out: dict[str, list[str]] = {}
    for model, mv in sorted((overview.get("models") or {}).items()):
        for v in mv.get("anomalies") or []:
            arrow = "↑" if v.get("direction") == "high" else "↓"
            tag = f"{v['kind'].upper()} {v.get('series', '?')}{arrow}"
            if v.get("z") is not None:
                tag += f" z={v['z']:.1f}"
            if v.get("driftPct") is not None:
                tag += f" {v['driftPct']:+.0f}%"
            out.setdefault(v["replica"], []).append(tag)
    return out


def render(overview: dict, width: int) -> str:
    verdicts = verdict_index(overview)
    lines = []
    models = overview.get("models") or {}
    for model, mv in sorted(models.items()):
        n = len(mv.get("anomalies") or [])
        mux = mv.get("multiplex") or {}
        mux_note = f"  mux={mux.get('attached', mux)}" if mux else ""
        lines.append(f"model {model}: {n} verdict(s){mux_note}")
    if not models:
        lines.append("no CRs with spec.anomaly published yet")
    lines.append("")
    # /debug/fleet-overview serves sources as a name-keyed dict; accept
    # a list of {"name": ...} dicts too so saved payloads replay.
    raw = overview.get("sources") or {}
    if isinstance(raw, dict):
        sources = sorted(raw.items())
    else:
        sources = [(s.get("name", "?"), s) for s in raw]
    name_w = max([12] + [len(name) + 4 for name, _ in sources])
    for name, src in sources:
        kind = src.get("kind", "replica")
        if src.get("error"):
            lines.append(
                f"{name:<{name_w}} {'DARK':<{width}} {src['error']}"
            )
            continue
        if kind == "router":
            lines.append(f"{name:<{name_w}} [router]")
            circuits = src.get("circuits") or {}
            for backend, line, head in backend_rows(src.get("timeseries"), width):
                circ = circuits.get(backend, {})
                mark = "✓" if circ.get("healthy", True) else "✗OPEN"
                flag = "  ".join(verdicts.get(backend, []))
                lines.append(
                    f"  {backend:<{name_w - 2}} {line} {mark:<5} {head}"
                    + (f"  << {flag}" if flag else "")
                )
        else:
            line, head = replica_row(src.get("timeseries"), width)
            flag = "  ".join(verdicts.get(name, []))
            lines.append(
                f"{name:<{name_w}} {line} {head}" + (f"  << {flag}" if flag else "")
            )
    return "\n".join(lines)


def fetch(url: str, timeout: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser("fleet_top")
    ap.add_argument(
        "--url",
        default="http://127.0.0.1:8080",
        help="operator metrics listener base URL",
    )
    ap.add_argument("--interval", type=float, default=2.0, help="refresh seconds")
    ap.add_argument("--width", type=int, default=32, help="sparkline buckets shown")
    ap.add_argument("--once", action="store_true", help="render one frame and exit")
    ap.add_argument(
        "--json",
        action="store_true",
        help="with --once: print the raw /debug/fleet-overview payload",
    )
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    endpoint = args.url.rstrip("/") + "/debug/fleet-overview"
    while True:
        try:
            overview = fetch(endpoint, args.timeout)
        except urllib.error.HTTPError as e:
            print(f"fleet_top: {endpoint}: HTTP {e.code}: {e.read().decode()!r}",
                  file=sys.stderr)
            return 1
        except Exception as e:
            print(f"fleet_top: {endpoint}: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(overview, indent=2))
        else:
            frame = render(overview, args.width)
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                frame = f"fleet_top  {time.strftime('%H:%M:%S')}\n\n" + frame
            print(frame, flush=True)
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
