"""Ablation round 2: price the attention core and GELU on the int8 path."""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")

from tpumlops.models import bert
from tpumlops.models.quantization import quantize_bert

BATCH, SEQ = 32, 128
RUNS, INNER = 6, 64


def timed(f, *args):
    f(*args).block_until_ready()
    samples = []
    for _ in range(RUNS):
        t0 = time.perf_counter()
        out = None
        for _ in range(INNER):
            out = f(*args)
        out.block_until_ready()
        samples.append((time.perf_counter() - t0) / INNER)
    return min(samples)


results: dict = {}
cfg = bert.BertConfig.base()
params = bert.init(jax.random.key(0), cfg)
qparams = quantize_bert(params)
ids = jax.random.randint(jax.random.key(1), (BATCH, SEQ), 0, cfg.vocab_size)
mask = jnp.ones((BATCH, SEQ), jnp.int32)


def run(name):
    g = jax.jit(lambda p, i, m: bert.classify(p, i, m, cfg=cfg, dtype=jnp.bfloat16))
    results[name] = timed(g, qparams, ids, mask) * 1e3
    print(name, results[name], flush=True)


run("full_int8_ms")

_orig_attn = bert._self_attention
_orig_gelu = bert.gelu


def _attn_passthrough(p, x, mask_bias, cfg):
    # QKV+O projections kept (they're in the GEMM budget); the attention
    # core (scores einsum + softmax + ctx einsum) replaced by identity-v.
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    bert._dense(x, p["q"])
    bert._dense(x, p["k"])
    v = bert._dense(x, p["v"]).reshape(b, s, nh, hd)
    return bert._dense(v.reshape(b, s, h), p["o"])


bert._self_attention = _attn_passthrough
run("ablate_attn_core_ms")
bert._self_attention = _orig_attn

bert.gelu = lambda x: x
run("ablate_gelu_ms")

bert.gelu = lambda x: jax.nn.gelu(x, approximate=True)
run("gelu_tanh_ms")
bert.gelu = _orig_gelu


# Attention core restructured: merge (b, n) into one leading batch dim so
# the two attention matmuls are plain 3-D batched GEMMs, softmax in bf16
# with explicit max-sub (numerics: scores are post-scale, small range).
def _attn_merged(p, x, mask_bias, cfg):
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    q = bert._dense(x, p["q"]).reshape(b, s, nh, hd)
    k = bert._dense(x, p["k"]).reshape(b, s, nh, hd)
    v = bert._dense(x, p["v"]).reshape(b, s, nh, hd)
    q = q.transpose(0, 2, 1, 3).reshape(b * nh, s, hd)
    k = k.transpose(0, 2, 1, 3).reshape(b * nh, s, hd)
    v = v.transpose(0, 2, 1, 3).reshape(b * nh, s, hd)
    scores = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
    ) / jnp.float32(hd**0.5)
    scores = scores.reshape(b, nh, s, s) + mask_bias
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype).reshape(b * nh, s, s)
    ctx = jax.lax.dot_general(probs, v, (((2,), (1,)), ((0,), (0,))))
    ctx = ctx.reshape(b, nh, s, hd).transpose(0, 2, 1, 3).reshape(b, s, h)
    return bert._dense(ctx, p["o"])


bert._self_attention = _attn_merged
run("attn_merged_bn_ms")
bert._self_attention = _orig_attn

print(json.dumps({k: round(v, 3) for k, v in results.items()}))
