"""Offline SLO planner CLI (the scriptable face of ``spec.planner``).

Replays a journey-ring trace (``GET /router/debug/requests`` export, or
the committed fixture) through the analytic cost model and prints the
cheapest knob configuration meeting the objective as JSON — exactly the
dict the reconciler writes to ``status.plan``.

``make verify`` runs this as the ``plan-contract`` step: ``--dry-run
--expect tests/fixtures/journey_plan.json`` re-plans the committed
fixture trace and fails on ANY byte drift from the committed plan, so a
cost-model change must re-commit the fixture plan (and say why) instead
of silently re-shaping fleets.

Usage:
    python scripts/plan.py --trace export.json --objective-ttft-p99-ms 250
    python scripts/plan.py --dry-run --expect tests/fixtures/journey_plan.json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

DEFAULT_TRACE = "tests/fixtures/journey_trace.json"
DEFAULT_OBJECTIVE_MS = 250.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--trace", default=DEFAULT_TRACE,
        help="journey trace: a /router/debug/requests export on disk",
    )
    ap.add_argument(
        "--objective-ttft-p99-ms", type=float,
        default=DEFAULT_OBJECTIVE_MS,
        help="the interactive-class TTFT p99 objective the plan must meet",
    )
    ap.add_argument(
        "--chips", type=int, default=8,
        help="chips the topology provides (bounds tp * replicas)",
    )
    ap.add_argument(
        "--seed", type=int, default=0,
        help="recorded in the plan for provenance (the search is "
        "exhaustive and deterministic; the seed changes nothing)",
    )
    ap.add_argument(
        "--dry-run", action="store_true",
        help="plan and print only — never touches a cluster (currently "
        "the only mode; the flag exists for CLI-contract parity with "
        "bench.py)",
    )
    ap.add_argument(
        "--expect",
        help="path to a committed plan JSON; exit 1 if the computed "
        "plan differs byte-for-byte (the plan-contract CI gate)",
    )
    args = ap.parse_args()

    from tpumlops.operator import planner
    from tpumlops.utils.journey_trace import (
        TraceFormatError,
        load_journey_trace,
    )

    try:
        trace = load_journey_trace(args.trace)
        result = planner.plan(
            trace,
            {"ttftP99Ms": args.objective_ttft_p99_ms},
            chips_available=args.chips,
            seed=args.seed,
        )
    except (TraceFormatError, ValueError) as e:
        print(f"plan: {e}", file=sys.stderr)
        return 2
    text = json.dumps(result, indent=1, sort_keys=True) + "\n"
    sys.stdout.write(text)
    if args.expect:
        expected = Path(args.expect).read_text()
        if text != expected:
            print(
                f"plan-contract FAILED: computed plan differs from "
                f"{args.expect} — the cost model or grid drifted; if "
                "intentional, re-commit the fixture plan",
                file=sys.stderr,
            )
            return 1
        print(f"plan-contract OK ({args.expect})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
