"""Compile-budget regression gate (``make verify`` -> ``compile-budget``).

The unified super-step engine's whole point is a SMALL program space:
one jit variant per (window-bucket x sampling-mode) instead of the
legacy decode/verify/multistep/packed cross-product.  This gate runs
both warmup sweeps on the tiny CPU model with the compile observatory
attached and fails if:

- the unified sweep's jit-variant count exceeds the committed budget,
- the legacy/unified collapse ratio drops below the committed floor
  (the ISSUE 16 acceptance bar: >= 3x at decodeSteps=4 + speculative +
  packed prefill), or
- the unified sweep's ``tpumlops_compile_seconds`` total exceeds the
  committed ceiling (generous — CPU XLA walls vary; the count is the
  tight contract, the seconds bound catches pathological blowups).

Budgets live in COMPILE_BUDGET.json at the repo root, next to the bench
records.  A legitimate program-space change (a new window bucket, a new
sampling mode) updates that file in the same PR, with the new inventory
visible in the diff.

Usage: ``env JAX_PLATFORMS=cpu python scripts/check_compile_budget.py``
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

# 8 virtual CPU devices BEFORE jax initializes: the dp/sp sweeps below
# build real multi-device meshes (same trick as tests/conftest.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_ROOT))

BUDGET_PATH = _ROOT / "COMPILE_BUDGET.json"


def _sweep(unified: bool, mesh_shape: dict | None = None,
           sp_prefill_threshold: int = 1024) -> dict:
    import jax
    import jax.numpy as jnp

    from tpumlops.models import llama, partition
    from tpumlops.server.device_telemetry import DeviceTelemetry
    from tpumlops.server.generation import GenerationEngine
    from tpumlops.server.speculative import SpeculativeConfig

    cfg = llama.LlamaConfig.tiny(max_seq=64)
    params = llama.init(jax.random.key(0), cfg, dtype=jnp.float32)
    if mesh_shape:
        mesh = partition.build_serving_mesh(mesh_shape)
        params = partition.shard_llama_params(params, mesh)
    telemetry = DeviceTelemetry()
    engine = GenerationEngine(
        params, cfg, max_slots=4, dtype=jnp.float32, decode_steps=4,
        speculative=SpeculativeConfig(
            enabled=True, draft_tokens=2, ngram_min=1, ngram_max=4,
            adaptive=True,
        ),
        prefill_chunk=8, prefill_batch=4,
        unified_step=unified, telemetry=telemetry,
        mesh_shape=mesh_shape,
        sp_prefill_threshold=sp_prefill_threshold,
    )
    engine.start(warmup=True)
    engine.shutdown()
    return telemetry.observatory.snapshot()["warmup"]


def main() -> int:
    budget = json.loads(BUDGET_PATH.read_text())
    legacy = _sweep(unified=False)
    unified = _sweep(unified=True)
    # dp shards the EXISTING programs' row axis — zero new variants
    # allowed.  sp adds the ring-prefill bucket ladder (+ the shared
    # [1, V] insert variant), a bounded count pinned here so the sp
    # axis cannot silently regrow the PR 16 collapse.
    dp = _sweep(unified=True, mesh_shape={"dp": 2, "tp": 1})
    sp = _sweep(
        unified=True, mesh_shape={"sp": 2}, sp_prefill_threshold=32
    )
    ratio = legacy["compiles"] / max(1, unified["compiles"])
    print(
        f"compile-budget: legacy={legacy['compiles']} "
        f"({legacy['seconds']:.1f}s) {legacy['ops']}"
    )
    print(
        f"compile-budget: unified={unified['compiles']} "
        f"({unified['seconds']:.1f}s) {unified['ops']} "
        f"ratio={ratio:.2f}"
    )
    dp_extra = dp["compiles"] - unified["compiles"]
    sp_extra = sp["compiles"] - unified["compiles"]
    print(
        f"compile-budget: dp2={dp['compiles']} (extra {dp_extra}) "
        f"{dp['ops']}"
    )
    print(
        f"compile-budget: sp2={sp['compiles']} (extra {sp_extra}) "
        f"{sp['ops']}"
    )
    failures = []
    if dp_extra > budget["max_dp_extra_compiles"]:
        failures.append(
            f"dp=2 adds {dp_extra} jit variants over the unified sweep "
            f"(budget {budget['max_dp_extra_compiles']}: dp must reshard "
            "existing programs, not mint new ones)"
        )
    if sp_extra > budget["max_sp_extra_compiles"]:
        failures.append(
            f"sp=2 adds {sp_extra} jit variants over the unified sweep, "
            f"budget {budget['max_sp_extra_compiles']}"
        )
    if unified["compiles"] > budget["max_unified_compiles"]:
        failures.append(
            f"unified jit-variant count {unified['compiles']} exceeds "
            f"budget {budget['max_unified_compiles']}"
        )
    if ratio < budget["min_collapse_ratio"]:
        failures.append(
            f"legacy/unified collapse ratio {ratio:.2f} below floor "
            f"{budget['min_collapse_ratio']}"
        )
    if unified["seconds"] > budget["max_unified_compile_seconds"]:
        failures.append(
            f"unified compile seconds {unified['seconds']:.1f} exceed "
            f"ceiling {budget['max_unified_compile_seconds']}"
        )
    if failures:
        for f in failures:
            print(f"compile-budget: FAIL: {f}", file=sys.stderr)
        print(
            "compile-budget: a legitimate program-space change must "
            "update COMPILE_BUDGET.json in the same PR",
            file=sys.stderr,
        )
        return 1
    print("compile-budget: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
