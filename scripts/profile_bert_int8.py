"""Ablation profile of the int8 BERT-base classify path (VERDICT r2 item 1).

Answers: where does the non-matmul half of the int8 batch go?  Each probe
is timed with the bench's pipelined-dispatch methodology (bench.py _timed;
single-call timing would measure the ~65 ms device tunnel, not the chip).

Probes
  1. bf16 / int8 full classify                  — the numbers of record
  2. raw GEMM ladders at the exact layer shapes — achievable MXU ceiling
     (bf16, s8 pre-quantized operands, s8 with on-the-fly act quant)
  3. model ablations: no-layernorm, no-softmax, f32-vs-bf16 softmax,
     attention-einsums-in-int8
Prints one JSON dict at the end.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")

from tpumlops.models import bert
from tpumlops.models.quantization import dense_q8, quantize_bert, quantize_tensor

BATCH, SEQ = 32, 128
RUNS, INNER = 6, 64


def timed(f, *args, runs=RUNS, inner=INNER):
    f(*args)[0].block_until_ready() if isinstance(f(*args), tuple) else f(
        *args
    ).block_until_ready()
    samples = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = None
        for _ in range(inner):
            out = f(*args)
        (out[0] if isinstance(out, tuple) else out).block_until_ready()
        samples.append((time.perf_counter() - t0) / inner)
    return min(samples)  # min: least host-noise estimate of steady state


results: dict = {}

cfg = bert.BertConfig.base()
params = bert.init(jax.random.key(0), cfg)
qparams = quantize_bert(params)
ids = jax.random.randint(jax.random.key(1), (BATCH, SEQ), 0, cfg.vocab_size)
mask = jnp.ones((BATCH, SEQ), jnp.int32)

f = jax.jit(lambda p, i, m: bert.classify(p, i, m, cfg=cfg, dtype=jnp.bfloat16))
results["full_int8_ms"] = timed(f, qparams, ids, mask) * 1e3
results["full_bf16_ms"] = timed(f, params, ids, mask) * 1e3
print("full:", results, flush=True)

# ---------------------------------------------------------------------------
# 2. Raw GEMM ladders at the exact per-layer shapes.
# One BERT layer = 4x (T,768)@(768,768) + (T,768)@(768,3072) +
# (T,3072)@(3072,768), T = B*S = 4096.  Chain 12 layers' worth so the
# timed region is model-sized and cannot be elided (output feeds back).
# ---------------------------------------------------------------------------
T, H, I = BATCH * SEQ, cfg.hidden_size, cfg.intermediate_size
kw = jax.random.split(jax.random.key(2), 6)
w_h = [jax.random.normal(k, (H, H), jnp.bfloat16) * 0.02 for k in kw[:4]]
w_up = jax.random.normal(kw[4], (H, I), jnp.bfloat16) * 0.02
w_dn = jax.random.normal(kw[5], (I, H), jnp.bfloat16) * 0.02
x0 = jax.random.normal(jax.random.key(3), (T, H), jnp.bfloat16)

qw_h = [quantize_tensor(w) for w in w_h]
qw_up, qw_dn = quantize_tensor(w_up), quantize_tensor(w_dn)


def ladder_bf16(x):
    for _ in range(cfg.num_layers):
        for w in w_h:
            x = jnp.matmul(x, w, preferred_element_type=jnp.float32).astype(
                jnp.bfloat16
            )
        u = jnp.matmul(x, w_up, preferred_element_type=jnp.float32).astype(
            jnp.bfloat16
        )
        x = jnp.matmul(u, w_dn, preferred_element_type=jnp.float32).astype(
            jnp.bfloat16
        )
    return x


def ladder_q8_dyn(x):
    # on-the-fly activation quant, exactly what dense_q8 does in the model
    for _ in range(cfg.num_layers):
        for qw in qw_h:
            x = dense_q8(x, qw)
        u = dense_q8(x, qw_up)
        x = dense_q8(u, qw_dn)
    return x


def ladder_q8_static(x8):
    # upper bound: operands already int8, rescale folded to a single mul
    for _ in range(cfg.num_layers):
        for qw in qw_h:
            y = jax.lax.dot_general(
                x8, qw["q8"], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            x8 = jnp.clip(y // 1024, -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(
            x8, qw_up["q8"], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        u8 = jnp.clip(y // 1024, -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(
            u8, qw_dn["q8"], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        x8 = jnp.clip(y // 1024, -127, 127).astype(jnp.int8)
    return x8

gemm_flops = cfg.num_layers * 2 * T * (4 * H * H + 2 * H * I)

t = timed(jax.jit(ladder_bf16), x0)
results["gemm_bf16_ms"] = t * 1e3
results["gemm_bf16_tflops"] = gemm_flops / t / 1e12
t = timed(jax.jit(ladder_q8_dyn), x0)
results["gemm_q8_dyn_ms"] = t * 1e3
results["gemm_q8_dyn_tflops"] = gemm_flops / t / 1e12
x0_8 = quantize_tensor(x0, axis=-1)["q8"]
t = timed(jax.jit(ladder_q8_static), x0_8)
results["gemm_q8_static_ms"] = t * 1e3
results["gemm_q8_static_tflops"] = gemm_flops / t / 1e12
print("gemm ladders:", results, flush=True)

# ---------------------------------------------------------------------------
# 3. Model ablations (int8 path): knock out one non-matmul component at a
# time; the delta vs full_int8 prices that component.
# ---------------------------------------------------------------------------
import tpumlops.models.common as common_mod

_orig_ln = common_mod.layer_norm
_orig_softmax = jax.nn.softmax


def run_variant(name, patch, unpatch):
    patch()
    try:
        g = jax.jit(
            lambda p, i, m: bert.classify(p, i, m, cfg=cfg, dtype=jnp.bfloat16)
        )
        results[name] = timed(g, qparams, ids, mask) * 1e3
    finally:
        unpatch()
    print(name, results[name], flush=True)


# no layernorm (identity)
run_variant(
    "ablate_no_layernorm_ms",
    lambda: setattr(bert, "layer_norm", lambda x, s, b, eps=1e-12: x),
    lambda: setattr(bert, "layer_norm", _orig_ln),
)

# softmax in bf16 instead of f32 scores
_orig_attn = bert._self_attention


def _attn_bf16_softmax(p, x, mask_bias, cfg):
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    q = bert._dense(x, p["q"]).reshape(b, s, nh, hd)
    k = bert._dense(x, p["k"]).reshape(b, s, nh, hd)
    v = bert._dense(x, p["v"]).reshape(b, s, nh, hd)
    scores = jnp.einsum("bqnd,bknd->bnqk", q, k) / jnp.bfloat16(hd**0.5)
    scores = scores + mask_bias.astype(x.dtype)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bnqk,bknd->bqnd", probs, v).reshape(b, s, h)
    return bert._dense(ctx, p["o"])


def _attn_no_softmax(p, x, mask_bias, cfg):
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    q = bert._dense(x, p["q"]).reshape(b, s, nh, hd)
    k = bert._dense(x, p["k"]).reshape(b, s, nh, hd)
    v = bert._dense(x, p["v"]).reshape(b, s, nh, hd)
    scores = jnp.einsum(
        "bqnd,bknd->bnqk", q, k, preferred_element_type=jnp.float32
    )
    probs = (scores * 0.001).astype(x.dtype)  # keep the tensor, drop softmax
    ctx = jnp.einsum("bnqk,bknd->bqnd", probs, v).reshape(b, s, h)
    return bert._dense(ctx, p["o"])


run_variant(
    "ablate_softmax_bf16_ms",
    lambda: setattr(bert, "_self_attention", _attn_bf16_softmax),
    lambda: setattr(bert, "_self_attention", _orig_attn),
)
run_variant(
    "ablate_no_softmax_ms",
    lambda: setattr(bert, "_self_attention", _attn_no_softmax),
    lambda: setattr(bert, "_self_attention", _orig_attn),
)

print(json.dumps({k: round(v, 3) for k, v in results.items()}))
