"""7B-scale load rehearsal on the real chip (VERDICT round 1, next #10):
stream the 13.5 GB synthetic Llama-2-7B checkpoint through
server/loader.py with quantize: int8, record wall time + HBM footprint,
then prove the loaded model decodes."""
import json, time
import jax
import jax.numpy as jnp
import numpy as np

dev = jax.devices()[0]
print("device:", dev)
t0 = time.time()
from tpumlops.server.loader import load_predictor
pred = load_predictor("/root/ckpt7b", quantize="int8")
load_s = time.time() - t0
stats = dev.memory_stats() or {}
in_use = stats.get("bytes_in_use", 0)
peak = stats.get("peak_bytes_in_use", 0)
limit = stats.get("bytes_limit", 0)
print(f"load time: {load_s:.1f}s")
print(f"HBM in use: {in_use/2**30:.2f} GiB  peak: {peak/2**30:.2f} GiB  limit: {limit/2**30:.2f} GiB")

from tpumlops.models.quantization import is_quantized, quantized_bytes
params = pred.causal_lm["params"]
for name in ("q", "k", "v", "o", "gate", "up", "down"):
    assert is_quantized(params["layers"][name]), name
assert is_quantized(params["lm_head"])
print(f"stored param bytes: {quantized_bytes(params)/2**30:.2f} GiB (int8 leaves)")

# Decode sanity: one prefill + a few decode steps through the model API.
from tpumlops.models import llama
cfg = pred.causal_lm["cfg"]
t0 = time.time()
cache = llama.RaggedKVCache.create(cfg, 1, jnp.bfloat16)
ids = jnp.ones((1, 16), jnp.int32)
logits, seq = llama.prefill(params, ids, cfg, dtype=jnp.bfloat16)
cache = llama.insert_sequence(cache, seq, jnp.int32(0), jnp.int32(16))
tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
for _ in range(8):
    logits, cache = llama.decode_ragged(params, tok, cache, cfg, window=512)
    tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
tok.block_until_ready()
assert bool(jnp.isfinite(logits).all())
print(f"prefill+8 decode steps (incl. compile): {time.time()-t0:.1f}s; logits finite")
stats = dev.memory_stats() or {}
print(f"HBM after decode: {stats.get('bytes_in_use',0)/2**30:.2f} GiB  peak: {stats.get('peak_bytes_in_use',0)/2**30:.2f} GiB")
print(json.dumps({"load_s": round(load_s,1), "hbm_weights_gib": round(in_use/2**30,2),
                  "hbm_peak_gib": round(stats.get('peak_bytes_in_use',0)/2**30,2),
                  "hbm_limit_gib": round(limit/2**30,2)}))
print("REHEARSAL OK")
