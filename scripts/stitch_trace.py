#!/usr/bin/env python3
"""Stitch router journeys + replica flight-recorder tracks into ONE
Perfetto trace.

The fleet trace plane (spec.fleet.observability.journeyRing /
--journey-ring) propagates one X-Request-Id + W3C traceparent across
every leg of a request's life — router forward, KV export/import
relays, failover retries, park releases, and the replica engine spans —
so the per-component Chrome traces share request ids.  This tool fetches
each component's trace and its started_unix clock anchor, shifts them
onto one timeline, and writes a single chrome trace JSON (load it at
https://ui.perfetto.dev or chrome://tracing).

Examples:

    # one router + two replicas, full ring
    python scripts/stitch_trace.py \
        --router http://127.0.0.1:9000 \
        --replica http://127.0.0.1:8001 --replica http://127.0.0.1:8002 \
        -o fleet_trace.json

    # just one request's span tree
    python scripts/stitch_trace.py --router http://127.0.0.1:9000 \
        --replica http://127.0.0.1:8001 --request-id my-id-123

The operator's telemetry listener serves the same merge live at
``GET /debug/fleet-trace`` when wired with the fleet's endpoints.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from research_and_development_of_kubernetes_operator_for_machine_learning_pipelines_tpu.utils.trace_stitch import (  # noqa: E501
    fetch_source,
    filter_request,
    stitch_chrome_traces,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "stitch_trace",
        description="Merge router + replica chrome traces into one "
        "Perfetto timeline (shared request ids across tracks).",
    )
    ap.add_argument(
        "--router", action="append", default=[], metavar="URL",
        help="router base URL (e.g. http://127.0.0.1:9000); repeatable",
    )
    ap.add_argument(
        "--replica", action="append", default=[], metavar="URL",
        help="replica base URL (server /debug endpoints); repeatable",
    )
    ap.add_argument(
        "--request-id", default=None,
        help="keep only this request's span tree",
    )
    ap.add_argument(
        "-o", "--output", default="-",
        help="output path (default '-' = stdout)",
    )
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    if not args.router and not args.replica:
        ap.error("need at least one --router or --replica URL")

    sources = []
    for i, url in enumerate(args.router):
        label = "router" if len(args.router) == 1 else f"router-{i}"
        sources.append(fetch_source(label, url, "router", args.timeout))
    for i, url in enumerate(args.replica):
        label = f"replica-{i}" if len(args.replica) > 1 else "replica"
        sources.append(fetch_source(label, url, "replica", args.timeout))

    trace = stitch_chrome_traces(sources)
    if args.request_id:
        trace = filter_request(trace, args.request_id)
    text = json.dumps(trace)
    if args.output == "-":
        print(text)
    else:
        Path(args.output).write_text(text)
        n = len(trace["traceEvents"])
        print(f"wrote {args.output}: {n} events from "
              f"{len(sources)} components", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
