"""Why does ragged-decode ms/step scale ~linearly with slots at 1.35B?

Expected: decode is weight-streaming-bound (1.35 GiB/step constant), so
doubling slots should barely move ms/step.  Measured (BENCH r3 ladder):
8.67 -> 16.1 -> 32.1 -> 65.7 ms for 8 -> 64 slots.  This probe prices one
decoder layer's components at B=8 vs B=32 to find the linear term:

  full        — write (vmapped DUS) + attention + matmuls (mirror of
                llama._block decode path, quant cache)
  write_at    — same but cache write via indexed .at[].set scatter
  no_write    — attention + matmuls only
  no_attn     — write + matmuls only
  matmuls     — matmuls only

Timing: bench.py scan-delta (data-chained lax.scan, varied carries,
params explicit) over a SINGLE layer's weights, 24 iterations standing in
for 24 layers.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
from jax import lax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")

import sys
sys.path.insert(0, "/root/repo")
from bench import _scan_delta_timed

H, NKV, NH, HD, I = 2048, 16, 16, 128, 5632
CAP, WINDOW, POS = 768, 512, 256
L = 24  # scan length multiplier: one layer body iterated L times


def make_weights(key):
    ks = jax.random.split(key, 7)
    w = {
        "q": jax.random.normal(ks[0], (H, NH * HD), jnp.bfloat16) * 0.02,
        "k": jax.random.normal(ks[1], (H, NKV * HD), jnp.bfloat16) * 0.02,
        "v": jax.random.normal(ks[2], (H, NKV * HD), jnp.bfloat16) * 0.02,
        "o": jax.random.normal(ks[3], (NH * HD, H), jnp.bfloat16) * 0.02,
        "gate": jax.random.normal(ks[4], (H, I), jnp.bfloat16) * 0.02,
        "up": jax.random.normal(ks[5], (H, I), jnp.bfloat16) * 0.02,
        "down": jax.random.normal(ks[6], (I, H), jnp.bfloat16) * 0.02,
    }
    from tpumlops.models.quantization import quantize_tensor

    return {k: quantize_tensor(v) for k, v in w.items()}


def deq(qw, dtype):
    return (qw["q8"].astype(jnp.float32) * qw["scale"]).astype(dtype)


def layer(p, x, k8, ks, v8, vs, start, variant):
    b = x.shape[0]
    q = jnp.matmul(x, deq(p["q"], x.dtype), preferred_element_type=jnp.float32)
    k = jnp.matmul(x, deq(p["k"], x.dtype), preferred_element_type=jnp.float32)
    v = jnp.matmul(x, deq(p["v"], x.dtype), preferred_element_type=jnp.float32)
    q = q.astype(x.dtype).reshape(b, 1, NH, HD)
    k = k.astype(x.dtype).reshape(b, 1, NKV, HD)
    v = v.astype(x.dtype).reshape(b, 1, NKV, HD)

    from tpumlops.models.llama import _quant_kv

    kq, kqs = _quant_kv(k)
    vq, vqs = _quant_kv(v)

    if variant in ("full", "no_attn"):
        def _write(row_cache, row_kv, row_start):
            z = jnp.zeros((), row_start.dtype)
            return lax.dynamic_update_slice(row_cache, row_kv, (row_start, z, z))

        k8 = jax.vmap(_write)(k8, kq.astype(k8.dtype), start)
        ks = jax.vmap(_write)(ks, kqs.astype(ks.dtype), start)
        v8 = jax.vmap(_write)(v8, vq.astype(v8.dtype), start)
        vs = jax.vmap(_write)(vs, vqs.astype(vs.dtype), start)
    elif variant == "write_at":
        rows = jnp.arange(b)
        k8 = k8.at[rows, start].set(kq[:, 0].astype(k8.dtype))
        ks = ks.at[rows, start].set(kqs[:, 0].astype(ks.dtype))
        v8 = v8.at[rows, start].set(vq[:, 0].astype(v8.dtype))
        vs = vs.at[rows, start].set(vqs[:, 0].astype(vs.dtype))

    if variant in ("full", "no_write", "write_at", "attn_i8"):
        qg = q.reshape(b, 1, NKV, NH // NKV, HD)
        key_pos = jnp.arange(WINDOW)
        valid = key_pos[None, None, :] <= start[:, None, None]
        mask = jnp.where(valid, 0.0, -1e9).astype(jnp.float32)[:, None]
        kscale = jnp.moveaxis(ks[:, :WINDOW, :, 0], 1, 2)[:, :, None, None, :]
        vscale = jnp.moveaxis(vs[:, :WINDOW, :, 0], 1, 2)[:, :, None, None, :]
        if variant == "attn_i8":
            # int8 x int8 -> int32 on the MXU: q quantized per (row, head);
            # the int8 cache is contracted RAW — no bf16 window copy.
            from tpumlops.models.quantization import quantize_tensor

            qq = quantize_tensor(qg, axis=-1)
            q8a, qs = qq["q8"], qq["scale"]  # [b,1,NKV,G,HD], [...,1]
            scores = jax.lax.dot_general(
                q8a, k8[:, :WINDOW],
                (((4,), (3,)), ((0, 2), (0, 2))),
                preferred_element_type=jnp.int32,
            )  # [b, NKV, 1(s), G, W]
            scores = scores.astype(jnp.float32).transpose(0, 1, 3, 2, 4)
            # fold q's per-(row, head) scale: [b,1,NKV,G,1] -> [b,NKV,G,1,1]
            scores = scores * qs.transpose(0, 2, 3, 1, 4)
            scores = scores / jnp.sqrt(jnp.float32(HD))
            scores = scores * kscale + mask[:, None]
            probs = jax.nn.softmax(scores, axis=-1)
            probs = probs * vscale
            pq = quantize_tensor(probs, axis=-1)
            p8, ps = pq["q8"], pq["scale"]  # [b,NKV,G,1,W]
            ctx = jax.lax.dot_general(
                p8, v8[:, :WINDOW],
                (((4,), (1,)), ((0, 1), (0, 2))),
                preferred_element_type=jnp.int32,
            )  # [b, NKV, G, 1, HD]
            ctx = ctx.astype(jnp.float32) * ps
            ctx = ctx.astype(x.dtype).transpose(0, 3, 1, 2, 4).reshape(b, NH * HD)
        else:
            kw = k8[:, :WINDOW]
            scores = jnp.einsum(
                "bqngd,bknd->bngqk", qg, kw.astype(x.dtype),
                preferred_element_type=jnp.float32,
            ) / jnp.sqrt(jnp.float32(HD))
            scores = scores * kscale + mask[:, None]
            probs = jax.nn.softmax(scores, axis=-1)
            probs = (probs * vscale).astype(x.dtype)
            ctx = jnp.einsum(
                "bngqk,bknd->bqngd", probs, v8[:, :WINDOW].astype(x.dtype)
            ).reshape(b, NH * HD)
    else:
        ctx = q.reshape(b, NH * HD)

    attn = jnp.matmul(ctx, deq(p["o"], x.dtype), preferred_element_type=jnp.float32)
    x = x + attn.astype(x.dtype).reshape(b, H)
    g = jnp.matmul(x, deq(p["gate"], x.dtype), preferred_element_type=jnp.float32)
    u = jnp.matmul(x, deq(p["up"], x.dtype), preferred_element_type=jnp.float32)
    act = (jax.nn.silu(g) * u).astype(x.dtype)
    d = jnp.matmul(act, deq(p["down"], x.dtype), preferred_element_type=jnp.float32)
    return (x + d.astype(x.dtype)), k8, ks, v8, vs


results = {}
params = make_weights(jax.random.key(0))
for b in (8, 32):
    start = jnp.full((b,), POS, jnp.int32)
    k8 = jnp.zeros((b, CAP, NKV, HD), jnp.int8)
    ks = jnp.zeros((b, CAP, NKV, 1), jnp.float32)
    v8 = jnp.zeros((b, CAP, NKV, HD), jnp.int8)
    vs = jnp.zeros((b, CAP, NKV, 1), jnp.float32)
    x0 = jax.random.normal(jax.random.key(1), (b, H), jnp.bfloat16)

    for variant in ("full", "write_at", "attn_i8", "no_write", "no_attn", "matmuls"):
        def step(p, carry, variant=variant):
            x, k8, ks, v8, vs = carry
            x, k8, ks, v8, vs = layer(p, x, k8, ks, v8, vs, start, variant)
            return (x, k8, ks, v8, vs), x[0, 0]

        def carry_at(i, b=b, x0=x0, k8=k8, ks=ks, v8=v8, vs=vs):
            return (x0 + jnp.bfloat16(0.01) * i, k8, ks, v8, vs)

        try:
            t0 = time.time()
            p50 = _scan_delta_timed(step, carry_at, runs=6, n1=8, n2=8 + L * 8,
                                    params=params)[50]
            # per-"model-step" equivalent: x L layers
            results[f"b{b}_{variant}_ms_per_24layers"] = round(p50 * L * 1000, 3)
            print(f"b{b} {variant}: {p50 * L * 1000:.3f} ms/24-layer-step "
                  f"({time.time() - t0:.0f}s)", flush=True)
        except Exception as e:
            results[f"b{b}_{variant}"] = f"{type(e).__name__}: {e}"[:100]
            print(f"b{b} {variant}: FAILED {e}", flush=True)

print(json.dumps(results))
