"""Decode-step component profile on the real chip (VERDICT r4 item #2).

Where does the missing HBM bandwidth go as slots grow?  The step's
traffic decomposes as weights + KV-window reads + scatter commit; this
script measures each by ablation at several slot counts:

- ``full``      — the production ``decode_ragged`` step (window=512);
- ``no_commit`` — same but the post-scan scatter is skipped (cache
  returned unmodified): isolates the commit's cost;
- ``win64``     — window=64: nearly removes KV READ traffic while
  keeping weights + commit (isolates read scaling);
- ``weights``   — window=1 and no commit: the pure weight-stream floor.

Marginal interpretation: (full - no_commit) = commit cost;
(full - win64) ~ cost of the extra 448 window positions; (win64 -
weights) ~ small-window attention overhead.  Run:
``python scripts/profile_decode.py [--slots 8,16,32,64] [--seven-b]``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", default="8,16,32")
    ap.add_argument("--seven-b", action="store_true",
                    help="7B geometry from BENCH_7B_CKPT (default: 1.35B random)")
    ap.add_argument("--window", type=int, default=512)
    ap.add_argument("--position", type=int, default=256)
    args = ap.parse_args()

    import bench
    from bench import _scan_delta_timed, _decode_hbm_bytes, V5E_HBM_GBPS

    jax = bench._setup_jax()
    import jax.numpy as jnp

    from tpumlops.models import llama
    from tpumlops.models.quantization import quantize_llama, quantized_bytes

    if args.seven_b:
        import os

        from tpumlops.server.loader import load_predictor

        ckpt = os.environ.get("BENCH_7B_CKPT", "/root/ckpt7b")
        pred = load_predictor(ckpt, quantize="int8")
        params, cfg = pred.causal_lm["params"], pred.causal_lm["cfg"]
        import dataclasses

        cfg = dataclasses.replace(cfg, max_seq=768)
    else:
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, num_layers=24,
            num_heads=16, num_kv_heads=16, intermediate_size=5632,
            max_seq=768,
        )
        params = quantize_llama(llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16))

    wbytes = quantized_bytes(params)

    def step_time(slots: int, *, window: int, commit: bool,
                  n1: int = 6, n2: int = 30) -> float:
        def step(p, carry):
            toks, c = carry
            logits, c2 = llama.decode_ragged(p, toks, c, cfg, window=window)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
            out_cache = c2 if commit else c
            return (nxt, out_cache), nxt[0, 0]

        def carry_at(i):
            # Fresh cache per call: the carry is donated (matching the
            # production loop in bench._decode_device_loop) so the cache
            # lives once and in-loop writes can alias in place.
            cache = llama.QuantRaggedKVCache.create(cfg, slots)
            cache = cache._replace(
                lengths=jnp.full((slots,), args.position, jnp.int32)
            )
            toks = jnp.full((slots, 1), (7 + i) % 1000 + 1, jnp.int32)
            return (toks, cache)

        p = _scan_delta_timed(
            step, carry_at, n1=n1, n2=n2, params=params, donate_carry=True
        )
        return p[50]

    out: dict = {"geometry": "7B" if args.seven_b else "1.35B",
                 "weight_gib": round(wbytes / 2**30, 2), "window": args.window}
    for slots in (int(s) for s in args.slots.split(",")):
        full = step_time(slots, window=args.window, commit=True)
        nocm = step_time(slots, window=args.window, commit=False)
        w64 = step_time(slots, window=64, commit=True)
        wonly = step_time(slots, window=1, commit=False)
        kv_bytes = _decode_hbm_bytes(params, cfg, slots, args.window, True) - wbytes
        entry = {
            "full_ms": round(full * 1e3, 2),
            "tok_per_s": round(slots / full, 1),
            "bw_util": round(
                (wbytes + kv_bytes) / full / 1e9 / V5E_HBM_GBPS, 3
            ),
            "no_commit_ms": round(nocm * 1e3, 2),
            "commit_cost_ms": round((full - nocm) * 1e3, 2),
            "win64_ms": round(w64 * 1e3, 2),
            "kv_read_cost_ms": round((full - w64) * 1e3, 2),
            "weights_only_ms": round(wonly * 1e3, 2),
            "kv_read_gib": round(kv_bytes / 2**30, 2),
            "kv_marginal_gbps": round(
                kv_bytes / max(full - w64, 1e-9) / 1e9, 1
            ),
        }
        out[str(slots)] = entry
        print(f"PROFILE {slots}: {json.dumps(entry)}", flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
