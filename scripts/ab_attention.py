"""In-process A/B of decode attention variants (xla einsum chain vs the
fused Pallas kernel, ops/decode_attention.py) at serving geometry.

Interleaved in one process for the same reason as ab_decode.py: this
environment's device tunnel drifts ±20% across processes, so only
A/B/A/B comparisons in one session are valid.  Reports each variant's
MIN over rounds.

Usage: ``python scripts/ab_attention.py [--slots 8,16,32] [--rounds 2]``
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", default="8,16,32")
    ap.add_argument("--variants", default="xla,pallas",
                    help="comma list of xla,pallas,pallas_single,pallas_vpu")
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--window", type=int, default=512)
    ap.add_argument("--position", type=int, default=256)
    args = ap.parse_args()

    import bench

    jax = bench._setup_jax()
    import jax.numpy as jnp

    from tpumlops.models import llama
    from tpumlops.models.quantization import quantize_llama

    cfg = llama.LlamaConfig(
        vocab_size=32000, hidden_size=2048, num_layers=24,
        num_heads=16, num_kv_heads=16, intermediate_size=5632, max_seq=768,
    )
    params = quantize_llama(llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16))

    variants = tuple(args.variants.split(","))
    out: dict = {}
    for slots in (int(s) for s in args.slots.split(",")):
        best = {v: float("inf") for v in variants}
        for _ in range(args.rounds):
            for variant in variants:
                llama._DECODE_ATTN = variant
                dt = bench._decode_device_loop(
                    jax, params, cfg, slots, kv_quant=True,
                    window=args.window, position=args.position, n1=6, n2=30,
                )
                best[variant] = min(best[variant], dt)
        entry = {f"{v}_ms": round(best[v] * 1e3, 2) for v in best} | {
            f"{v}_tok_s": round(slots / best[v], 1) for v in best
        }
        out[str(slots)] = entry
        print(f"AB {slots}: {json.dumps(entry)}", flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
