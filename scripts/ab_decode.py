"""In-process A/B of decode layer-walk variants (fori vs scan).

Cross-process timings through this environment's device tunnel differ by
~±20% (compile session / tunnel mood), so variant comparisons are only
valid INTERLEAVED in one process: A, B, A, B per slot count, reporting
each variant's MIN over rounds (the min strips additive stalls).

Usage: ``python scripts/ab_decode.py [--slots 8,16,32,64] [--rounds 2]``
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", default="8,16,32,64")
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--window", type=int, default=512)
    args = ap.parse_args()

    import bench

    jax = bench._setup_jax()
    import jax.numpy as jnp

    from tpumlops.models import llama
    from tpumlops.models.quantization import quantize_llama

    cfg = llama.LlamaConfig(
        vocab_size=32000, hidden_size=2048, num_layers=24,
        num_heads=16, num_kv_heads=16, intermediate_size=5632, max_seq=768,
    )
    params = quantize_llama(llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16))

    out: dict = {}
    for slots in (int(s) for s in args.slots.split(",")):
        best = {"fori": float("inf"), "scan": float("inf")}
        for _ in range(args.rounds):
            for variant in ("fori", "scan"):
                llama._DECODE_LAYER_LOOP = variant
                dt = bench._decode_device_loop(
                    jax, params, cfg, slots, kv_quant=True,
                    window=args.window, position=256, n1=6, n2=30,
                )
                best[variant] = min(best[variant], dt)
        entry = {
            f"{v}_ms": round(best[v] * 1e3, 2) for v in best
        } | {
            f"{v}_tok_s": round(slots / best[v], 1) for v in best
        }
        out[str(slots)] = entry
        print(f"AB {slots}: {json.dumps(entry)}", flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
