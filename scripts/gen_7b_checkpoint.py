"""Generate a synthetic Llama-2-7B checkpoint (real shapes, random bf16)
through save_native_model — the multi-GiB artifact for the load rehearsal."""
import os, time
os.environ["JAX_PLATFORMS"] = "cpu"  # generation shouldn't touch the chip
import numpy as np
import ml_dtypes

H, L, NH, NKV, INTER, VOCAB, SEQ = 4096, 32, 32, 32, 11008, 32000, 4096
rng = np.random.default_rng(0)

def rnd(*shape):
    # generate in manageable float32 chunks, store bf16
    out = np.empty(shape, ml_dtypes.bfloat16)
    flat = out.reshape(-1)
    CH = 1 << 24
    for i in range(0, flat.size, CH):
        n = min(CH, flat.size - i)
        flat[i:i+n] = (rng.standard_normal(n, dtype=np.float32) * 0.02).astype(ml_dtypes.bfloat16)
    return out

t0 = time.time()
params = {
    "embed": rnd(VOCAB, H),
    "layers": {
        "q": rnd(L, H, H), "k": rnd(L, H, H), "v": rnd(L, H, H), "o": rnd(L, H, H),
        "gate": rnd(L, H, INTER), "up": rnd(L, H, INTER), "down": rnd(L, INTER, H),
        "attn_norm": rnd(L, H).astype(ml_dtypes.bfloat16),
        "mlp_norm": rnd(L, H),
    },
    "final_norm": rnd(H),
    "lm_head": rnd(H, VOCAB),
}
print(f"generated in {time.time()-t0:.0f}s")
from tpumlops.server.loader import save_native_model
t0 = time.time()
save_native_model("/root/ckpt7b", "llama-generate", params, config={
    "vocab_size": VOCAB, "hidden_size": H, "num_layers": L, "num_heads": NH,
    "num_kv_heads": NKV, "intermediate_size": INTER, "max_seq": 1024})
print(f"saved in {time.time()-t0:.0f}s")
import subprocess
print(subprocess.run(["du","-sh","/root/ckpt7b"], capture_output=True, text=True).stdout)
