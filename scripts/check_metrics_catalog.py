"""Metrics-catalog lint (``make verify`` -> ``metrics-catalog``).

docs/OBSERVABILITY.md carries a "Prometheus series catalog" — three
tables (Server / Operator / Router) that are supposed to enumerate
every exported family.  Catalogs rot: a PR adds a Counter and forgets
the row, or renames one and strands the old row.  This gate collects
the real family inventory from each plane and diffs it against the
parsed tables, failing on EITHER direction (exported-but-undocumented
or documented-but-gone):

- Server: instantiate ``ServerMetrics(device_telemetry=True)`` and walk
  its registry (prometheus_client strips ``_total`` from counter family
  names on collect(); the catalog uses exposition names, so counters
  get the suffix re-appended here).
- Operator: same, via ``OperatorTelemetry()``.
- Router: the native router has no Python registry — parse the
  ``# TYPE <family> <type>`` exposition lines straight out of
  ``native/router.cc``.

Table cells may name several families (comma- or slash-separated) and
use brace expansion (``tpumlops_prefix_cache_{hits,evictions}_total``);
a trailing ``{label}`` annotation (no comma inside) is stripped.

Usage: ``python scripts/check_metrics_catalog.py``
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_ROOT))

DOC = _ROOT / "docs" / "OBSERVABILITY.md"
PKG = (
    "research_and_development_of_kubernetes_operator_for_"
    "machine_learning_pipelines_tpu"
)
ROUTER_CC = _ROOT / PKG / "native" / "router.cc"

_BRACES = re.compile(r"\{([^{}]*)\}")


def expand_cell(cell: str) -> set[str]:
    """``cell`` is one backtick-quoted family token from a table row."""
    # Trailing {label} annotation (no comma) is documentation, not a
    # name component; {a,b,c} anywhere is brace expansion.
    names = {cell}
    while True:
        expanded = set()
        again = False
        for name in names:
            m = _BRACES.search(name)
            if m is None:
                expanded.add(name)
            elif "," in m.group(1):
                again = True
                for alt in m.group(1).split(","):
                    expanded.add(name[: m.start()] + alt.strip() + name[m.end() :])
            else:
                again = True
                expanded.add(name[: m.start()] + name[m.end() :])
        names = expanded
        if not again:
            return names


def doc_families() -> dict[str, set[str]]:
    """Parse the three catalog tables -> {"server"|"operator"|"router": names}."""
    text = DOC.read_text()
    try:
        catalog = text.split("## Prometheus series catalog", 1)[1]
    except IndexError:
        raise SystemExit("metrics-catalog: catalog heading missing from doc")
    out: dict[str, set[str]] = {}
    for plane in ("Server", "Operator", "Router"):
        m = re.search(rf"### {plane}\b.*?\n(.*?)(?=\n### |\n## |\Z)", catalog, re.S)
        if m is None:
            raise SystemExit(f"metrics-catalog: '### {plane}' table missing")
        names: set[str] = set()
        for line in m.group(1).splitlines():
            if not line.startswith("|") or line.startswith("|---"):
                continue
            first = line.split("|")[1]
            if first.strip() == "family":
                continue
            for token in re.findall(r"`([^`]+)`", first):
                names |= expand_cell(token.strip())
        out[plane.lower()] = names
    return out


def registry_families(registry) -> set[str]:
    names = set()
    for mf in registry.collect():
        name = mf.name
        if mf.type == "counter":
            name += "_total"
        names.add(name)
    return names


def router_cc_families() -> set[str]:
    names = set()
    for m in re.finditer(r"# TYPE (tpumlops_router_\w+) \w+", ROUTER_CC.read_text()):
        names.add(m.group(1))
    return names


def main() -> int:
    from research_and_development_of_kubernetes_operator_for_machine_learning_pipelines_tpu.operator.telemetry import (  # noqa: E501
        OperatorTelemetry,
    )
    from research_and_development_of_kubernetes_operator_for_machine_learning_pipelines_tpu.server.metrics import (  # noqa: E501
        ServerMetrics,
    )

    exported = {
        "server": registry_families(
            ServerMetrics("d", "p", "ns", device_telemetry=True).registry
        ),
        "operator": registry_families(OperatorTelemetry().registry),
        "router": router_cc_families(),
    }
    documented = doc_families()

    problems: list[str] = []
    for plane in ("server", "operator", "router"):
        for name in sorted(exported[plane] - documented[plane]):
            problems.append(f"{plane}: `{name}` exported but not in the catalog")
        for name in sorted(documented[plane] - exported[plane]):
            problems.append(f"{plane}: `{name}` in the catalog but not exported")

    if problems:
        print("metrics-catalog: OUT OF SYNC with docs/OBSERVABILITY.md:", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    total = sum(len(v) for v in exported.values())
    print(f"metrics-catalog: OK ({total} families across 3 planes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
