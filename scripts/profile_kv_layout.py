"""KV-cache layout + commit-strategy microbench (VERDICT r4 item #2).

profile_decode.py showed two superlinear-cost components at high slot
counts: the post-scan scatter commit (14 ms at 64 slots — consistent
with XLA copying the cache buffers instead of writing in place) and
KV-window read marginal bandwidth decaying 612 -> 300 GB/s.  This bench
isolates both on raw buffers at 1.35B geometry, no model code:

commit strategies (write one [L,B,N,D] row-set at per-row positions):
- ``scatter``  — ``buf.at[:, rows, lengths].set(vals)`` (production);
- ``dus_loop`` — ``fori_loop`` over rows of per-row
  ``dynamic_update_slice`` (classic in-place pattern);
- ``same_pos`` — single ``dynamic_update_slice`` at one shared position
  (in-place upper bound; not ragged-correct, a bound only).

read/attention layouts (score einsum over the 512-window):
- ``bknd`` — cache stored [B, W, NKV, D], einsum "bqngd,bknd->bngqk"
  (production: position-major, head minor);
- ``bnkd`` — cache stored [B, NKV, W, D], einsum "bqngd,bnkd->bngqk"
  (head-major: the dot's natural operand layout — if production pays a
  materialized transpose, this variant shows the gap).

Run on the chip: ``python scripts/profile_kv_layout.py``.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


L, NKV, D, T, W = 24, 16, 128, 768, 512
GROUP = 1  # 1.35B is MHA: num_heads == num_kv_heads


def main() -> None:
    import bench
    from bench import _scan_delta_timed

    jax = bench._setup_jax()
    import jax.numpy as jnp
    from jax import lax

    results: dict = {}

    # 8-slot commits are in-place and sub-noise through the tunnel (the
    # delta collapses to zero — itself the answer); measure where the
    # model-level profile saw the superlinear cost.
    for slots in (32, 64):
        entry: dict = {}
        k8 = jnp.zeros((L, slots, T, NKV, D), jnp.int8)
        new_rows = jnp.ones((L, slots, NKV, D), jnp.int8)
        lengths0 = jnp.full((slots,), 256, jnp.int32)
        rows = jnp.arange(slots)

        # -- commit strategies (the buffer rides the scan carry, so each
        # iteration's write is a real loop-carried dependency) ----------
        def run_commit(kind) -> float:
            def step(carry):
                buf, lengths = carry
                # The written values depend on the PREVIOUS iteration's
                # write (dynamic-index read), and the probe reads THIS
                # iteration's write: the chain cannot be DCE'd or
                # scatter-forwarded (indices are traced values).
                prev = lax.dynamic_index_in_dim(
                    buf, lengths[0] - 1, axis=2, keepdims=False
                )[0, 0, 0, 0]
                vals = new_rows + prev
                if kind == "scatter":
                    buf = buf.at[:, rows, lengths].set(vals)
                elif kind == "dus_loop":
                    def body(i, b):
                        return lax.dynamic_update_slice(
                            b,
                            vals[:, i][:, None, None],
                            (0, i, lengths[i], 0, 0),
                        )
                    buf = lax.fori_loop(0, slots, body, buf)
                elif kind == "same_pos":
                    buf = lax.dynamic_update_slice(
                        buf,
                        vals[:, :, None],
                        (0, 0, lengths[0], 0, 0),
                    )
                probe = lax.dynamic_index_in_dim(
                    buf, lengths[0], axis=2, keepdims=False
                )[0, 0, 0, 0].astype(jnp.int32)
                lengths = lengths + 1
                return (buf, lengths), probe

            p = _scan_delta_timed(
                step, lambda i: (k8, lengths0 + i % 3), n1=8, n2=40
            )
            return p[50]

        for kind in ("scatter", "dus_loop", "same_pos"):
            try:
                entry[f"commit_{kind}_ms"] = round(run_commit(kind) * 1e3, 3)
            except RuntimeError as e:  # below the tunnel's noise floor
                entry[f"commit_{kind}_ms"] = f"sub-noise ({e})"[:60]
        print(f"COMMIT {slots}: {json.dumps(entry)}", flush=True)

        # -- read/attention layouts --------------------------------------
        q = jnp.ones((slots, 1, NKV, GROUP, D), jnp.bfloat16)

        def run_read(layout) -> float:
            # Non-constant cache values: the probe (a reduction over the
            # scores) must differ across varied-q calls or the replay
            # detector rejects every sample.
            n_elem = slots * W * NKV * D
            data = (jnp.arange(n_elem, dtype=jnp.int32) % 251 - 125).astype(
                jnp.int8
            )
            if layout == "bknd":
                cache = data.reshape(slots, W, NKV, D)
                eq = "bqngd,bknd->bngqk"
            else:
                cache = data.reshape(slots, NKV, W, D)
                eq = "bqngd,bnkd->bngqk"

            def step(cache_arg, carry):
                qq, probe = carry
                scores = jnp.einsum(
                    eq, qq, cache_arg.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
                # MAX, not sum: sum(einsum(q, K)) is linear in K, so XLA
                # rewrites it to einsum(q, sum(K)) and hoists the entire
                # cache read out of the loop as loop-invariant — the
                # "collapsed to zero" runs.  max cannot commute through
                # the contraction.
                s = jnp.max(jnp.abs(scores))
                # Feed the score back through q with a non-foldable tiny
                # multiplier: keeps a true data dependency between scan
                # iterations (mul-by-zero would constant-fold away and
                # let the tunnel pipeline/elide iterations).
                qq = qq + (s * jnp.float32(1e-30)).astype(jnp.bfloat16)
                return (qq, s), s

            # 0.125 * i: exactly representable in bf16 and >= one ulp at
            # 1.0 — a sub-ulp perturbation (e.g. 0.001*i) rounds away and
            # the tunnel replays cached results for the identical input.
            p = _scan_delta_timed(
                step, lambda i: (q + jnp.bfloat16(0.125) * i, jnp.float32(0)),
                n1=32, n2=160, params=cache,
            )
            return p[50]

        for layout in ("bknd", "bnkd"):
            entry[f"read_{layout}_us"] = round(run_read(layout) * 1e6, 1)
        kv_bytes = slots * W * NKV * D
        entry["read_bknd_gbps"] = round(
            kv_bytes / (entry["read_bknd_us"] / 1e6) / 1e9, 1
        )
        entry["read_bnkd_gbps"] = round(
            kv_bytes / (entry["read_bnkd_us"] / 1e6) / 1e9, 1
        )

        results[str(slots)] = entry
        print(f"LAYOUT {slots}: {json.dumps(entry)}", flush=True)

    print(json.dumps(results))


if __name__ == "__main__":
    main()
