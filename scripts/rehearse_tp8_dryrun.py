"""TP8 sharding dryrun at 7B layer shapes (2 layers to bound CPU RAM):
the v5e-8 deployment path — mesh build, sharded load, int8 quantize on
the mesh, one decode step — on 8 virtual CPU devices."""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import time, jax, jax.numpy as jnp, numpy as np, tempfile
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
from jax.extend import backend as _jeb
_jeb.clear_backends()
assert len(jax.devices()) == 8, jax.devices()
from tpumlops.models import llama
from tpumlops.server.loader import load_predictor, save_native_model

cfg = llama.LlamaConfig(vocab_size=8192, hidden_size=4096, num_layers=2,
                        num_heads=32, num_kv_heads=32, intermediate_size=11008,
                        max_seq=128)
params = llama.init(jax.random.key(0), cfg, dtype=jnp.bfloat16)
art = tempfile.mkdtemp() + "/llm7b2l"
save_native_model(art, "llama-generate", params, config={
    "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
    "num_layers": cfg.num_layers, "num_heads": cfg.num_heads,
    "num_kv_heads": cfg.num_kv_heads, "intermediate_size": cfg.intermediate_size,
    "max_seq": cfg.max_seq})
t0 = time.time()
pred = load_predictor(art, mesh_shape={"tp": 8}, quantize="int8")
print(f"sharded int8 load: {time.time()-t0:.1f}s")
p = pred.causal_lm["params"]
from tpumlops.models.quantization import is_quantized
assert is_quantized(p["layers"]["q"]) and is_quantized(p["lm_head"])
# q8 leaves must actually be sharded over tp
sh = p["layers"]["q"]["q8"].sharding
print("q8 sharding:", sh)
assert not sh.is_fully_replicated
# One sharded forward (prefill) is the compile-bound step worth proving;
# full generate at 7B shapes is minutes of CPU XLA compile for no extra
# sharding coverage.
t0 = time.time()
logits, seq = llama.prefill(p, jnp.ones((1, 16), jnp.int32), pred.causal_lm["cfg"], dtype=jnp.bfloat16)
logits.block_until_ready()
assert bool(jnp.isfinite(logits).all())
print(f"sharded prefill (incl. compile): {time.time()-t0:.1f}s")
print("TP8 DRYRUN OK", logits.shape)
