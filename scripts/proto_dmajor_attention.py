"""Prototype: D-major KV layout + VPU decode attention (round-5 probe).

The shipped decode attention sits at the MXU's G=1 matvec tiling floor
(~0.5 us per slot-head dot; docs/PERF.md round 5), because every
formulation over the head-major ``[.., W, D]`` cache either pays MXU
passes with one live row or (VPU spelling) burns its advantage on
Mosaic relayouts.  This probe measures the remaining candidate: store
the window TRANSPOSED, ``[B, NKV, D, W]`` — D on sublanes, W on lanes —
so

- scores  = sublane-reduce of q[:, None] * k   ->  [1, W]  (lane-dense)
- softmax = lane ops on [1, W] directly
- context = lane-reduce  of p * v              ->  [D, 1]

with no transposes inside the kernel and no dot_general anywhere.

Run on chip:  python scripts/proto_dmajor_attention.py [--slots 8,32]
Compares per-STEP attention-only cost (24 layer-calls) against the
production XLA einsum chain on the same values (parity-checked).
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NKV, D, W = 16, 128, 512
LAYERS = 24  # per-step multiplier: one attention call per layer


def _kernel_dmajor(q_ref, k_ref, ks_ref, v_ref, vs_ref, mask_ref, o_ref,
                   *, scale, bb):
    for t in range(bb):
        q = q_ref[t, 0].astype(jnp.float32) * scale        # [D, 1]
        k = k_ref[t, 0].astype(jnp.float32)                # [D, W]
        s = jnp.sum(k * q, axis=0, keepdims=True)          # [1, W] sublane-red
        s = s * ks_ref[t, 0] + mask_ref[t]                 # [1, W]
        m = jnp.max(s, axis=1, keepdims=True)              # [1, 1]
        p = jnp.exp(s - m)                                 # [1, W]
        denom = jnp.sum(p, axis=1, keepdims=True)          # [1, 1]
        pv = p * vs_ref[t, 0]                              # [1, W]
        v = v_ref[t, 0].astype(jnp.float32)                # [D, W]
        ctx = jnp.sum(v * pv, axis=1, keepdims=True)       # [D, 1] lane-red
        o_ref[t, 0] = ctx / denom


def attn_dmajor(q, k8t, ks, v8t, vs, mask, *, interpret=False):
    """q [B,NKV,D,1]; k8t/v8t [B,NKV,D,W] int8; ks/vs [B,NKV,1,W] f32;
    mask [B,1,W] -> out [B,NKV,D,1] f32."""
    b = q.shape[0]
    bb = 8 if b % 8 == 0 else (4 if b % 4 == 0 else 1)
    kernel = functools.partial(_kernel_dmajor, scale=1.0 / D ** 0.5, bb=bb)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, NKV, D, 1), jnp.float32),
        grid=(b // bb, NKV),
        in_specs=[
            pl.BlockSpec((bb, 1, D, 1), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bb, 1, D, W), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bb, 1, 1, W), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bb, 1, D, W), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bb, 1, 1, W), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bb, 1, W), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bb, 1, D, 1), lambda i, j: (i, j, 0, 0)),
        interpret=interpret,
    )(q, k8t, ks, v8t, vs, mask)


def attn_xla(q, k8, ks, v8, vs, mask):
    """Production-shaped einsum chain on head-major [B,NKV,W,D] (the
    no-self-term core, matching the prototype's contract)."""
    qf = q.astype(jnp.float32) / (D ** 0.5)               # [B,NKV,1,D]
    s = jnp.einsum("bngd,bnwd->bngw", qf, k8.astype(jnp.float32))
    s = s * ks[..., 0][:, :, None, :] + mask[:, :, None, :]
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    ctx = jnp.einsum("bngw,bnwd->bngd",
                     p * vs[..., 0][:, :, None, :], v8.astype(jnp.float32))
    return ctx / denom


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", default="8,32")
    ap.add_argument("--rounds", type=int, default=2)
    args = ap.parse_args()
    import numpy as np

    import bench

    bench._setup_jax()
    out = {}
    for b in (int(s) for s in args.slots.split(",")):
        key = jax.random.key(0)
        ks_ = jax.random.split(key, 6)
        k8 = jax.random.randint(ks_[0], (b, NKV, W, D), -127, 128, jnp.int8)
        v8 = jax.random.randint(ks_[1], (b, NKV, W, D), -127, 128, jnp.int8)
        ksc = jnp.abs(jax.random.normal(ks_[2], (b, NKV, W, 1))) * 0.01 + 1e-3
        vsc = jnp.abs(jax.random.normal(ks_[3], (b, NKV, W, 1))) * 0.01 + 1e-3
        q = jax.random.normal(ks_[4], (b, NKV, 1, D), jnp.float32)
        lengths = jnp.arange(b, dtype=jnp.int32) * (W // max(b, 1)) + 1
        mask = jnp.where(jnp.arange(W)[None, :] < lengths[:, None],
                         0.0, -1e9).astype(jnp.float32)[:, None, :]
        # D-major copies of the same values
        k8t = jnp.swapaxes(k8, 2, 3)
        v8t = jnp.swapaxes(v8, 2, 3)
        kst = jnp.swapaxes(ksc, 2, 3)
        vst = jnp.swapaxes(vsc, 2, 3)
        qt = jnp.swapaxes(q, 2, 3)

        # Parity first.
        ref = attn_xla(q, k8, ksc, v8, vsc, mask)
        got = attn_dmajor(qt, k8t, kst, v8t, vst, mask)
        delta = float(jnp.max(jnp.abs(
            jnp.swapaxes(got, 2, 3) - ref)))
        assert delta < 1e-3, delta

        # Timed with the bench's scan-delta machinery: each scan
        # iteration is ONE attention call, the q carry chains them, the
        # big buffers ride as explicit params.
        def step_x(pr, c):
            kk, kks, vv, vvs, mm = pr
            o = attn_xla(c, kk, kks, vv, vvs, mm)
            return c + 1e-6 * o, o[0, 0, 0, 0]

        def step_d(pr, c):
            kk, kks, vv, vvs, mm = pr
            o = attn_dmajor(c, kk, kks, vv, vvs, mm)
            return c + 1e-6 * o, o[0, 0, 0, 0]

        res = {}
        for name, step, qin, pr in (
            ("xla", step_x, q, (k8, ksc, v8, vsc, mask)),
            ("dmajor", step_d, qt, (k8t, kst, v8t, vst, mask)),
        ):
            p = bench._scan_delta_timed(
                step, lambda i, qin=qin: qin + 1e-5 * i,
                runs=max(3, args.rounds * 2), n1=LAYERS, n2=LAYERS * 5,
                params=pr,
            )
            res[name] = p[50] * LAYERS  # per 24-layer decode step
        out[str(b)] = {f"{k}_ms_per_step": round(v * 1e3, 3)
                       for k, v in res.items()} | {
            "speedup": round(res["xla"] / res["dmajor"], 2)}
        print(b, json.dumps(out[str(b)]), flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
