"""Bench regression sentinel (``make verify`` -> ``bench-history``).

Every committed bench record (``BENCH_*.json`` at the repo root) carries
a handful of headline numbers — tok/s, TTFT/ITL tails, dispatch ratios,
compile collapse, token agreement.  Those numbers regress silently: a
PR re-runs one scenario, pastes the new JSON, and nobody compares it to
the record it replaced.  This gate keeps an append-only history
(``BENCH_HISTORY.jsonl``, one compact line per committed record
revision) and fails when a watched key moves the wrong way past its
tolerance versus the LAST committed revision of the same scenario:

- ``higher``: value must not drop below ``last * (1 - tol)``
  (throughput, collapse ratios, speedups),
- ``lower``:  value must not rise above ``last * (1 + tol)``
  (tail latencies, compile counts, loss/hang/shed tallies),
- ``max_delta``: ``abs(new - last)`` must stay within an absolute bound
  (keys that hover near zero or legitimately go negative, like
  observability ``overhead_pct``),
- ``exact``: byte-equal (token_agreement — correctness is not a dial).

Tolerances are deliberately loose for wall-clock keys (CPU bench walls
vary run to run) and zero for deterministic counters.  A legitimate
trade-off (e.g. a feature that costs throughput) updates this registry
or the history in the same PR, visible in the diff.

When a record changed AND passes, its compact line is appended to the
history so the next revision compares against it.  An unchanged record
appends nothing — re-running ``make verify`` is idempotent.

Usage: ``python scripts/check_bench_history.py [--dry-run]``
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

_ROOT = Path(__file__).resolve().parent.parent
HISTORY = _ROOT / "BENCH_HISTORY.jsonl"

# file -> (scenario, {key: (kind, tolerance)}).  kind semantics in the
# module docstring; tolerance is relative for higher/lower, absolute
# points for max_delta, ignored for exact.
WATCHED: dict[str, tuple[str, dict[str, tuple[str, float]]]] = {
    "BENCH_SUPERSTEP.json": (
        "ragged_superstep",
        {
            "tok_per_s_unified": ("higher", 0.30),
            "compile_collapse_ratio": ("higher", 0.10),
            "unified_compiles": ("lower", 0.0),
            "unified_dispatches_per_token": ("lower", 0.10),
            "itl_p99_ms_unified": ("lower", 0.50),
            "token_agreement": ("exact", 0.0),
        },
    ),
    "BENCH_FLEET_TRACE.json": (
        "fleet_trace",
        {
            "tok_per_s_on": ("higher", 0.30),
            "overhead_pct": ("max_delta", 10.0),
            "stitched_components": ("lower", 0.0),
            "token_agreement": ("exact", 0.0),
        },
    ),
    "BENCH_MULTIMODEL.json": (
        "multimodel_mux",
        {
            "lost": ("lower", 0.0),
            "chips_saved": ("higher", 0.0),
            "p99_ratio": ("max_delta", 0.75),
            "token_agreement": ("exact", 0.0),
        },
    ),
    "BENCH_CHAOS.json": (
        "chaos_resilience",
        {
            "availability_pct": ("higher", 0.0),
            "bare_502": ("lower", 0.0),
            "hangs": ("lower", 0.0),
        },
    ),
    "BENCH_COLD_START.json": (
        "cold_start",
        {
            "restore_speedup_vs_native": ("higher", 0.50),
            "bytes_reduction": ("higher", 0.20),
            "token_agreement": ("exact", 0.0),
        },
    ),
    "BENCH_LONGCTX.json": (
        "longctx_sp",
        {
            "est_ttft_gain_32k": ("higher", 0.10),
            "sp_dispatches": ("lower", 0.0),
            "token_agreement": ("exact", 0.0),
        },
    ),
    "BENCH_TP.json": (
        "tp_dp_ladder",
        {
            "dp_tokens_per_dispatch_ratio": ("higher", 0.10),
            "token_agreement": ("exact", 0.0),
            "dp_token_agreement": ("exact", 0.0),
        },
    ),
    "BENCH_ANOMALY.json": (
        "anomaly_observability_serving",
        {
            "tok_per_s_on": ("higher", 0.30),
            "overhead_pct": ("max_delta", 10.0),
            "straggler_flagged": ("exact", 0.0),
            "false_positives": ("lower", 0.0),
            "token_agreement": ("exact", 0.0),
        },
    ),
}


def lookup(record: dict, key: str):
    """Find ``key`` in ``record``, descending into dict values.

    Committed record shapes vary: most are flat, some nest the numbers
    under ``"result"`` (BENCH_FLEET_TRACE.json).  First match wins on a
    deterministic (insertion-order) walk.
    """
    if key in record:
        return record[key]
    for v in record.values():
        if isinstance(v, dict):
            found = lookup(v, key)
            if found is not None:
                return found
    return None


def extract(record: dict, rules: dict) -> dict:
    out = {}
    for key in rules:
        val = lookup(record, key)
        if isinstance(val, bool):
            val = int(val)
        if val is not None:
            out[key] = val
    return out


def check(scenario: str, keys: dict, last: dict, rules: dict) -> list[str]:
    problems = []
    for key, (kind, tol) in rules.items():
        if key not in keys:
            problems.append(f"{scenario}: watched key {key!r} missing from record")
            continue
        if key not in last:
            continue  # key is new — nothing to regress against
        new, old = keys[key], last[key]
        if kind == "exact":
            if new != old:
                problems.append(
                    f"{scenario}: {key} changed {old!r} -> {new!r} (exact pin)"
                )
        elif kind == "higher":
            floor = old * (1.0 - tol) if old >= 0 else old * (1.0 + tol)
            if new < floor:
                problems.append(
                    f"{scenario}: {key} regressed {old} -> {new} "
                    f"(floor {floor:.4g}, tol {tol:.0%})"
                )
        elif kind == "lower":
            ceil = old * (1.0 + tol) if old >= 0 else old * (1.0 - tol)
            if new > ceil:
                problems.append(
                    f"{scenario}: {key} regressed {old} -> {new} "
                    f"(ceiling {ceil:.4g}, tol {tol:.0%})"
                )
        elif kind == "max_delta":
            if abs(new - old) > tol:
                problems.append(
                    f"{scenario}: {key} moved {old} -> {new} "
                    f"(|delta| {abs(new - old):.4g} > {tol:g})"
                )
    return problems


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser("check_bench_history")
    ap.add_argument(
        "--dry-run",
        action="store_true",
        help="check only; never append to BENCH_HISTORY.jsonl",
    )
    args = ap.parse_args(argv)

    history: dict[str, dict] = {}  # scenario -> last line (latest wins)
    if HISTORY.exists():
        for line in HISTORY.read_text().splitlines():
            line = line.strip()
            if line:
                rec = json.loads(line)
                history[rec["scenario"]] = rec

    problems: list[str] = []
    appends: list[dict] = []
    for fname, (scenario, rules) in sorted(WATCHED.items()):
        path = _ROOT / fname
        if not path.exists():
            continue  # scenario not committed yet — nothing to watch
        record = json.loads(path.read_text())
        keys = extract(record, rules)
        last = history.get(scenario)
        if last is None:
            appends.append({"scenario": scenario, "file": fname, "keys": keys})
            print(f"bench-history: {scenario}: first record, seeding history")
            continue
        if keys == last["keys"]:
            continue  # unchanged — idempotent re-run
        found = check(scenario, keys, last["keys"], rules)
        if found:
            problems.extend(found)
        else:
            appends.append({"scenario": scenario, "file": fname, "keys": keys})
            print(f"bench-history: {scenario}: record changed, within tolerance")

    if problems:
        print("bench-history: REGRESSION (history not updated):", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        print(
            "bench-history: a deliberate trade-off updates the registry in "
            "scripts/check_bench_history.py (or amends BENCH_HISTORY.jsonl) "
            "in the same PR.",
            file=sys.stderr,
        )
        return 1

    if appends and not args.dry_run:
        with HISTORY.open("a") as f:
            for rec in appends:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
    n = len(history) + len(appends)
    print(f"bench-history: OK ({n} scenario(s) tracked, {len(appends)} appended)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
