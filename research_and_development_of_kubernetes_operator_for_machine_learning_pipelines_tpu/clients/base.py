"""Protocol interfaces and data types for the operator's external systems.

Shapes mirror what the reference consumes:

- ``ModelVersion``: the two fields the reference reads off MLflow's
  model-version object — ``version`` (``mlflow_operator.py:95``) and
  ``source`` (``:126,:132``).
- ``ModelMetrics``: the six quantities ``get_model_metrics`` computes from
  PromQL (``:363-417``), with ``None`` meaning "no traffic in the window"
  exactly as the reference does (``:387-390,:401-404``).
- ``KubeClient``: the five CustomObjectsApi verbs the reference uses
  (get/create/replace/patch-status/delete, ``:73,:241-282,:462-477``) plus
  event emission (``kopf.event`` call sites ``:90,:122,:332,:344,:361``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping, Protocol, runtime_checkable


# ---------------------------------------------------------------------------
# Errors
# ---------------------------------------------------------------------------


class ApiError(Exception):
    """Kubernetes API error with an HTTP status code."""

    def __init__(self, status: int, message: str = ""):
        super().__init__(f"{status}: {message}")
        self.status = status


class NotFound(ApiError):
    def __init__(self, message: str = "not found"):
        super().__init__(404, message)


class Conflict(ApiError):
    """409 — stale resourceVersion on replace.

    The reference propagates resourceVersion (``mlflow_operator.py:256-259``)
    but never catches the resulting 409s (SURVEY §5 race note); the rebuild's
    reconciler retries on Conflict.
    """

    def __init__(self, message: str = "conflict"):
        super().__init__(409, message)


class WatchExpired(ApiError):
    """410 Gone — the watch's resourceVersion fell out of etcd's history.

    The standard Kubernetes informer contract: the watcher must re-list to
    get a fresh resourceVersion and resume from there.
    """

    def __init__(self, message: str = "watch expired"):
        super().__init__(410, message)


class RegistryError(Exception):
    """MLflow registry unreachable or returned an unexpected error."""


class AliasNotFound(RegistryError):
    """Alias does not exist on the registered model.

    The reference treats *any* exception from
    ``get_model_version_by_alias`` as alias-missing
    (``mlflow_operator.py:58-62``); the rebuild distinguishes a definitive
    miss (this error -> error status + teardown) from transport errors
    (``RegistryError`` -> keep last-known-good deployment and retry).
    """


# ---------------------------------------------------------------------------
# Data types
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ModelVersion:
    version: str
    source: str  # artifact URI as stored by MLflow, e.g. mlflow-artifacts:/1/abc/artifacts/model


@dataclass(frozen=True)
class ModelMetrics:
    """One predictor's metrics over a window (reference ``:363-417``)."""

    latency_p95: float | None = None
    error_responses: float = 0.0
    error_rate: float | None = None
    latency_avg: float | None = None
    request_count: float = 0.0
    feedback_request_count: float = 0.0

    def as_dict(self) -> dict[str, Any]:
        return {
            "latency_95th": self.latency_p95,
            "error_responses": self.error_responses,
            "error_rate": self.error_rate,
            "latency_avg": self.latency_avg,
            "request_count": self.request_count,
            "feedback_request_count": self.feedback_request_count,
        }


@dataclass(frozen=True)
class EngineMetrics:
    """One predictor's engine-saturation signals over a window.

    The replica autoscaler's inputs (``operator/autoscaler.py``): queue
    depth summed across the predictor's replicas (instant gauge), and
    the p95 of admission wait / TTFT over the window.  ``None`` means
    the signal is unavailable (no such series, Prometheus unreachable,
    or no traffic in the window) — the autoscaler must treat that as
    "hold", never as zero load, or a metrics blackout would drain the
    fleet to minReplicas under full load.
    """

    queue_depth: float | None = None
    admission_wait_p95_ms: float | None = None
    ttft_p95_s: float | None = None
    # Requests held in the router's park buffer because the CR is at
    # zero replicas (tpumlops_router_parked_requests / GET
    # /router/parked).  THE wake signal for scale-to-zero: a parked
    # request is a user already waiting, so the autoscaler wakes
    # immediately on parked > 0.  None = no parking-capable source.
    parked: float | None = None
    # SLO tails (spec.slo): p99 of tpumlops_ttft_seconds /
    # tpumlops_itl_seconds over the window.  Filled only by sources
    # asked to serve the SLO tracker; as_dict omits them when None so
    # pre-SLO journal records (ScaleRecord.observed) stay byte-for-byte.
    ttft_p99_s: float | None = None
    itl_p99_s: float | None = None

    def as_dict(self) -> dict[str, Any]:
        out = {
            "queue_depth": self.queue_depth,
            "admission_wait_p95_ms": self.admission_wait_p95_ms,
            "ttft_p95_s": self.ttft_p95_s,
            "parked": self.parked,
        }
        if self.ttft_p99_s is not None:
            out["ttft_p99_s"] = self.ttft_p99_s
        if self.itl_p99_s is not None:
            out["itl_p99_s"] = self.itl_p99_s
        return out


@dataclass(frozen=True)
class WatchEvent:
    """One event off a Kubernetes watch stream.

    ``type`` is the API server's event type: ``ADDED`` / ``MODIFIED`` /
    ``DELETED``, plus ``BOOKMARK`` when ``allowWatchBookmarks`` is on
    (a resourceVersion checkpoint carrying no object change).
    """

    type: str
    object: Mapping[str, Any]


@dataclass(frozen=True)
class Event:
    """A Kubernetes Event attached to a CR (reference ``kopf.event`` sites)."""

    type: str  # "Normal" | "Warning"
    reason: str  # e.g. "TrafficIncrease", "PromotionFailed"
    message: str


@dataclass
class ObjectRef:
    group: str
    version: str
    namespace: str
    plural: str
    name: str

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version


# The two custom-resource kinds the operator touches.
MLFLOWMODEL = dict(group="mlflow.nizepart.com", version="v1alpha1", plural="mlflowmodels")
SELDONDEPLOYMENT = dict(
    group="machinelearning.seldon.io", version="v1", plural="seldondeployments"
)


# ---------------------------------------------------------------------------
# Protocols
# ---------------------------------------------------------------------------


@runtime_checkable
class RegistryClient(Protocol):
    """Model-registry lookups (MLflow in the reference)."""

    def get_version_by_alias(self, model_name: str, alias: str) -> ModelVersion:
        """Resolve alias -> ModelVersion.  Raises AliasNotFound / RegistryError."""
        ...

    def get_version(self, model_name: str, version: str) -> ModelVersion:
        ...


@runtime_checkable
class KubeClient(Protocol):
    """Minimal dynamic-object Kubernetes API (CustomObjectsApi equivalent)."""

    def get(self, ref: ObjectRef) -> Mapping[str, Any]:
        ...

    def create(self, ref: ObjectRef, body: Mapping[str, Any]) -> Mapping[str, Any]:
        ...

    def replace(self, ref: ObjectRef, body: Mapping[str, Any]) -> Mapping[str, Any]:
        ...

    def patch_status(self, ref: ObjectRef, status: Mapping[str, Any]) -> Mapping[str, Any]:
        ...

    def delete(self, ref: ObjectRef) -> None:
        ...

    def list(self, ref: ObjectRef) -> list[Mapping[str, Any]]:
        ...

    def emit_event(self, ref: ObjectRef, event: Event) -> None:
        ...


@runtime_checkable
class MetricsSource(Protocol):
    """Per-predictor serving metrics (Prometheus in the reference)."""

    def model_metrics(
        self,
        deployment_name: str,
        predictor_name: str,
        namespace: str,
        window_s: int = 60,
    ) -> ModelMetrics:
        ...
