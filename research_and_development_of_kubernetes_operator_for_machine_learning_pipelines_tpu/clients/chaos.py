"""Fault injection for client protocols (test/chaos harness).

The reference has no fault injection of any kind (SURVEY §5) and its
promotion loop dies on the first unhandled Prometheus/MLflow exception
(``mlflow_operator.py`` only try/excepts the alias lookup, ``:58-62``).
The rebuild's recovery guarantees — reconcile backoff, resumable promotion
state, alias self-healing — are only guarantees if they're exercised, so
this wrapper makes any injected client (kube / registry / metrics) fail on
a script.

``FaultInjector`` proxies attributes of the wrapped client; its own
control surface is ``inject_``-prefixed so it can never shadow a wrapped
method (e.g. ``FakeMetrics.clear``).  Scheduled faults are consumed per
method call:

    metrics = FaultInjector(FakeMetrics())
    metrics.inject_fail("model_metrics", ApiError(503, "prom down"), times=4)
    ...
    metrics.inject_fail_if("apply", lambda ns, name: name == "canary",
                           Conflict(...))

Works against the fakes in tests and equally against the real REST clients
for in-cluster chaos runs.
"""

from __future__ import annotations

import threading
from typing import Any, Callable


class FaultInjector:
    def __init__(self, target: Any):
        self._target = target
        self._lock = threading.Lock()
        self._scheduled: dict[str, list[Exception]] = {}
        self._conditional: dict[str, list[tuple[Callable[..., bool], Exception]]] = {}
        self.proxy_calls: list[tuple[str, tuple, dict]] = []
        self.faults_fired: int = 0

    # -- scheduling ----------------------------------------------------------

    def inject_fail(self, method: str, exc: Exception, times: int = 1) -> None:
        """Fail the next ``times`` calls of ``method`` with ``exc``."""
        with self._lock:
            self._scheduled.setdefault(method, []).extend([exc] * times)

    def inject_fail_if(
        self, method: str, predicate: Callable[..., bool], exc: Exception
    ) -> None:
        """Fail any call of ``method`` whose arguments satisfy ``predicate``
        (checked after scheduled faults; not consumed — fires every time)."""
        with self._lock:
            self._conditional.setdefault(method, []).append((predicate, exc))

    def inject_clear(self, method: str | None = None) -> None:
        with self._lock:
            if method is None:
                self._scheduled.clear()
                self._conditional.clear()
            else:
                self._scheduled.pop(method, None)
                self._conditional.pop(method, None)

    def inject_pending(self, method: str) -> int:
        with self._lock:
            return len(self._scheduled.get(method, []))

    # -- proxying ------------------------------------------------------------

    def __getattr__(self, attr: str) -> Any:
        value = getattr(self._target, attr)
        if not callable(value):
            return value

        def wrapper(*args, **kwargs):
            with self._lock:
                queued = self._scheduled.get(attr)
                if queued:
                    exc = queued.pop(0)
                    self.faults_fired += 1
                    raise exc
                for predicate, exc in self._conditional.get(attr, []):
                    if predicate(*args, **kwargs):
                        self.faults_fired += 1
                        raise exc
                self.proxy_calls.append((attr, args, kwargs))
            return value(*args, **kwargs)

        return wrapper
