"""Fault injection for client protocols (test/chaos harness).

The reference has no fault injection of any kind (SURVEY §5) and its
promotion loop dies on the first unhandled Prometheus/MLflow exception
(``mlflow_operator.py`` only try/excepts the alias lookup, ``:58-62``).
The rebuild's recovery guarantees — reconcile backoff, resumable promotion
state, alias self-healing — are only guarantees if they're exercised, so
this wrapper makes any injected client (kube / registry / metrics) fail on
a script.

``FaultInjector`` proxies attributes of the wrapped client; its own
control surface is ``inject_``-prefixed so it can never shadow a wrapped
method (e.g. ``FakeMetrics.clear``).  Scheduled faults are consumed per
method call:

    metrics = FaultInjector(FakeMetrics())
    metrics.inject_fail("model_metrics", ApiError(503, "prom down"), times=4)
    ...
    metrics.inject_fail_if("apply", lambda ns, name: name == "canary",
                           Conflict(...))

Works against the fakes in tests and equally against the real REST clients
for in-cluster chaos runs.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable


class FaultInjector:
    def __init__(self, target: Any):
        self._target = target
        self._lock = threading.Lock()
        self._scheduled: dict[str, list[Exception]] = {}
        self._conditional: dict[str, list[tuple[Callable[..., bool], Exception]]] = {}
        self.proxy_calls: list[tuple[str, tuple, dict]] = []
        self.faults_fired: int = 0

    # -- scheduling ----------------------------------------------------------

    def inject_fail(self, method: str, exc: Exception, times: int = 1) -> None:
        """Fail the next ``times`` calls of ``method`` with ``exc``."""
        with self._lock:
            self._scheduled.setdefault(method, []).extend([exc] * times)

    def inject_fail_if(
        self, method: str, predicate: Callable[..., bool], exc: Exception
    ) -> None:
        """Fail any call of ``method`` whose arguments satisfy ``predicate``
        (checked after scheduled faults; not consumed — fires every time)."""
        with self._lock:
            self._conditional.setdefault(method, []).append((predicate, exc))

    def inject_clear(self, method: str | None = None) -> None:
        with self._lock:
            if method is None:
                self._scheduled.clear()
                self._conditional.clear()
            else:
                self._scheduled.pop(method, None)
                self._conditional.pop(method, None)

    def inject_pending(self, method: str) -> int:
        with self._lock:
            return len(self._scheduled.get(method, []))

    # -- proxying ------------------------------------------------------------

    def __getattr__(self, attr: str) -> Any:
        value = getattr(self._target, attr)
        if not callable(value):
            return value

        def wrapper(*args, **kwargs):
            with self._lock:
                queued = self._scheduled.get(attr)
                if queued:
                    exc = queued.pop(0)
                    self.faults_fired += 1
                    raise exc
                for predicate, exc in self._conditional.get(attr, []):
                    if predicate(*args, **kwargs):
                        self.faults_fired += 1
                        raise exc
                self.proxy_calls.append((attr, args, kwargs))
            return value(*args, **kwargs)

        return wrapper


class ChaosProxy:
    """Data-plane fault injection: a TCP proxy whose failures are scripted.

    ``FaultInjector`` above covers the *control* plane (Python clients
    whose method calls can raise on a script); the data plane — the
    native router proxying bytes to live replica sockets — needs faults
    at the WIRE level.  Park a ChaosProxy between the router and a real
    backend (``--backend name=127.0.0.1:<proxy.port>:w``) and script the
    three failure shapes the failure-containment layer must contain:

    - ``inject_refuse(times)``: the next ``times`` connections are
      accepted and immediately reset — the upstream dies before any
      response byte (connect-level failure: trips circuits, is
      failover-idempotent);
    - ``inject_kill_midstream(times, after_bytes)``: the request is
      relayed, then the response is cut after ``after_bytes`` bytes —
      generation has started, so the request is NOT failover-eligible
      (typed 503 / SSE terminal error territory);
    - ``inject_slow(delay_s, times)``: the response is held for
      ``delay_s`` before relaying (deadline-exceeded shape for probe /
      client-timeout tests).

    Unscripted connections pass through byte-for-byte, both directions,
    so the proxy is invisible until a fault is scheduled.  ``stop()``
    closes the listener entirely — the classic dead-pod ECONNREFUSED —
    and ``restart()`` brings it back on the SAME port (the half-open
    probe re-admission story).  Thread-per-connection: chaos tests run a
    handful of concurrent requests, not production load.
    """

    def __init__(self, upstream_port: int, host: str = "127.0.0.1"):
        self.upstream = (host, int(upstream_port))
        self._lock = threading.Lock()
        # Scripted modes, consumed one per ACCEPTED connection, in
        # schedule order: ("refuse", None) | ("kill", after_bytes) |
        # ("slow", delay_s).
        self._script: list[tuple[str, float | int | None]] = []
        self.connections = 0
        self.faults_fired = 0
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._stopping = False
        # Live relay sockets, severed on stop(): a dead pod kills its
        # established connections too, not just the listener.
        self._active: set[socket.socket] = set()
        self.port = 0
        self._bind()

    # -- scripting -----------------------------------------------------------

    def inject_refuse(self, times: int = 1) -> None:
        with self._lock:
            self._script.extend([("refuse", None)] * times)

    def inject_kill_midstream(
        self, times: int = 1, after_bytes: int = 1
    ) -> None:
        with self._lock:
            self._script.extend([("kill", int(after_bytes))] * times)

    def inject_slow(self, delay_s: float, times: int = 1) -> None:
        with self._lock:
            self._script.extend([("slow", float(delay_s))] * times)

    def inject_clear(self) -> None:
        with self._lock:
            self._script.clear()

    def inject_pending(self) -> int:
        with self._lock:
            return len(self._script)

    # -- lifecycle -----------------------------------------------------------

    def _bind(self) -> None:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", self.port))  # 0 first time; sticky after
        listener.listen(64)
        self.port = listener.getsockname()[1]
        self._listener = listener
        self._stopping = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="chaos-proxy"
        )
        self._accept_thread.start()

    def stop(self) -> None:
        """The hard kill: close the listener (new connections see
        ECONNREFUSED) AND sever every established relay — a dead pod
        takes its open sockets with it, which is exactly what the
        router's before-first-byte/EOF-mid-response handling must
        contain."""
        self._stopping = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            # The accept thread may be BLOCKED inside accept() — CPython
            # defers the fd close while another thread is in a socket
            # call, so the OS keeps accepting into the backlog.  One
            # self-connection wakes it; the post-stop accept is dropped
            # by the loop's stopping check.
            try:
                socket.create_connection(
                    ("127.0.0.1", self.port), timeout=1
                ).close()
            except OSError:
                pass
            self._listener = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        with self._lock:
            active = list(self._active)
        for s in active:
            # shutdown, not close: relay threads may be blocked inside
            # recv on these sockets, and CPython defers the fd close
            # while another thread is in a socket call.
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def restart(self) -> None:
        """Re-listen on the SAME port (the pod-restarted shape the
        half-open probe re-admits)."""
        if self._listener is None:
            self._bind()

    # -- relay ---------------------------------------------------------------

    def _next_fault(self) -> tuple[str, float | int | None] | None:
        with self._lock:
            if self._script:
                self.faults_fired += 1
                return self._script.pop(0)
        return None

    def _accept_loop(self) -> None:
        listener = self._listener
        while not self._stopping and listener is not None:
            try:
                client, _ = listener.accept()
            except OSError:  # listener closed by stop()
                return
            if self._stopping:
                # Accepted between stop() and the fd actually closing
                # (incl. the wake-up poke): a dead pod serves nobody.
                try:
                    client.close()
                except OSError:
                    pass
                return
            self.connections += 1
            fault = self._next_fault()
            if fault is not None and fault[0] == "refuse":
                # Before-first-byte death: RST beats FIN here (a FIN on
                # an unanswered request is the same EOF-mid-response
                # shape; RST is the unambiguous connect-level failure).
                try:
                    client.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        b"\x01\x00\x00\x00\x00\x00\x00\x00",
                    )
                    client.close()
                except OSError:
                    pass
                continue
            threading.Thread(
                target=self._relay, args=(client, fault), daemon=True
            ).start()

    def _relay(self, client: socket.socket, fault) -> None:
        mode, arg = fault if fault is not None else (None, None)
        try:
            up = socket.create_connection(self.upstream, timeout=10)
        except OSError:
            client.close()
            return
        with self._lock:
            self._active.add(client)
            self._active.add(up)
        stop = threading.Event()

        def pump_up() -> None:  # client -> upstream, transparent
            try:
                while not stop.is_set():
                    data = client.recv(65536)
                    if not data:
                        break
                    up.sendall(data)
            except OSError:
                pass
            finally:
                try:
                    up.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t = threading.Thread(target=pump_up, daemon=True)
        t.start()
        relayed = 0
        try:
            if mode == "slow":
                # Hold the RESPONSE, not the request: the upstream gets
                # the work; the caller waits past its deadline.
                time.sleep(float(arg))
            while True:
                data = up.recv(65536)
                if not data:
                    break
                if mode == "kill":
                    take = max(0, int(arg) - relayed)
                    client.sendall(data[:take])
                    relayed += len(data[:take])
                    if relayed >= int(arg):
                        # Mid-stream kill: response bytes are out, then
                        # the connection dies (EOF mid-response — the
                        # first-byte-seen failure class).
                        break
                else:
                    client.sendall(data)
                    relayed += len(data)
        except OSError:
            pass
        finally:
            stop.set()
            with self._lock:
                self._active.discard(client)
                self._active.discard(up)
            for s in (client, up):
                # shutdown BEFORE close: pump_up is blocked in recv on
                # this socket, and CPython defers the fd close while
                # another thread is inside a socket call — without the
                # shutdown the peer never sees the connection die.
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
