"""In-memory fakes for the three external systems (SURVEY.md §4).

The reference has no tests and no fakes; these are the seams the rebuild's
test strategy is built on:

- ``FakeRegistry``  — alias -> version map with mutation helpers, standing in
  for the MLflow registry.
- ``FakeKube``      — an in-memory object store with real Kubernetes
  semantics: resourceVersion bumping, 404 on missing, 409 on stale replace
  (the failure mode the reference provokes but never handles,
  ``mlflow_operator.py:256-269``), recorded events.
- ``FakeMetrics``   — scripted per-predictor metric readings to drive the
  promotion gate through promote / hold / fail / rollback paths.
"""

from __future__ import annotations

import copy
import queue as _queue
import threading
from typing import Any, Callable, Mapping

from .base import (
    AliasNotFound,
    Conflict,
    EngineMetrics,
    Event,
    ModelMetrics,
    ModelVersion,
    NotFound,
    ObjectRef,
    RegistryError,
    WatchEvent,
)


class FakeRegistry:
    """Dict-backed model registry: ``(model, alias) -> ModelVersion``."""

    def __init__(self):
        self._aliases: dict[tuple[str, str], str] = {}
        self._versions: dict[tuple[str, str], ModelVersion] = {}
        self.fail_next: Exception | None = None  # inject a transport error

    # -- test setup helpers -------------------------------------------------
    def register(self, model: str, version: str, source: str) -> None:
        self._versions[(model, version)] = ModelVersion(version=version, source=source)

    def set_alias(self, model: str, alias: str, version: str) -> None:
        if (model, version) not in self._versions:
            raise KeyError(f"register version {version} first")
        self._aliases[(model, alias)] = version

    def drop_alias(self, model: str, alias: str) -> None:
        self._aliases.pop((model, alias), None)

    # -- RegistryClient protocol -------------------------------------------
    def get_version_by_alias(self, model_name: str, alias: str) -> ModelVersion:
        if self.fail_next is not None:
            err, self.fail_next = self.fail_next, None
            raise err
        try:
            version = self._aliases[(model_name, alias)]
        except KeyError:
            raise AliasNotFound(
                f"alias {alias!r} not found on model {model_name!r}"
            ) from None
        return self._versions[(model_name, version)]

    def get_version(self, model_name: str, version: str) -> ModelVersion:
        try:
            return self._versions[(model_name, version)]
        except KeyError:
            raise RegistryError(
                f"model {model_name!r} has no version {version!r}"
            ) from None


class FakeKube:
    """In-memory Kubernetes custom-objects store.

    Keyed by ``(group, plural, namespace, name)``.  Implements optimistic
    concurrency: ``replace`` requires the body's ``metadata.resourceVersion``
    to match the stored one (or be absent), else raises ``Conflict`` — the
    same contract as a real API server, which the reference relies on at
    ``mlflow_operator.py:256-269``.
    """

    def __init__(self):
        self._objects: dict[tuple[str, str, str, str], dict] = {}
        self._rv_counter = 0
        self._lock = threading.RLock()
        self.events: list[tuple[str, Event]] = []  # (object name, event)
        self.apply_log: list[dict] = []  # every create/replace body, in order
        # Live watch subscriptions: each is a queue fed by every mutation.
        self._watchers: list[_queue.Queue] = []

    def _next_rv(self) -> str:
        self._rv_counter += 1
        return str(self._rv_counter)

    def _broadcast(self, ref: ObjectRef, type_: str, obj: dict) -> None:
        ev = WatchEvent(type=type_, object=copy.deepcopy(obj))
        for q in list(self._watchers):
            q.put((ref.group, ref.plural, ev))

    @staticmethod
    def _key(ref: ObjectRef) -> tuple[str, str, str, str]:
        return (ref.group, ref.plural, ref.namespace, ref.name)

    def get(self, ref: ObjectRef) -> dict:
        with self._lock:
            try:
                return copy.deepcopy(self._objects[self._key(ref)])
            except KeyError:
                raise NotFound(f"{ref.plural}/{ref.name}") from None

    def list(self, ref: ObjectRef) -> list[dict]:
        with self._lock:
            return [
                copy.deepcopy(obj)
                for (g, p, ns, _), obj in self._objects.items()
                if g == ref.group
                and p == ref.plural
                and (not ref.namespace or ns == ref.namespace)
            ]

    def create(self, ref: ObjectRef, body: Mapping[str, Any]) -> dict:
        with self._lock:
            key = self._key(ref)
            if key in self._objects:
                raise Conflict(f"{ref.plural}/{ref.name} already exists")
            obj = copy.deepcopy(dict(body))
            obj.setdefault("metadata", {})
            obj["metadata"]["name"] = ref.name
            obj["metadata"]["namespace"] = ref.namespace
            obj["metadata"]["resourceVersion"] = self._next_rv()
            obj["metadata"].setdefault("uid", f"uid-{ref.name}")
            # Real API-server semantics: generation starts at 1 and bumps
            # only on spec changes (status patches leave it alone).
            obj["metadata"]["generation"] = 1
            self._objects[key] = obj
            self.apply_log.append(copy.deepcopy(obj))
            self._broadcast(ref, "ADDED", obj)
            return copy.deepcopy(obj)

    def replace(self, ref: ObjectRef, body: Mapping[str, Any]) -> dict:
        with self._lock:
            key = self._key(ref)
            if key not in self._objects:
                raise NotFound(f"{ref.plural}/{ref.name}")
            stored_rv = self._objects[key]["metadata"]["resourceVersion"]
            sent_rv = dict(body).get("metadata", {}).get("resourceVersion")
            if sent_rv is not None and sent_rv != stored_rv:
                raise Conflict(
                    f"stale resourceVersion {sent_rv} (stored {stored_rv})"
                )
            obj = copy.deepcopy(dict(body))
            obj.setdefault("metadata", {})
            obj["metadata"]["name"] = ref.name
            obj["metadata"]["namespace"] = ref.namespace
            obj["metadata"]["resourceVersion"] = self._next_rv()
            obj["metadata"].setdefault("uid", self._objects[key]["metadata"].get("uid"))
            old_gen = self._objects[key]["metadata"].get("generation", 1)
            spec_changed = obj.get("spec") != self._objects[key].get("spec")
            obj["metadata"]["generation"] = old_gen + 1 if spec_changed else old_gen
            # status is a subresource: plain replace does not change it
            if "status" in self._objects[key]:
                obj["status"] = copy.deepcopy(self._objects[key]["status"])
            self._objects[key] = obj
            self.apply_log.append(copy.deepcopy(obj))
            self._broadcast(ref, "MODIFIED", obj)
            return copy.deepcopy(obj)

    def patch_status(self, ref: ObjectRef, status: Mapping[str, Any]) -> dict:
        with self._lock:
            key = self._key(ref)
            if key not in self._objects:
                raise NotFound(f"{ref.plural}/{ref.name}")
            obj = self._objects[key]
            merged = dict(obj.get("status") or {})
            merged.update(copy.deepcopy(dict(status)))
            obj["status"] = merged
            obj["metadata"]["resourceVersion"] = self._next_rv()
            self._broadcast(ref, "MODIFIED", obj)
            return copy.deepcopy(obj)

    def delete(self, ref: ObjectRef) -> None:
        with self._lock:
            key = self._key(ref)
            if key not in self._objects:
                raise NotFound(f"{ref.plural}/{ref.name}")
            gone = self._objects.pop(key)
            self._broadcast(ref, "DELETED", gone)

    def emit_event(self, ref: ObjectRef, event: Event) -> None:
        with self._lock:
            self.events.append((ref.name, event))

    def list_with_version(self, ref: ObjectRef) -> tuple[list[dict], str]:
        with self._lock:
            return self.list(ref), str(self._rv_counter)

    def watch(
        self,
        ref: ObjectRef,
        resource_version: str | None = None,
        timeout_s: int = 300,
        stop=None,
    ):
        """Generator of WatchEvents for mutations after subscription.

        Delivers only post-subscription events (the fake keeps no history,
        so ``resource_version`` is accepted but unused -- callers list
        first, exactly like against the real API server).  Ends when
        ``stop`` is set, mimicking the server closing an idle watch.
        """
        # Register eagerly (watch() is NOT a generator): the queue must be
        # live the moment watch() returns, or mutations between a caller's
        # list() and its first next() would be dropped — the fake keeps no
        # history to replay them from.
        q: _queue.Queue = _queue.Queue()
        with self._lock:
            self._watchers.append(q)
        return self._drain_watch(q, ref, stop)

    def _drain_watch(self, q: _queue.Queue, ref: ObjectRef, stop):
        try:
            while stop is None or not stop.is_set():
                try:
                    group, plural, ev = q.get(timeout=0.05)
                except _queue.Empty:
                    continue
                if group != ref.group or plural != ref.plural:
                    continue
                meta = ev.object.get("metadata") or {}
                if (
                    ref.namespace
                    and meta.get("namespace")
                    and meta["namespace"] != ref.namespace
                ):
                    continue
                yield ev
        finally:
            with self._lock:
                self._watchers.remove(q)

    # -- test helpers -------------------------------------------------------
    def event_reasons(self) -> list[str]:
        return [e.reason for _, e in self.events]


class FakeMetrics:
    """Scripted metrics source.

    Set a constant reading per predictor with ``set_metrics``, or a callable
    ``(window_s) -> ModelMetrics`` with ``set_series`` for time-varying
    behavior.  Unknown predictors return the reference's no-traffic shape:
    all gating metrics ``None`` (``mlflow_operator.py:372,:390,:404``).
    """

    def __init__(self):
        self._readings: dict[tuple[str, str, str], Callable[[int], ModelMetrics]] = {}
        self.query_log: list[tuple[str, str, str]] = []
        # Engine-saturation readings for the replica autoscaler
        # (mirrors PrometheusSource.engine_metrics).  Unknown predictors
        # return the all-None shape = signal unavailable, which the
        # autoscaler treats as "hold".
        self._engine: dict[
            tuple[str, str, str], Callable[[int], EngineMetrics]
        ] = {}
        self.engine_query_log: list[tuple[str, str, str]] = []

    def set_metrics(
        self, deployment: str, predictor: str, namespace: str, metrics: ModelMetrics
    ) -> None:
        self._readings[(deployment, predictor, namespace)] = lambda _w: metrics

    def set_series(
        self,
        deployment: str,
        predictor: str,
        namespace: str,
        fn: Callable[[int], ModelMetrics],
    ) -> None:
        self._readings[(deployment, predictor, namespace)] = fn

    def clear(self, deployment: str, predictor: str, namespace: str) -> None:
        self._readings.pop((deployment, predictor, namespace), None)

    def model_metrics(
        self,
        deployment_name: str,
        predictor_name: str,
        namespace: str,
        window_s: int = 60,
    ) -> ModelMetrics:
        self.query_log.append((deployment_name, predictor_name, namespace))
        fn = self._readings.get((deployment_name, predictor_name, namespace))
        if fn is None:
            return ModelMetrics()  # no traffic: latency/error metrics all None
        return fn(window_s)

    def set_engine_metrics(
        self, deployment: str, predictor: str, namespace: str, metrics: EngineMetrics
    ) -> None:
        self._engine[(deployment, predictor, namespace)] = lambda _w: metrics

    def set_engine_series(
        self,
        deployment: str,
        predictor: str,
        namespace: str,
        fn: Callable[[int], EngineMetrics],
    ) -> None:
        self._engine[(deployment, predictor, namespace)] = fn

    def engine_metrics(
        self,
        deployment_name: str,
        predictor_name: str,
        namespace: str,
        window_s: int = 60,
        slo_tails: bool = False,
    ) -> EngineMetrics:
        # ``slo_tails`` is accepted for interface parity (real sources
        # gate the p99 work on it); scripted readings carry whatever the
        # test set regardless.
        self.engine_query_log.append(
            (deployment_name, predictor_name, namespace)
        )
        fn = self._engine.get((deployment_name, predictor_name, namespace))
        if fn is None:
            return EngineMetrics()  # unavailable: autoscaler holds
        return fn(window_s)
