"""Data-plane client: synthetic warm-up traffic to a predictor.

Solves the zero-traffic deadlock (SURVEY §3.5(4)): a 10%-weight canary may
never accumulate the samples the gate needs.  The operator POSTs a burst of
V2 inference requests directly to the canary predictor's service (bypassing
the Istio split, so the burst cannot skew the stable model's metrics).

The service URL follows Seldon's naming (``<deployment>-<predictor>`` svc in
the model namespace); override with ``url_template`` for other layouts.
"""

from __future__ import annotations

import logging

import httpx

_log = logging.getLogger(__name__)

DEFAULT_URL_TEMPLATE = (
    "http://{deployment}-{predictor}.{namespace}:9000/v2/models/{model}/infer"
)


class DataPlaneWarmup:
    def __init__(
        self,
        url_template: str = DEFAULT_URL_TEMPLATE,
        timeout: float = 2.0,
        max_wall_s: float = 10.0,
        example: dict | None = None,
    ):
        self.url_template = url_template
        # Short per-request timeout AND an overall deadline: warmup runs on
        # the single-threaded reconcile loop, so a hanging canary must never
        # stall other resources' gate checks (reconciler design contract).
        self.timeout = timeout
        self.max_wall_s = max_wall_s
        # A 1-element FP32 vector by default; model-specific warmup bodies
        # can be injected per-operator via ``example``.
        self.example = example or {
            "inputs": [
                {"name": "x", "shape": [1, 1], "datatype": "FP32", "data": [0.0]}
            ]
        }

    def __call__(
        self,
        deployment: str,
        predictor: str,
        namespace: str,
        n: int,
        model: str | None = None,
    ) -> int:
        import time

        # The V2 infer route is registered under spec.modelName (server
        # app.py), which need not equal the deployment/CR name.
        url = self.url_template.format(
            deployment=deployment,
            predictor=predictor,
            namespace=namespace,
            model=model or deployment,
        )
        ok = 0
        deadline = time.monotonic() + self.max_wall_s
        with httpx.Client(timeout=self.timeout) as client:
            for _ in range(n):
                if time.monotonic() > deadline:
                    _log.info("warmup wall-time budget exhausted")
                    break
                try:
                    resp = client.post(url, json=self.example)
                    # Only a handled inference counts: a 404/400 produces no
                    # request metric, so counting it would report a warmup
                    # that unblocks nothing.
                    if 200 <= resp.status_code < 300:
                        ok += 1
                    else:
                        _log.debug(
                            "warmup request to %s got %d", url, resp.status_code
                        )
                except httpx.HTTPError as e:
                    _log.debug("warmup request failed: %s", e)
        _log.info("warmup: %d/%d requests served by %s", ok, n, url)
        return ok
