"""envtest-style in-process Kubernetes API server (real HTTP, real watches).

VERDICT r2 "missing #4": the REST client's wire behavior had only ever been
tested against scripted httpx responses; the reference's README walkthrough
(`/root/reference/README.md:44-58`) assumes a live apiserver this
environment cannot provide (no docker/kind).  This module is the
controller-runtime ``envtest`` idea scaled to what the operator actually
uses: a threaded HTTP server speaking the CustomObjects + corev1-events
subset of the Kubernetes API with faithful semantics for

- **resourceVersion** — one monotonic counter; PUT with a stale
  ``metadata.resourceVersion`` is a 409 (the optimistic-concurrency seam
  ``Reconciler._apply_object`` retries on);
- **generation** — bumped only when ``spec`` changes (what the watch
  runtime's generation-gated notify relies on);
- **merge-patch /status** — RFC 7386 merge on the status subresource with
  no generation bump;
- **watch streams** — chunked JSON-lines with ADDED/MODIFIED/DELETED
  events from the collection's change log, honoring ``resourceVersion``
  resume cursors, ``timeoutSeconds``, and emitting a 410-coded ERROR
  event when the cursor predates the retained log (`compact()` forces
  this for tests — the 410 recovery path CrWatcher must survive);
- **bearer-token auth** — 401 without the expected token (exercises the
  client's token-refresh path when combined with a token file).

Not implemented (the operator does not use them): field selectors, server
-side apply, OpenAPI validation, RBAC.  Use::

    with EnvtestServer(token="secret") as srv:
        client = KubeRestClient(base_url=srv.url, token="secret")
        ...

Runs entirely on loopback TCP — the same bytes a real apiserver would see.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

__all__ = ["EnvtestServer"]


def _merge(base: dict, patch: Any) -> Any:
    """RFC 7386 merge patch."""
    if not isinstance(patch, dict):
        return patch
    out = dict(base) if isinstance(base, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge(out.get(k, {}), v)
    return out


class _State:
    """Object store + per-collection change logs, one lock."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.rv = 0
        # (collection key, namespace, name) -> object dict
        self.objects: dict[tuple[str, str, str], dict] = {}
        # collection key -> list of (rv, event dict); compact() trims it
        self.log: dict[str, list[tuple[int, dict]]] = {}
        self.log_floor: dict[str, int] = {}
        # collection key -> condition to wake blocked watchers
        self.cond = threading.Condition(self.lock)

    def next_rv(self) -> int:
        self.rv += 1
        return self.rv

    def record(self, coll: str, etype: str, obj: dict) -> None:
        rv = int(obj["metadata"]["resourceVersion"])
        self.log.setdefault(coll, []).append((rv, {"type": etype, "object": obj}))
        self.cond.notify_all()

    def compact(self, coll: str, floor_rv: int) -> None:
        """Drop log entries at/below ``floor_rv`` — subsequent watches
        resuming from an older cursor get the 410 a real apiserver would
        produce after etcd compaction."""
        with self.lock:
            self.log_floor[coll] = max(self.log_floor.get(coll, 0), floor_rv)
            self.log[coll] = [
                (rv, e) for rv, e in self.log.get(coll, []) if rv > floor_rv
            ]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "envtest"
    state: _State  # set by EnvtestServer subclassing
    token: str | None

    # -- plumbing ----------------------------------------------------------

    def log_message(self, *a):  # quiet
        pass

    def _auth_ok(self) -> bool:
        if not self.token:
            return True
        return self.headers.get("Authorization") == f"Bearer {self.token}"

    def _body(self) -> Any:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return json.loads(raw) if raw else None

    def _send(self, code: int, payload: Any) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _status(self, code: int, reason: str, message: str) -> None:
        self._send(
            code,
            {
                "kind": "Status",
                "apiVersion": "v1",
                "status": "Failure",
                "reason": reason,
                "message": message,
                "code": code,
            },
        )

    # -- path parsing ------------------------------------------------------

    def _parse(self):
        """-> (collection key, namespace, name, subresource, query dict)."""
        from urllib.parse import parse_qs, urlparse

        u = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        parts = [p for p in u.path.split("/") if p]
        # /apis/{group}/{version}/namespaces/{ns}/{plural}[/{name}[/{sub}]]
        # /api/{version}/namespaces/{ns}/{plural}[/{name}[/{sub}]]
        if not parts or parts[0] not in ("apis", "api"):
            return None
        idx = 3 if parts[0] == "apis" else 2
        group_version = "/".join(parts[1:idx])
        ns = None
        if len(parts) > idx and parts[idx] == "namespaces":
            ns = parts[idx + 1]
            idx += 2
        if len(parts) <= idx:
            return None
        plural = parts[idx]
        name = parts[idx + 1] if len(parts) > idx + 1 else None
        sub = parts[idx + 2] if len(parts) > idx + 2 else None
        # Collection key is namespace-agnostic so cluster-wide lists and
        # watches (no /namespaces/ segment) see every namespace's objects.
        coll = f"{group_version}/{plural}"
        return coll, ns, name, sub, q

    # -- verbs -------------------------------------------------------------

    def do_GET(self):
        if not self._auth_ok():
            return self._status(401, "Unauthorized", "bad token")
        parsed = self._parse()
        if not parsed:
            return self._status(404, "NotFound", "bad path")
        coll, ns, name, _sub, q = parsed
        st = self.state
        if name is None and q.get("watch") in ("1", "true"):
            return self._watch(coll, q, ns)
        with st.lock:
            if name is None:
                items = [
                    obj
                    for (c, ons, _n), obj in st.objects.items()
                    if c == coll and (ns is None or ons == ns)
                ]
                return self._send(
                    200,
                    {
                        "kind": "List",
                        "items": items,
                        "metadata": {"resourceVersion": str(st.rv)},
                    },
                )
            obj = st.objects.get((coll, ns or "", name))
            if obj is None:
                return self._status(404, "NotFound", f"{coll}/{name}")
            return self._send(200, obj)

    def do_POST(self):
        # Read the body FIRST, even on error paths: an undrained body
        # desyncs the keep-alive connection — the next request on the
        # pooled socket gets parsed out of leftover body bytes.
        body = self._body() or {}
        if not self._auth_ok():
            return self._status(401, "Unauthorized", "bad token")
        parsed = self._parse()
        if not parsed:
            return self._status(404, "NotFound", "bad path")
        coll, ns, _name, _sub, _q = parsed
        st = self.state
        name = (body.get("metadata") or {}).get("generateName")
        with st.lock:
            meta = dict(body.get("metadata") or {})
            if name:  # corev1 events use generateName
                meta["name"] = f"{name}{uuid.uuid4().hex[:6]}"
            if not meta.get("name"):
                return self._status(422, "Invalid", "metadata.name required")
            key = (coll, ns or "", meta["name"])
            if key in st.objects:
                return self._status(409, "AlreadyExists", meta["name"])
            meta.setdefault("namespace", ns)
            meta["uid"] = uuid.uuid4().hex
            meta["resourceVersion"] = str(st.next_rv())
            meta["generation"] = 1
            obj = dict(body)
            obj["metadata"] = meta
            st.objects[key] = obj
            st.record(coll, "ADDED", obj)
            return self._send(201, obj)

    def do_PUT(self):
        body = self._body() or {}  # drain first (see do_POST)
        if not self._auth_ok():
            return self._status(401, "Unauthorized", "bad token")
        parsed = self._parse()
        if not parsed or parsed[2] is None:
            return self._status(404, "NotFound", "bad path")
        coll, ns, name, _sub, _q = parsed
        st = self.state
        with st.lock:
            key = (coll, ns or "", name)
            old = st.objects.get(key)
            if old is None:
                return self._status(404, "NotFound", name)
            sent_rv = (body.get("metadata") or {}).get("resourceVersion")
            if sent_rv is not None and sent_rv != old["metadata"]["resourceVersion"]:
                return self._status(
                    409, "Conflict", f"stale resourceVersion {sent_rv}"
                )
            meta = dict(old["metadata"])
            meta["resourceVersion"] = str(st.next_rv())
            if body.get("spec") != old.get("spec"):
                meta["generation"] = int(meta.get("generation", 1)) + 1
            obj = dict(body)
            obj["metadata"] = meta
            # Status subresource semantics: PUT to the main resource
            # ignores the body's "status" and preserves the server-held
            # one — otherwise every operator manifest apply would wipe
            # the status its own patch_status wrote (real apiservers
            # with a status subresource behave this way).
            obj.pop("status", None)
            if "status" in old:
                obj["status"] = old["status"]
            st.objects[key] = obj
            st.record(coll, "MODIFIED", obj)
            return self._send(200, obj)

    def do_PATCH(self):
        patch = self._body() or {}  # drain first (see do_POST)
        if not self._auth_ok():
            return self._status(401, "Unauthorized", "bad token")
        parsed = self._parse()
        if not parsed or parsed[2] is None:
            return self._status(404, "NotFound", "bad path")
        coll, ns, name, sub, _q = parsed
        if "merge-patch" not in (self.headers.get("Content-Type") or ""):
            return self._status(415, "UnsupportedMediaType", "merge-patch only")
        st = self.state
        with st.lock:
            key = (coll, ns or "", name)
            old = st.objects.get(key)
            if old is None:
                return self._status(404, "NotFound", name)
            if sub == "status":
                patch = {"status": patch.get("status", {})}
            obj = _merge(old, patch)
            meta = dict(obj["metadata"])
            meta["resourceVersion"] = str(st.next_rv())
            # status patches never bump generation; spec merge would.
            if sub != "status" and obj.get("spec") != old.get("spec"):
                meta["generation"] = int(meta.get("generation", 1)) + 1
            obj["metadata"] = meta
            st.objects[key] = obj
            st.record(coll, "MODIFIED", obj)
            return self._send(200, obj)

    def do_DELETE(self):
        if not self._auth_ok():
            return self._status(401, "Unauthorized", "bad token")
        parsed = self._parse()
        if not parsed or parsed[2] is None:
            return self._status(404, "NotFound", "bad path")
        coll, ns, name, _sub, _q = parsed
        st = self.state
        with st.lock:
            obj = st.objects.pop((coll, ns or "", name), None)
            if obj is None:
                return self._status(404, "NotFound", name)
            meta = dict(obj["metadata"])
            meta["resourceVersion"] = str(st.next_rv())
            obj = dict(obj)
            obj["metadata"] = meta
            st.record(coll, "DELETED", obj)
            return self._send(200, obj)

    # -- watch -------------------------------------------------------------

    def _watch(self, coll: str, q: dict, ns: str | None = None) -> None:
        st = self.state
        deadline = time.monotonic() + float(q.get("timeoutSeconds") or 300)
        cursor = int(q.get("resourceVersion") or 0)

        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def write_line(payload: dict) -> bool:
            data = json.dumps(payload).encode() + b"\n"
            try:
                self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                self.wfile.flush()
                return True
            except OSError:
                return False  # client went away

        with st.lock:
            # Strict: a cursor exactly at the floor misses nothing — the
            # floor IS the rv a fresh post-compaction list returns, and
            # 410ing it would spin CrWatcher in a list->watch->410 loop.
            if cursor and cursor < st.log_floor.get(coll, 0):
                write_line(
                    {
                        "type": "ERROR",
                        "object": {
                            "kind": "Status",
                            "code": 410,
                            "reason": "Expired",
                            "message": f"resourceVersion {cursor} compacted",
                        },
                    }
                )
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass
                return

        def select(cur):
            return [
                e
                for rv, e in st.log.get(coll, [])
                if rv > cur
                and (
                    ns is None
                    or e["object"].get("metadata", {}).get("namespace") == ns
                )
            ]

        while time.monotonic() < deadline:
            with st.cond:
                pending = select(cursor)
                if not pending:
                    st.cond.wait(timeout=0.2)
                    pending = select(cursor)
            for event in pending:
                cursor = int(event["object"]["metadata"]["resourceVersion"])
                if not write_line(event):
                    return
        try:
            self.wfile.write(b"0\r\n\r\n")  # clean chunked EOF on timeout
        except OSError:
            pass


class EnvtestServer:
    """Threaded loopback apiserver; ``url`` is its base URL."""

    def __init__(self, token: str | None = None):
        self.state = _State()
        handler = type(
            "BoundHandler", (_Handler,), {"state": self.state, "token": token}
        )
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def start(self) -> "EnvtestServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "EnvtestServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # test helper: force "etcd compaction" so old watch cursors 410
    def compact(self, group_version: str, plural: str) -> None:
        self.state.compact(f"{group_version}/{plural}", self.state.rv)
