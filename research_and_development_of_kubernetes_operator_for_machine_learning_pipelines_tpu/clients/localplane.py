"""Local data-plane harness: real servers + native router, no cluster.

One implementation shared by the e2e tests (tests/test_e2e_localplane.py)
and the benchmark of record (bench.py) — both drive a full unscripted
canary where the predictors are live aiohttp/JAX servers, traffic flows
through the compiled ``native/router.cc`` split, and the gate reads the
router's real histograms.  The pieces map to the reference's production
loop (``mlflow_operator.py:56-361``):

    reference            here
    ------------------   ------------------------------------------
    Seldon MLFLOW_SERVER server.app (JAX data plane)
    Istio traffic split  native/router.cc smooth-WRR split
    Seldon executor      router's seldon_api_executor_* histograms
    kopf + API server    OperatorRuntime + FakeKube
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time
import urllib.request

from .base import SELDONDEPLOYMENT, EngineMetrics, ModelMetrics
from .fakes import FakeKube
from .router import RouterSync, parse_prometheus_text

__all__ = [
    "free_port",
    "ModelServerHandle",
    "start_model_server",
    "SyncingKube",
    "TrafficGenerator",
    "train_iris_pair",
    "relaxed_gate_spec",
    "LocalReplicaSet",
    "ReplicaSetMetrics",
]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ModelServerHandle:
    """A live inference server on a daemon thread, stoppable."""

    def __init__(self, server, loop, port: int, runner=None):
        self.server = server
        self.loop = loop
        self.port = port
        self.runner = runner

    def stop(self) -> None:
        # Run the aiohttp cleanup (closes the listening socket) before
        # stopping the loop — a bare loop.stop() leaves the port bound,
        # and a later client probing it would hang instead of failing.
        async def _cleanup():
            if self.runner is not None:
                await self.runner.cleanup()
            self.loop.stop()

        asyncio.run_coroutine_threadsafe(_cleanup(), self.loop)
        self.server.shutdown()


def start_model_server(
    model_uri: str,
    predictor: str,
    port: int,
    model_name: str = "iris",
    deployment_name: str | None = None,
    namespace: str = "models",
    tpu=None,
    ready_timeout_s: float = 180.0,
    warmup: bool = True,
    wake_start_wall: float | None = None,
) -> ModelServerHandle:
    """Run a real inference server (aiohttp) on a daemon thread; raises
    TimeoutError if it never becomes ready.  ``wake_start_wall`` (unix
    seconds) marks when the controller decided to wake this replica —
    it anchors the server's ``tpumlops_cold_start_seconds`` ladder."""
    from ..server.app import build_server
    from ..utils.config import ServerConfig

    cfg_kwargs = dict(
        model_name=model_name,
        model_uri=model_uri,
        deployment_name=deployment_name or model_name,
        predictor_name=predictor,
        namespace=namespace,
        port=port,
    )
    if tpu is not None:
        cfg_kwargs["tpu"] = tpu
    server = build_server(
        ServerConfig(**cfg_kwargs),
        warmup=warmup,
        wake_start_wall=wake_start_wall,
    )
    loop = asyncio.new_event_loop()
    handle = ModelServerHandle(server, loop, port)
    boot_error: list[BaseException] = []

    def run():
        asyncio.set_event_loop(loop)
        from aiohttp import web

        try:
            runner = web.AppRunner(server.build_app())
            handle.runner = runner
            loop.run_until_complete(runner.setup())
            loop.run_until_complete(
                web.TCPSite(runner, "127.0.0.1", port).start()
            )
        except BaseException as e:  # surface to the waiting caller
            boot_error.append(e)
            # The loop never serves; nothing can clean it up later.
            loop.close()
            return
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    deadline = time.monotonic() + ready_timeout_s
    while time.monotonic() < deadline:
        if boot_error:
            server.shutdown()  # loop is closed; only the engine needs stopping
            raise RuntimeError(
                f"model server on :{port} failed to start"
            ) from boot_error[0]
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v2/health/ready", timeout=1
            )
            return handle
        except Exception:
            time.sleep(0.05)
    handle.stop()
    raise TimeoutError(f"model server on :{port} never became ready")


class SyncingKube(FakeKube):
    """FakeKube that plays the Seldon-controller/Istio role: every applied
    SeldonDeployment is pushed into its router as backends + weights.

    ``syncs`` maps deployment name -> RouterSync; a single RouterSync may
    be passed for the one-deployment case.
    """

    def __init__(self, syncs: "RouterSync | dict[str, RouterSync]"):
        super().__init__()
        self._syncs = syncs

    def _sync_for(self, name: str) -> RouterSync | None:
        if isinstance(self._syncs, dict):
            return self._syncs.get(name)
        return self._syncs

    def _push(self, ref, obj) -> None:
        if ref.plural == SELDONDEPLOYMENT["plural"]:
            sync = self._sync_for(ref.name)
            if sync is not None:
                sync.sync_manifest(obj)

    def create(self, ref, body):
        obj = super().create(ref, body)
        self._push(ref, obj)
        return obj

    def replace(self, ref, body):
        obj = super().replace(ref, body)
        self._push(ref, obj)
        return obj


class LocalReplicaSet:
    """The Deployment-controller role for the local plane: make predictor
    ``replicas`` REAL.

    In-cluster, a predictor's ``replicas`` count materializes as pods via
    Seldon/Kubernetes; here each replica is a live inference server on a
    local port.  ``sync_manifest`` diffs an applied SeldonDeployment
    against the running set: scale-up starts servers, scale-down (and
    predictor removal) runs the LOSSLESS drain protocol — the port is
    unlisted from :meth:`ports` first, ``POST /admin/drain`` finishes
    every in-flight sequence, and only then does the server stop — so
    the autoscaler's e2e can prove no request is ever dropped across a
    topology change.
    """

    def __init__(
        self,
        model_uris: dict,  # predictor name -> artifact uri
        model_name: str,
        namespace: str = "models",
        deployment_name: str | None = None,
        tpu=None,  # TpuSpec for every replica server
        drain_grace_s: float = 30.0,
        stop_linger_s: float = 0.5,
        warmup: bool = True,  # False: replicas boot fast, compile lazily
    ):
        self.model_uris = dict(model_uris)
        self.model_name = model_name
        self.namespace = namespace
        self.deployment_name = deployment_name or model_name
        self.tpu = tpu
        self.drain_grace_s = drain_grace_s
        # Post-drain linger before the socket closes: clients that
        # snapshotted the port list just before it was unlisted get
        # their request answered (shed or served), never a connection
        # refusal — the local analogue of the --drain-s endpoint-removal
        # lag in production.
        self.stop_linger_s = stop_linger_s
        self.warmup = warmup
        self._lock = threading.RLock()
        self._replicas: dict[str, list[ModelServerHandle]] = {}
        # Every drain's final /admin/drain response, for the e2e's
        # zero-lost-requests proof.
        self.drain_reports: list[dict] = []
        self.scale_log: list[tuple[str, int]] = []  # (predictor, replicas)
        # Straggler verdicts (anomaly observatory, operator/anomaly.py):
        # ports to drain FIRST when the next scale-down picks victims.
        # Empty (the default) = the historical newest-last choice,
        # byte-identical.
        self.straggler_ports: frozenset = frozenset()

    def set_stragglers(self, ports) -> None:
        """Replace the straggler port set the next scale-down prefers
        as victims (a flagged replica should leave the fleet before a
        healthy one does)."""
        with self._lock:
            self.straggler_ports = frozenset(int(p) for p in ports)

    def ports(self) -> list[int]:
        """Live (non-draining) replica ports, all predictors."""
        with self._lock:
            return [
                h.port for handles in self._replicas.values() for h in handles
            ]

    def replica_ports(self, predictor: str) -> list[int]:
        """Live ports of ONE predictor (router backend resolution)."""
        with self._lock:
            return [h.port for h in self._replicas.get(predictor, [])]

    def replica_count(self, predictor: str | None = None) -> int:
        with self._lock:
            if predictor is not None:
                return len(self._replicas.get(predictor, []))
            return sum(len(v) for v in self._replicas.values())

    def sync_manifest(self, manifest: dict) -> None:
        spec = manifest.get("spec") or {}
        desired = {
            p.get("name"): int(p.get("replicas", 1))
            for p in spec.get("predictors") or []
        }
        with self._lock:
            current = {k: list(v) for k, v in self._replicas.items()}
        # Scale up / create first (capacity before teardown), then drain
        # down — the same order a rolling controller uses.
        for pred, n in desired.items():
            have = len(current.get(pred, []))
            # A predictor going 0 -> n is a WAKE: stamp the decision
            # instant so the replica's tpumlops_cold_start_seconds
            # ladder carries the controller-side wake stage too.
            wake = time.time() if have == 0 and n > 0 else None
            for _ in range(have, n):
                self._start(pred, wake_start_wall=wake)
            if n != have:
                self.scale_log.append((pred, n))
        for pred, handles in current.items():
            keep = desired.get(pred, 0)
            if self.straggler_ports and len(handles) > keep:
                # Stable sort pushes flagged ports into the drained
                # slice; with no verdicts the slice (and every drain
                # order) is exactly what it always was.
                handles = sorted(
                    handles, key=lambda h: h.port in self.straggler_ports
                )
            for handle in handles[keep:]:
                self._drain_stop(pred, handle)

    def _start(
        self, predictor: str, wake_start_wall: float | None = None
    ) -> None:
        uri = self.model_uris[predictor]
        handle = start_model_server(
            uri,
            predictor,
            free_port(),
            model_name=self.model_name,
            deployment_name=self.deployment_name,
            namespace=self.namespace,
            tpu=self.tpu,
            warmup=self.warmup,
            wake_start_wall=wake_start_wall,
        )
        with self._lock:
            self._replicas.setdefault(predictor, []).append(handle)

    def _drain_stop(self, predictor: str, handle: ModelServerHandle) -> None:
        # Unlist BEFORE draining: new traffic must stop targeting this
        # replica while its in-flight tail finishes.
        with self._lock:
            handles = self._replicas.get(predictor, [])
            if handle in handles:
                handles.remove(handle)
            if not handles:
                self._replicas.pop(predictor, None)
        report: dict = {"predictor": predictor, "port": handle.port}
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{handle.port}/admin/drain",
                data=json.dumps({"grace_s": self.drain_grace_s}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(
                req, timeout=self.drain_grace_s + 10
            ) as resp:
                report.update(json.loads(resp.read()))
        except Exception as e:  # drain endpoint gone/failed: record it
            report["error"] = str(e)
        self.drain_reports.append(report)
        if self.stop_linger_s > 0:
            time.sleep(self.stop_linger_s)
        handle.stop()

    def stop_all(self) -> None:
        with self._lock:
            handles = [
                h for hs in self._replicas.values() for h in hs
            ]
            self._replicas.clear()
        for h in handles:
            h.stop()


class ReplicaSetMetrics:
    """Engine-saturation source over live local replicas.

    The in-cluster shape is Prometheus scraping every replica pod and the
    autoscaler's PromQL summing ``tpumlops_engine_queue_depth`` across
    them (``PrometheusSource.engine_metrics``); here we scrape each
    replica's ``/metrics`` directly and do the same sum.  A replica that
    fails to answer is skipped; no replicas answering returns the
    all-None shape, which the autoscaler treats as "hold".
    ``model_metrics`` returns the no-traffic shape — the promotion gate
    is not part of the scaling loop this source serves.
    """

    _FAMILY = "tpumlops_engine_queue_depth"

    def __init__(self, ports, timeout: float = 2.0, router_admin=None):
        self._ports = ports  # Callable[[], list[int]]
        self._timeout = timeout
        # RouterAdmin | None: when given, each engine_metrics read also
        # reports the router's park-buffer depth — THE wake signal for a
        # predictor at zero replicas (no replica ports to scrape there).
        self._router_admin = router_admin

    def model_metrics(
        self, deployment_name, predictor_name, namespace, window_s=60
    ) -> ModelMetrics:
        return ModelMetrics()

    def engine_metrics(
        self, deployment_name, predictor_name, namespace, window_s=60,
        slo_tails=False,
    ) -> EngineMetrics:
        from .router import _histogram_quantile

        ident = {
            ("deployment_name", deployment_name),
            ("predictor_name", predictor_name),
            ("namespace", namespace),
        }
        total: float | None = None
        # Cumulative bucket sums across replicas for the SLO tails,
        # accumulated ONLY when the caller serves the SLO tracker
        # (local source: lifetime quantile, the PromQL rate() window is
        # Prometheus's job in-cluster).
        buckets: dict[str, dict[float, float]] = (
            {"tpumlops_ttft_seconds": {}, "tpumlops_itl_seconds": {}}
            if slo_tails
            else {}
        )
        for port in list(self._ports()):
            try:
                text = (
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=self._timeout,
                    )
                    .read()
                    .decode()
                )
            except Exception:
                continue  # replica mid-boot/mid-drain: partial sum
            for (name, labels), value in parse_prometheus_text(text).items():
                if name == self._FAMILY and ident <= labels:
                    total = (total or 0.0) + value
                elif buckets and name.endswith("_bucket"):
                    fam = name[: -len("_bucket")]
                    if fam in buckets and ident <= labels:
                        le = dict(labels).get("le")
                        if le is not None:
                            b = buckets[fam]
                            b[float(le)] = b.get(float(le), 0.0) + value
        parked = None
        if self._router_admin is not None:
            try:
                parked = float(self._router_admin.parked().get("parked", 0))
            except Exception:
                parked = None  # router unreachable: park signal unknown

        def p99(fam: str) -> float | None:
            b = buckets.get(fam) or {}
            if not b:
                return None
            return _histogram_quantile(
                0.99, sorted(b.items(), key=lambda x: x[0])
            )

        return EngineMetrics(
            queue_depth=total,
            parked=parked,
            ttft_p99_s=p99("tpumlops_ttft_seconds"),
            itl_p99_s=p99("tpumlops_itl_seconds"),
        )


class TrafficGenerator:
    """Continuous client traffic through the router (the gate needs live
    samples on both predictors; in production this is user traffic)."""

    def __init__(
        self,
        router_port: int,
        model_name: str = "iris",
        body: bytes | None = None,
        path: str = "infer",
    ):
        # ``path="generate"`` drives the continuous-batching causal-LM
        # endpoint instead — the router proxies (and records gate
        # histograms for) every model path the same way.
        self.url = f"http://127.0.0.1:{router_port}/v2/models/{model_name}/{path}"
        self.body = body or json.dumps(
            {
                "inputs": [
                    {
                        "name": "x",
                        "shape": [2, 4],
                        "datatype": "FP32",
                        "data": [5.1, 3.5, 1.4, 0.2, 6.7, 3.0, 5.2, 2.3],
                    }
                ]
            }
        ).encode()
        self._stop = threading.Event()
        self.sent = 0
        self.errors = 0

    def _loop(self):
        while not self._stop.is_set():
            try:
                req = urllib.request.Request(
                    self.url,
                    data=self.body,
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=2).read()
            except Exception:
                self.errors += 1  # 502s while a canary backend is dead, etc.
            self.sent += 1
            time.sleep(0.002)

    def __enter__(self):
        threading.Thread(target=self._loop, daemon=True).start()
        return self

    def __exit__(self, *exc):
        self._stop.set()


class DeploymentSyncWatcher:
    """Watch SeldonDeployments on a (real) API server and push each
    change's traffic split into the router — the role Seldon's controller
    + Istio play in-cluster, reduced to its data-plane essence.

    Unlike :class:`SyncingKube` (a FakeKube subclass that intercepts
    writes in-process), this consumes the apiserver's WATCH STREAM, so an
    operator talking to a real (or envtest) API server over HTTP gets its
    weight changes applied the same way a production controller would:
    asynchronously, from events.
    """

    def __init__(self, kube, sync: RouterSync, namespace: str = "models"):
        from .base import SELDONDEPLOYMENT, ObjectRef, WatchExpired

        self._kube = kube
        self._sync = sync
        self._ref = ObjectRef(namespace=namespace, name="", **SELDONDEPLOYMENT)
        self._WatchExpired = WatchExpired
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> "DeploymentSyncWatcher":
        self._thread.start()
        return self

    def _run(self) -> None:
        rv = None
        while not self._stop.is_set():
            try:
                if rv is None:
                    items, rv = self._kube.list_with_version(self._ref)
                    for obj in items:
                        self._sync.sync_manifest(obj)
                for ev in self._kube.watch(
                    self._ref, resource_version=rv, timeout_s=5,
                    stop=self._stop,
                ):
                    rv = (ev.object.get("metadata") or {}).get(
                        "resourceVersion", rv
                    )
                    if ev.type in ("ADDED", "MODIFIED"):
                        self._sync.sync_manifest(ev.object)
            except self._WatchExpired:
                rv = None  # re-list
            except Exception:
                if not self._stop.is_set():
                    time.sleep(0.1)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)


def train_iris_pair(root) -> dict[str, str]:
    """Two distinguishable sklearn iris models saved as v1/v2 artifacts —
    the canary pair used by both the e2e tests and the benchmark."""
    from pathlib import Path

    from sklearn.datasets import load_iris
    from sklearn.linear_model import LogisticRegression

    from ..server.loader import save_sklearn_model

    root = Path(root)
    X, y = load_iris(return_X_y=True)
    uris = {}
    for tag, model in {
        "1": LogisticRegression(max_iter=200).fit(X, y),
        "2": LogisticRegression(max_iter=500, C=0.5).fit(X, y),
    }.items():
        path = str(root / f"v{tag}")
        save_sklearn_model(path, model, "sklearn-linear")
        uris[tag] = path
    return uris


def relaxed_gate_spec(**canary_overrides) -> dict:
    """CR spec skeleton for local-plane canaries on live metrics.

    Generous latency tolerances: both versions are identical sklearn
    models on a loaded box — the gate must judge real jittery numbers
    without flaking; the error floor absorbs transient 502s at
    weight-switch instants.  Canary pacing fields come from the caller.
    """
    spec = {
        "modelName": "iris",
        "modelAlias": "prod",
        "monitoringInterval": 0.2,
        "thresholds": {
            "latencyP95": 5.0,
            "latencyAvg": 5.0,
            "errorRate": 1.0,
            "errorRateFloor": 0.5,
            "minSampleCount": 3,
        },
        "canary": {
            "step": 25,
            "stepInterval": 0.2,
            "attemptDelay": 0.15,
            "maxAttempts": 60,
            "initialTraffic": 25,
            "metricsWindow": 2,
        },
    }
    spec["canary"].update(canary_overrides)
    return spec
