"""MLflow registry client over the REST API — no mlflow SDK.

Implements the two calls the reference makes through ``MlflowClient``
(``mlflow_operator.py:44``): ``get_model_version_by_alias`` (``:59``) and
``get_model_version`` (``:131``), against MLflow's documented 2.0 REST
endpoints.  Credentials follow the same convention as the reference's
deployment (env via the creds secret, ``mlflow-operator-deployment.yaml:21-23``):
``MLFLOW_TRACKING_URI``, optional ``MLFLOW_TRACKING_USERNAME``/``PASSWORD``
or ``MLFLOW_TRACKING_TOKEN``.
"""

from __future__ import annotations

import os

import httpx

from .base import AliasNotFound, ModelVersion, RegistryError


class MlflowRestClient:
    def __init__(self, tracking_uri: str | None = None, timeout: float = 30.0):
        tracking_uri = tracking_uri or os.environ.get("MLFLOW_TRACKING_URI")
        if not tracking_uri:
            raise RuntimeError("MLFLOW_TRACKING_URI not configured")
        auth = None
        user = os.environ.get("MLFLOW_TRACKING_USERNAME")
        password = os.environ.get("MLFLOW_TRACKING_PASSWORD")
        headers = {}
        if user and password:
            auth = (user, password)
        token = os.environ.get("MLFLOW_TRACKING_TOKEN")
        if token:
            headers["Authorization"] = f"Bearer {token}"
        self._http = httpx.Client(
            base_url=tracking_uri.rstrip("/"),
            auth=auth,
            headers=headers,
            timeout=timeout,
        )

    def _get(self, path: str, params: dict) -> dict:
        try:
            resp = self._http.get(path, params=params)
        except httpx.HTTPError as e:
            raise RegistryError(f"mlflow unreachable: {e}") from e
        if resp.status_code >= 400:
            body = resp.text[:500]
            # Only MLflow's own structured error for a missing alias/version
            # may report AliasNotFound — that verdict triggers error status +
            # deployment teardown (base.py contract).  A bare 404 from an
            # ingress/proxy (wrong path prefix, upstream down) is an infra
            # fault and must stay retryable, not tear down a healthy model.
            try:
                error_code = resp.json().get("error_code")
            except ValueError:
                error_code = None
            if error_code == "RESOURCE_DOES_NOT_EXIST":
                raise AliasNotFound(body)
            raise RegistryError(f"mlflow error {resp.status_code}: {body}")
        return resp.json()

    @staticmethod
    def _parse_version(body: dict) -> ModelVersion:
        mv = body.get("model_version") or {}
        version = mv.get("version")
        if version is None:
            # A 200 without model_version.version must not become the
            # string "None" and trigger a phantom rollout.
            raise RegistryError(f"malformed mlflow response: {body!r:.200}")
        return ModelVersion(version=str(version), source=mv.get("source", ""))

    def get_version_by_alias(self, model_name: str, alias: str) -> ModelVersion:
        return self._parse_version(
            self._get(
                "/api/2.0/mlflow/registered-models/alias",
                {"name": model_name, "alias": alias},
            )
        )

    def get_version(self, model_name: str, version: str) -> ModelVersion:
        return self._parse_version(
            self._get(
                "/api/2.0/mlflow/model-versions/get",
                {"name": model_name, "version": version},
            )
        )
