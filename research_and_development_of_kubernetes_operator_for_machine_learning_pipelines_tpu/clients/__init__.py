"""External-system clients: protocol interfaces, real REST clients, fakes.

The reference talks to four external systems over the network — the
Kubernetes API server, the MLflow tracking server, Prometheus, and (via
Seldon) the inference data plane (SURVEY.md §1).  It binds to concrete SDK
clients at import time (``mlflow_operator.py:1-13``), which makes it
untestable without a cluster.  Here every dependency is a small protocol;
the operator core only sees the protocol, and three implementations exist:

- in-memory fakes (``fakes``) for tests,
- real REST clients (``kube_rest``, ``mlflow_rest``, ``prom_http``) built on
  httpx/stdlib, import-guarded so the core never needs cluster SDKs.
"""

from .base import (
    AliasNotFound,
    ApiError,
    Conflict,
    EngineMetrics,
    KubeClient,
    MetricsSource,
    ModelMetrics,
    ModelVersion,
    NotFound,
    RegistryClient,
    RegistryError,
)
from .fakes import FakeKube, FakeMetrics, FakeRegistry

__all__ = [
    "AliasNotFound",
    "ApiError",
    "Conflict",
    "EngineMetrics",
    "KubeClient",
    "MetricsSource",
    "ModelMetrics",
    "ModelVersion",
    "NotFound",
    "RegistryClient",
    "RegistryError",
    "FakeKube",
    "FakeMetrics",
    "FakeRegistry",
]
