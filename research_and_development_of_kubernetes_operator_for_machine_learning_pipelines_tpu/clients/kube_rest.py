"""Kubernetes API client over plain REST (httpx) — no kubernetes SDK.

Speaks the same CustomObjects endpoints the reference uses through
``kubernetes.client.CustomObjectsApi`` (``mlflow_operator.py:35,:241``),
with in-cluster auth: ServiceAccount bearer token + cluster CA from the
standard mounts, API server address from the standard env vars (what
``config.load_incluster_config()`` reads at ``mlflow_operator.py:13``).
"""

from __future__ import annotations

import json
import logging
import os
from pathlib import Path
from typing import Any, Mapping

import httpx

from .base import ApiError, Conflict, Event, NotFound, ObjectRef, WatchEvent, WatchExpired

_log = logging.getLogger(__name__)

_SA_DIR = Path("/var/run/secrets/kubernetes.io/serviceaccount")


class KubeRestClient:
    _token_from_mount = False

    def __init__(
        self,
        base_url: str | None = None,
        token: str | None = None,
        verify: Any = None,
        timeout: float = 30.0,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in-cluster (KUBERNETES_SERVICE_HOST unset) and no "
                    "base_url given"
                )
            base_url = f"https://{host}:{port}"
        # Remember whether the token came from the SA mount: bound SA tokens
        # expire (~1h) and the kubelet rotates the file, so a 401 means
        # "re-read the mount", not "give up".
        self._token_from_mount = token is None and (_SA_DIR / "token").exists()
        if self._token_from_mount:
            token = (_SA_DIR / "token").read_text().strip()
        if verify is None:
            ca = _SA_DIR / "ca.crt"
            verify = str(ca) if ca.exists() else True
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        self._http = httpx.Client(
            base_url=base_url, headers=headers, verify=verify, timeout=timeout
        )

    # -- plumbing ------------------------------------------------------------

    def _request(self, method: str, path: str, **kw) -> httpx.Response:
        resp = self._http.request(method, path, **kw)
        if resp.status_code == 401 and self._token_from_mount:
            fresh = (_SA_DIR / "token").read_text().strip()
            self._http.headers["Authorization"] = f"Bearer {fresh}"
            resp = self._http.request(method, path, **kw)
        return resp

    @staticmethod
    def _path(ref: ObjectRef, name: bool = True) -> str:
        group_part = (
            f"/apis/{ref.group}/{ref.version}" if ref.group else f"/api/{ref.version}"
        )
        ns_part = f"/namespaces/{ref.namespace}" if ref.namespace else ""
        name_part = f"/{ref.name}" if name and ref.name else ""
        return f"{group_part}{ns_part}/{ref.plural}{name_part}"

    @staticmethod
    def _check(resp: httpx.Response) -> dict:
        if resp.status_code == 404:
            raise NotFound(resp.text[:200])
        if resp.status_code == 409:
            raise Conflict(resp.text[:200])
        if resp.status_code >= 400:
            raise ApiError(resp.status_code, resp.text[:500])
        return resp.json()

    # -- KubeClient protocol -------------------------------------------------

    def get(self, ref: ObjectRef) -> dict:
        return self._check(self._request("GET", self._path(ref)))

    def list(self, ref: ObjectRef) -> list[dict]:
        return self.list_with_version(ref)[0]

    def list_with_version(self, ref: ObjectRef) -> tuple[list[dict], str]:
        """List plus the collection's resourceVersion — the watch cursor.

        Starting a watch from the list's resourceVersion (not per-item RVs)
        is the informer contract: every change after this snapshot is
        guaranteed to appear on the stream.
        """
        body = self._check(self._request("GET", self._path(ref, name=False)))
        rv = (body.get("metadata") or {}).get("resourceVersion", "")
        return body.get("items", []), rv

    def watch(
        self,
        ref: ObjectRef,
        resource_version: str | None = None,
        timeout_s: int = 300,
        stop=None,
    ):
        """Stream watch events for a collection (kopf's push model,
        reference ``mlflow_operator.py:26-27``, without kopf).

        Yields :class:`WatchEvent`.  Raises :class:`WatchExpired` on 410
        (either HTTP status or an ERROR event carrying code 410) — the
        caller must re-list and restart the watch from the fresh
        resourceVersion.  ``timeout_s`` is the server-side watch timeout;
        the generator simply ends when the server closes the stream, and
        the caller reconnects with its latest bookmark.
        """
        params: dict[str, str] = {
            "watch": "1",
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(int(timeout_s)),
        }
        if resource_version:
            params["resourceVersion"] = resource_version
        # Read timeout bounds how long a blocking read can ignore ``stop``:
        # an idle stream raises ReadTimeout after 15s, the generator ends
        # cleanly, and the caller reconnects from its cursor (no re-list).
        # Without it, stop() could wait out the full server-side timeout.
        with self._http.stream(
            "GET",
            self._path(ref, name=False),
            params=params,
            timeout=httpx.Timeout(30.0, read=15.0),
        ) as resp:
            if resp.status_code == 410:
                raise WatchExpired("watch list version expired")
            if resp.status_code >= 400:
                resp.read()
                raise ApiError(resp.status_code, resp.text[:500])
            lines = resp.iter_lines()
            while True:
                if stop is not None and stop.is_set():
                    return
                try:
                    line = next(lines)
                except (StopIteration, httpx.ReadTimeout):
                    return
                if not line.strip():
                    continue
                try:
                    raw = json.loads(line)
                except json.JSONDecodeError:
                    _log.warning("undecodable watch line: %r", line[:200])
                    continue
                if raw.get("type") == "ERROR":
                    code = (raw.get("object") or {}).get("code")
                    if code == 410:
                        raise WatchExpired(str(raw.get("object"))[:200])
                    raise ApiError(int(code or 500), str(raw.get("object"))[:300])
                yield WatchEvent(type=raw.get("type", ""), object=raw.get("object") or {})

    def create(self, ref: ObjectRef, body: Mapping[str, Any]) -> dict:
        return self._check(
            self._request("POST", self._path(ref, name=False), json=dict(body))
        )

    def replace(self, ref: ObjectRef, body: Mapping[str, Any]) -> dict:
        return self._check(self._request("PUT", self._path(ref), json=dict(body)))

    def patch_status(self, ref: ObjectRef, status: Mapping[str, Any]) -> dict:
        return self._check(
            self._request(
                "PATCH",
                self._path(ref) + "/status",
                content=json.dumps({"status": dict(status)}),
                headers={"Content-Type": "application/merge-patch+json"},
            )
        )

    def delete(self, ref: ObjectRef) -> None:
        self._check(self._request("DELETE", self._path(ref)))

    def emit_event(self, ref: ObjectRef, event: Event) -> None:
        """Create a corev1 Event attached to the CR (kopf.event equivalent,
        reference call sites :90,:122,:332,:344,:361)."""
        import datetime

        now = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        )
        try:
            obj = self.get(ref)
            uid = (obj.get("metadata") or {}).get("uid")
        except (ApiError, httpx.HTTPError):
            uid = None
        body = {
            "metadata": {"generateName": f"{ref.name}-", "namespace": ref.namespace},
            "involvedObject": {
                "apiVersion": ref.api_version,
                "kind": "MlflowModel",
                "name": ref.name,
                "namespace": ref.namespace,
                **({"uid": uid} if uid else {}),
            },
            "type": event.type,
            "reason": event.reason,
            "message": event.message,
            "firstTimestamp": now,
            "lastTimestamp": now,
            "count": 1,
            "source": {"component": "tpumlops-operator"},
        }
        # Best-effort end to end: a cosmetic event must never abort a
        # reconcile step, whether the API rejects it or the transport drops.
        try:
            self._check(
                self._request(
                    "POST", f"/api/v1/namespaces/{ref.namespace}/events", json=body
                )
            )
        except (ApiError, httpx.HTTPError) as e:
            _log.warning("event emission failed: %s", e)
