"""Kubernetes API client over plain REST (httpx) — no kubernetes SDK.

Speaks the same CustomObjects endpoints the reference uses through
``kubernetes.client.CustomObjectsApi`` (``mlflow_operator.py:35,:241``),
with in-cluster auth: ServiceAccount bearer token + cluster CA from the
standard mounts, API server address from the standard env vars (what
``config.load_incluster_config()`` reads at ``mlflow_operator.py:13``).
"""

from __future__ import annotations

import json
import logging
import os
from pathlib import Path
from typing import Any, Mapping

import httpx

from .base import ApiError, Conflict, Event, NotFound, ObjectRef

_log = logging.getLogger(__name__)

_SA_DIR = Path("/var/run/secrets/kubernetes.io/serviceaccount")


class KubeRestClient:
    _token_from_mount = False

    def __init__(
        self,
        base_url: str | None = None,
        token: str | None = None,
        verify: Any = None,
        timeout: float = 30.0,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in-cluster (KUBERNETES_SERVICE_HOST unset) and no "
                    "base_url given"
                )
            base_url = f"https://{host}:{port}"
        # Remember whether the token came from the SA mount: bound SA tokens
        # expire (~1h) and the kubelet rotates the file, so a 401 means
        # "re-read the mount", not "give up".
        self._token_from_mount = token is None and (_SA_DIR / "token").exists()
        if self._token_from_mount:
            token = (_SA_DIR / "token").read_text().strip()
        if verify is None:
            ca = _SA_DIR / "ca.crt"
            verify = str(ca) if ca.exists() else True
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        self._http = httpx.Client(
            base_url=base_url, headers=headers, verify=verify, timeout=timeout
        )

    # -- plumbing ------------------------------------------------------------

    def _request(self, method: str, path: str, **kw) -> httpx.Response:
        resp = self._http.request(method, path, **kw)
        if resp.status_code == 401 and self._token_from_mount:
            fresh = (_SA_DIR / "token").read_text().strip()
            self._http.headers["Authorization"] = f"Bearer {fresh}"
            resp = self._http.request(method, path, **kw)
        return resp

    @staticmethod
    def _path(ref: ObjectRef, name: bool = True) -> str:
        group_part = (
            f"/apis/{ref.group}/{ref.version}" if ref.group else f"/api/{ref.version}"
        )
        ns_part = f"/namespaces/{ref.namespace}" if ref.namespace else ""
        name_part = f"/{ref.name}" if name and ref.name else ""
        return f"{group_part}{ns_part}/{ref.plural}{name_part}"

    @staticmethod
    def _check(resp: httpx.Response) -> dict:
        if resp.status_code == 404:
            raise NotFound(resp.text[:200])
        if resp.status_code == 409:
            raise Conflict(resp.text[:200])
        if resp.status_code >= 400:
            raise ApiError(resp.status_code, resp.text[:500])
        return resp.json()

    # -- KubeClient protocol -------------------------------------------------

    def get(self, ref: ObjectRef) -> dict:
        return self._check(self._request("GET", self._path(ref)))

    def list(self, ref: ObjectRef) -> list[dict]:
        body = self._check(self._request("GET", self._path(ref, name=False)))
        return body.get("items", [])

    def create(self, ref: ObjectRef, body: Mapping[str, Any]) -> dict:
        return self._check(
            self._request("POST", self._path(ref, name=False), json=dict(body))
        )

    def replace(self, ref: ObjectRef, body: Mapping[str, Any]) -> dict:
        return self._check(self._request("PUT", self._path(ref), json=dict(body)))

    def patch_status(self, ref: ObjectRef, status: Mapping[str, Any]) -> dict:
        return self._check(
            self._request(
                "PATCH",
                self._path(ref) + "/status",
                content=json.dumps({"status": dict(status)}),
                headers={"Content-Type": "application/merge-patch+json"},
            )
        )

    def delete(self, ref: ObjectRef) -> None:
        self._check(self._request("DELETE", self._path(ref)))

    def emit_event(self, ref: ObjectRef, event: Event) -> None:
        """Create a corev1 Event attached to the CR (kopf.event equivalent,
        reference call sites :90,:122,:332,:344,:361)."""
        import datetime

        now = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        )
        try:
            obj = self.get(ref)
            uid = (obj.get("metadata") or {}).get("uid")
        except (ApiError, httpx.HTTPError):
            uid = None
        body = {
            "metadata": {"generateName": f"{ref.name}-", "namespace": ref.namespace},
            "involvedObject": {
                "apiVersion": ref.api_version,
                "kind": "MlflowModel",
                "name": ref.name,
                "namespace": ref.namespace,
                **({"uid": uid} if uid else {}),
            },
            "type": event.type,
            "reason": event.reason,
            "message": event.message,
            "firstTimestamp": now,
            "lastTimestamp": now,
            "count": 1,
            "source": {"component": "tpumlops-operator"},
        }
        # Best-effort end to end: a cosmetic event must never abort a
        # reconcile step, whether the API rejects it or the transport drops.
        try:
            self._check(
                self._request(
                    "POST", f"/api/v1/namespaces/{ref.namespace}/events", json=body
                )
            )
        except (ApiError, httpx.HTTPError) as e:
            _log.warning("event emission failed: %s", e)
