"""Prometheus metrics source over the HTTP query API — no client SDK.

Runs the exact PromQL the reference runs (``get_model_metrics``,
``mlflow_operator.py:363-417``): p95 via histogram_quantile over the
client-requests buckets, error/total counts with the ``or on() vector(0)``
zero-fallback, mean latency as increase(sum)/increase(count), request and
feedback counts — keyed by {deployment_name, predictor_name, namespace}.
"""

from __future__ import annotations

import logging

import httpx

from .base import EngineMetrics, ModelMetrics

_log = logging.getLogger(__name__)


class PrometheusSource:
    def __init__(self, url: str, timeout: float = 30.0):
        self._http = httpx.Client(base_url=url.rstrip("/"), timeout=timeout)

    def _query(self, promql: str) -> float | None:
        try:
            resp = self._http.get("/api/v1/query", params={"query": promql})
            resp.raise_for_status()
            result = resp.json().get("data", {}).get("result", [])
        except (httpx.HTTPError, ValueError) as e:
            _log.warning("prometheus query failed: %s", e)
            return None
        if not result:
            return None
        try:
            value = float(result[0]["value"][1])
        except (KeyError, IndexError, TypeError, ValueError):
            return None
        return None if value != value else value  # NaN -> None

    def model_metrics(
        self,
        deployment_name: str,
        predictor_name: str,
        namespace: str,
        window_s: int = 60,
    ) -> ModelMetrics:
        sel = (
            f'deployment_name="{deployment_name}", '
            f'predictor_name="{predictor_name}", namespace="{namespace}"'
        )
        w = f"{window_s}s"

        # Reference :367-372
        p95 = self._query(
            "histogram_quantile(0.95, sum(rate("
            f"seldon_api_executor_client_requests_seconds_bucket{{{sel}}}[{w}]"
            ")) by (le))"
        )
        # NOTE on None vs 0: every count query below carries PromQL's
        # ``or on() vector(0)`` fallback, so a *successful* query returns a
        # real number (possibly 0).  ``_query`` returning None means the
        # query itself failed (Prometheus unreachable / bad response) — that
        # must surface as metric-unavailable (None), never as 0, or a
        # transient Prometheus blip would read as a perfect canary and pass
        # the gate.
        # Reference :375-380
        errors = self._query(
            "sum(increase("
            f'seldon_api_executor_server_requests_seconds_count{{code!="200", {sel}}}[{w}]'
            ")) or on() vector(0)"
        )
        # Reference :383-390
        total = self._query(
            "sum(increase("
            f"seldon_api_executor_server_requests_seconds_count{{{sel}}}[{w}]"
            ")) or on() vector(0)"
        )
        if errors is None or total is None:
            error_rate = None
        else:
            error_rate = (errors / total) if total > 0 else None
        # Reference :393-404
        lat_sum = self._query(
            "sum(increase("
            f"seldon_api_executor_client_requests_seconds_sum{{{sel}}}[{w}]"
            ")) or on() vector(0)"
        )
        lat_count = self._query(
            "sum(increase("
            f"seldon_api_executor_client_requests_seconds_count{{{sel}}}[{w}]"
            ")) or on() vector(0)"
        )
        if lat_sum is None or lat_count is None:
            latency_avg = None
        else:
            latency_avg = (lat_sum / lat_count) if lat_count > 0 else None
        # Reference :410-415
        # NOTE: a failed query stays 0.0 only because ModelMetrics requires a
        # float here and nothing gates on feedback count; keep the None-vs-0
        # distinction if a consumer ever appears.
        feedback = self._query(
            "sum(increase("
            f'seldon_api_executor_server_requests_seconds_count{{service="feedback", {sel}}}[{w}]'
            ")) or on() vector(0)"
        )
        feedback = feedback if feedback is not None else 0.0

        return ModelMetrics(
            latency_p95=p95,
            error_responses=errors if errors is not None else 0.0,
            error_rate=error_rate,
            latency_avg=latency_avg,
            # On query failure request_count reads 0, which the
            # min_sample_count hardening treats as not-enough-samples (safe).
            request_count=lat_count if lat_count is not None else 0.0,
            feedback_request_count=feedback,
        )

    def engine_metrics(
        self,
        deployment_name: str,
        predictor_name: str,
        namespace: str,
        window_s: int = 60,
        slo_tails: bool = False,
    ) -> EngineMetrics:
        """Engine-saturation signals for the replica autoscaler.

        Queue depth is summed over the predictor's replicas — each
        replica exports its own ``tpumlops_engine_queue_depth`` gauge
        under the same identity labels, so the sum is the predictor's
        total backlog.  No ``vector(0)`` fallback anywhere: a failed or
        empty query must surface as None (signal unavailable), never as
        0 — the autoscaler treats blindness as "hold", and a Prometheus
        blackout reading as "no load" would drain the fleet to
        minReplicas under full traffic.
        """
        sel = (
            f'deployment_name="{deployment_name}", '
            f'predictor_name="{predictor_name}", namespace="{namespace}"'
        )
        w = f"{window_s}s"
        queue_depth = self._query(
            f"sum(tpumlops_engine_queue_depth{{{sel}}})"
        )
        wait_p95 = self._query(
            "histogram_quantile(0.95, sum(rate("
            f"tpumlops_admission_wait_ms_bucket{{{sel}}}[{w}]"
            ")) by (le))"
        )
        ttft_p95 = self._query(
            "histogram_quantile(0.95, sum(rate("
            f"tpumlops_ttft_seconds_bucket{{{sel}}}[{w}]"
            ")) by (le))"
        )
        # The router's park buffer (native/router.cc): requests held for
        # a CR at zero replicas.  Keyed by deployment/namespace only —
        # the router parks before any predictor is picked, so the gauge
        # carries no predictor_name.  Same no-vector(0) discipline:
        # None = park signal unobservable, and the autoscaler then
        # refuses the last scale-down step to zero.
        parked = self._query(
            "sum(tpumlops_router_parked_requests{"
            f'deployment_name="{deployment_name}", '
            f'namespace="{namespace}"}})'
        )
        # SLO tails (spec.slo): p99 of the same TTFT histogram plus the
        # inter-token-latency one.  Queried ONLY when the caller serves
        # the SLO tracker — autoscale-only CRs keep the 4-query shape.
        # Same no-vector(0) discipline: an unobservable tail contributes
        # NO sample to the error budget.
        ttft_p99 = itl_p99 = None
        if slo_tails:
            ttft_p99 = self._query(
                "histogram_quantile(0.99, sum(rate("
                f"tpumlops_ttft_seconds_bucket{{{sel}}}[{w}]"
                ")) by (le))"
            )
            itl_p99 = self._query(
                "histogram_quantile(0.99, sum(rate("
                f"tpumlops_itl_seconds_bucket{{{sel}}}[{w}]"
                ")) by (le))"
            )
        return EngineMetrics(
            queue_depth=queue_depth,
            admission_wait_p95_ms=wait_p95,
            ttft_p95_s=ttft_p95,
            parked=parked,
            ttft_p99_s=ttft_p99,
            itl_p99_s=itl_p99,
        )
