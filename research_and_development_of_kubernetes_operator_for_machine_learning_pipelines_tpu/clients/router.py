"""Build, supervise, and drive the native canary router (native/router.cc).

The reference splits canary traffic with Istio weights written into a
SeldonDeployment and reads latency histograms from the Seldon *executor*
(``mlflow_operator.py:205,:220,:322-324`` / ``:367-415``).  Outside a
service mesh — a bare TPU-VM node pool, a dev box, the benchmark harness —
this framework carries its own executor: ``tpumlops-router``, a C++ epoll
reverse proxy that does the weighted split and exports the same
``seldon_api_executor_*`` histogram families the gate queries.

This module is the Python face of that binary:

- :func:`build_router` — compile ``router.cc`` with the system ``g++`` into
  a content-addressed cache (no pip/cmake involvement; the toolchain is a
  baseline environment guarantee);
- :class:`RouterProcess` — spawn/supervise one router instance;
- :class:`RouterAdmin` — typed admin API (weights, config, metrics) used by
  tests and by operators running in local/router mode.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import subprocess
import time
import urllib.error
import urllib.request

_SRC = pathlib.Path(__file__).resolve().parent.parent / "native" / "router.cc"


def _cache_dir() -> pathlib.Path:
    # Per-user, mode-0700 cache — NOT a world-writable /tmp path, where
    # another local user could pre-plant a binary at the predictable
    # source-hash name and have us exec it.
    base = os.environ.get("TPUMLOPS_CACHE") or os.path.join(
        os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache"),
        "tpumlops-native",
    )
    path = pathlib.Path(base)
    path.mkdir(parents=True, exist_ok=True, mode=0o700)
    return path


def build_router(src: pathlib.Path | None = None) -> pathlib.Path:
    """Compile the router (cached by source hash). Returns the binary path."""
    src = src or _SRC
    text = src.read_bytes()
    tag = hashlib.sha256(text).hexdigest()[:16]
    cache = _cache_dir()
    out = cache / f"tpumlops-router-{tag}"
    # Trust the cached binary only if this user owns it.
    if out.exists() and out.stat().st_uid == os.getuid():
        return out
    tmp = out.with_suffix(f".build{os.getpid()}")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-Wall", "-o", str(tmp), str(src)],
        check=True,
        capture_output=True,
    )
    tmp.replace(out)
    return out


class RouterAdmin:
    """Admin-API client for a running router (stdlib urllib; no deps)."""

    def __init__(self, port: int, host: str = "127.0.0.1", timeout: float = 5.0):
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def _req(self, path: str, method: str = "GET", body: dict | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def healthy(self) -> bool:
        try:
            return self._req("/router/healthz") == b"ok\n"
        except (urllib.error.URLError, ConnectionError, OSError):
            return False

    def get_weights(self) -> dict[str, int]:
        return json.loads(self._req("/router/weights"))

    def set_weights(self, weights: dict[str, int]) -> None:
        self._req("/router/weights", "PUT", weights)

    def get_config(self) -> dict:
        return json.loads(self._req("/router/config"))

    def set_config(
        self,
        backends: list[dict],
        namespace: str | None = None,
        deployment: str | None = None,
    ) -> dict:
        body: dict = {"backends": backends}
        if namespace:
            body["namespace"] = namespace
        if deployment:
            body["deployment"] = deployment
        return json.loads(self._req("/router/config", "PUT", body))

    def metrics_text(self) -> str:
        return self._req("/router/metrics").decode()


class RouterProcess:
    """One supervised router instance.

    >>> with RouterProcess(port=9000, namespace="ns", deployment="bert",
    ...                    backends={"v1": ("127.0.0.1", 8001, 90),
    ...                              "v2": ("127.0.0.1", 8002, 10)}) as r:
    ...     r.admin.set_weights({"v1": 80, "v2": 20})
    """

    def __init__(
        self,
        port: int,
        backends: dict[str, tuple[str, int, int]],
        namespace: str = "default",
        deployment: str = "router",
        binary: pathlib.Path | None = None,
    ):
        self.port = port
        self.backends = backends
        self.namespace = namespace
        self.deployment = deployment
        self.binary = binary or build_router()
        self.proc: subprocess.Popen | None = None
        self.admin = RouterAdmin(port)

    def start(self, wait_s: float = 5.0) -> "RouterProcess":
        argv = [
            str(self.binary),
            "--port", str(self.port),
            "--namespace", self.namespace,
            "--deployment", self.deployment,
        ]
        for name, (host, port, weight) in self.backends.items():
            argv += ["--backend", f"{name}={host}:{port}:{weight}"]
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE
        )
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            if self.admin.healthy():
                return self
            if self.proc.poll() is not None:
                err = self.proc.stderr.read().decode() if self.proc.stderr else ""
                raise RuntimeError(f"router exited at startup: {err}")
            time.sleep(0.02)
        self.stop()
        raise TimeoutError("router did not become healthy")

    def stop(self) -> None:
        if self.proc is not None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
            if self.proc.stderr:
                self.proc.stderr.close()
            self.proc = None

    def __enter__(self) -> "RouterProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
