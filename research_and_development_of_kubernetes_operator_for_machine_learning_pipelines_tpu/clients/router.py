"""Build, supervise, and drive the native canary router (native/router.cc).

The reference splits canary traffic with Istio weights written into a
SeldonDeployment and reads latency histograms from the Seldon *executor*
(``mlflow_operator.py:205,:220,:322-324`` / ``:367-415``).  Outside a
service mesh — a bare TPU-VM node pool, a dev box, the benchmark harness —
this framework carries its own executor: ``tpumlops-router``, a C++ epoll
reverse proxy that does the weighted split and exports the same
``seldon_api_executor_*`` histogram families the gate queries.

This module is the Python face of that binary:

- :func:`build_router` — compile ``router.cc`` with the system ``g++`` into
  a content-addressed cache (no pip/cmake involvement; the toolchain is a
  baseline environment guarantee);
- :class:`RouterProcess` — spawn/supervise one router instance;
- :class:`RouterAdmin` — typed admin API (weights, config, metrics) used by
  tests and by operators running in local/router mode.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import subprocess
import time
import urllib.error
import urllib.request

_SRC = pathlib.Path(__file__).resolve().parent.parent / "native" / "router.cc"


def _cache_dir() -> pathlib.Path:
    # Per-user, mode-0700 cache — NOT a world-writable /tmp path, where
    # another local user could pre-plant a binary at the predictable
    # source-hash name and have us exec it.
    base = os.environ.get("TPUMLOPS_CACHE") or os.path.join(
        os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache"),
        "tpumlops-native",
    )
    path = pathlib.Path(base)
    path.mkdir(parents=True, exist_ok=True, mode=0o700)
    return path


def build_router(src: pathlib.Path | None = None) -> pathlib.Path:
    """Compile the router (cached by source hash). Returns the binary path."""
    src = src or _SRC
    text = src.read_bytes()
    tag = hashlib.sha256(text).hexdigest()[:16]
    cache = _cache_dir()
    out = cache / f"tpumlops-router-{tag}"
    # Trust the cached binary only if this user owns it.
    if out.exists() and out.stat().st_uid == os.getuid():
        return out
    tmp = out.with_suffix(f".build{os.getpid()}")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-Wall", "-o", str(tmp), str(src)],
        check=True,
        capture_output=True,
    )
    tmp.replace(out)
    return out


class RouterAdmin:
    """Admin-API client for a running router (stdlib urllib; no deps)."""

    def __init__(self, port: int, host: str = "127.0.0.1", timeout: float = 5.0):
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def _req(self, path: str, method: str = "GET", body: dict | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def healthy(self) -> bool:
        try:
            return self._req("/router/healthz") == b"ok\n"
        except (urllib.error.URLError, ConnectionError, OSError):
            return False

    def get_weights(self) -> dict[str, int]:
        return json.loads(self._req("/router/weights"))

    def _req_retry(
        self,
        path: str,
        method: str,
        body: dict | None,
        retries: int,
        backoff_s: float,
        sleep=time.sleep,
    ):
        """Bounded retry on TRANSIENT transport errors only.

        An HTTPError means the router is up and answered (a real 4xx/5xx
        the caller must see); connection refused/reset/timeout means it
        is restarting — exactly the window a scale event's weight flip
        used to race and lose, leaving the split stale until the next
        reconcile.  Exponential backoff, ``retries`` re-attempts, then
        the last error propagates."""
        for attempt in range(retries + 1):
            try:
                return self._req(path, method, body)
            except urllib.error.HTTPError:
                raise  # the router answered; not a transient
            except (urllib.error.URLError, ConnectionError, OSError):
                if attempt == retries:
                    raise
                sleep(backoff_s * (2 ** attempt))

    def set_weights(
        self,
        weights: dict[str, int],
        retries: int = 3,
        backoff_s: float = 0.05,
        sleep=time.sleep,
    ) -> None:
        """Idempotent (PUT of an absolute weight map), so retrying a
        flip against a mid-restart router is always safe."""
        self._req_retry(
            "/router/weights", "PUT", weights, retries, backoff_s, sleep
        )

    def get_config(self) -> dict:
        return json.loads(self._req("/router/config"))

    def drain_latencies(self) -> list[float]:
        """Exact router-internal per-request latencies (SECONDS) since
        the last drain — read-and-clear.  Precise where the Prometheus
        histogram's buckets are decades wide; used to attribute tail
        latency to inside-the-proxy vs kernel/client scheduling."""
        payload = json.loads(self._req("/router/latencies"))
        return [us / 1e6 for us in payload.get("recent_us", [])]

    def set_config(
        self,
        backends: list[dict],
        namespace: str | None = None,
        deployment: str | None = None,
        journey_ring: int | None = None,
        mux_models: int | None = None,
        timeseries_ring: int | None = None,
    ) -> dict:
        body: dict = {"backends": backends}
        if namespace:
            body["namespace"] = namespace
        if deployment:
            body["deployment"] = deployment
        if journey_ring is not None:
            # Fleet trace plane sizing (0 disables; omitted = keep the
            # router's running ring).
            body["journeyRing"] = int(journey_ring)
        if timeseries_ring is not None:
            # Per-backend 1 s history sizing (0 disables; omitted = keep
            # the router's running ring).
            body["timeseriesRing"] = int(timeseries_ring)
        if mux_models is not None:
            # Multi-model multiplexing toggle (0 disables; omitted =
            # keep the router's running mode).  Backend entries may then
            # carry a "model" key — the attached-model table the
            # model-aware pick and per-model park release consult.
            body["muxModels"] = int(mux_models)
        return json.loads(self._req("/router/config", "PUT", body))

    def metrics_text(self) -> str:
        return self._req("/router/metrics").decode()

    def parked(self) -> dict:
        """Park-buffer state (``GET /router/parked``): ``parked`` count,
        ``capacity``, ``oldest_wait_s``, and the released/overflow/
        timeout counters — the operator's wake signal for a CR whose
        replicas are at zero.  With multiplexing on the body also
        carries ``models`` — a per-model parked breakdown, so the
        bin-packer attaches the RIGHT model instead of inferring from
        the fleet-wide count."""
        return json.loads(self._req("/router/parked"))

    def fleet(self) -> dict:
        """Disaggregated-fleet state (``GET /router/fleet``): affinity
        hit/miss tallies, KV handoff counts/bytes/failures, ring size,
        and per-backend role + known-prefix counts."""
        return json.loads(self._req("/router/fleet"))

    def journeys(self) -> dict:
        """The journey ring (``GET /router/debug/requests``): per-request
        JourneyRecords — identity, affinity decision, per-leg backend/
        bytes/wall, park hold spans, failover attempts, final outcome —
        plus the ``started_unix`` clock anchor the fleet-trace stitcher
        uses.  404 (HTTPError) while ``--journey-ring`` is 0."""
        return json.loads(self._req("/router/debug/requests"))

    def journey_trace(self, fmt: str = "chrome") -> dict:
        """The journey ring as Chrome trace-event JSON
        (``GET /router/debug/trace?format=chrome``): one track per
        backend, async request spans keyed by request id."""
        return json.loads(self._req(f"/router/debug/trace?format={fmt}"))

    def timeseries(self) -> dict:
        """The timeseries ring (``GET /router/debug/timeseries``):
        per-backend 1 s buckets of leg wall p50/p99, leg/error/failover
        counts, plus a router-level park series — the proxy-side
        per-replica history the operator's anomaly detector compares
        across peers.  404 (HTTPError) while ``--timeseries-ring`` is
        0."""
        return json.loads(self._req("/router/debug/timeseries"))


def parse_prometheus_text(text: str) -> dict[tuple[str, frozenset], float]:
    """Parse Prometheus exposition text into {(name, labelset): value}."""
    out: dict[tuple[str, frozenset], float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
        except ValueError:
            continue
        if "{" in series:
            name, rest = series.split("{", 1)
            labels = frozenset(
                tuple(pair.split("=", 1)) for pair in _split_labels(rest.rstrip("}"))
            )
        else:
            name, labels = series, frozenset()
        try:
            out[(name, labels)] = float(value)
        except ValueError:
            continue
    return out


def _split_labels(raw: str) -> list[str]:
    """Split 'a="x",b="y,z"' respecting quoted commas; strips quotes."""
    parts, cur, in_q = [], "", False
    for ch in raw:
        if ch == '"':
            in_q = not in_q
            continue
        if ch == "," and not in_q:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        parts.append(cur)
    return parts


def _histogram_quantile(q: float, buckets: list[tuple[float, float]]) -> float | None:
    """PromQL histogram_quantile over cumulative (le, count) buckets.

    ``buckets`` must be sorted by le and include the +Inf bucket last.
    Returns None when the histogram is empty.
    """
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_count = 0.0, 0.0
    for le, count in buckets:
        if count >= rank:
            if le == float("inf"):
                return prev_le  # PromQL returns the highest finite bound
            if count == prev_count:
                return le
            return prev_le + (le - prev_le) * (rank - prev_count) / (count - prev_count)
        prev_le, prev_count = le, count
    return buckets[-1][0]


class RouterMetricsSource:
    """``MetricsSource`` over the router's ``/router/metrics`` endpoint.

    In-cluster, Prometheus scrapes the router and the gate runs PromQL
    (reference ``mlflow_operator.py:363-417``).  In local/router mode there
    is no Prometheus; this class keeps a short history of scrapes and
    computes the same six quantities over the requested window from
    histogram deltas — ``rate()``/``increase()`` semantics, including the
    reference's "None means no traffic in the window" convention.
    """

    _CLIENT = "seldon_api_executor_client_requests_seconds"
    _SERVER = "seldon_api_executor_server_requests_seconds"

    def __init__(self, admin: "RouterAdmin"):
        self.admin = admin
        self._snapshots: list[tuple[float, dict]] = []  # (monotonic_t, parsed)
        self._max_window_s = 60.0  # grows to the largest window requested

    def _scrape(self) -> dict:
        now = time.monotonic()
        parsed = parse_prometheus_text(self.admin.metrics_text())
        self._snapshots.append((now, parsed))
        # Keep only what any requested window can reach (plus slack) — the
        # reconciler scrapes several times per second during a canary, and
        # ten minutes of full parsed snapshots would be thousands of dicts.
        cutoff = now - (2.0 * self._max_window_s + 10.0)
        while len(self._snapshots) > 2 and self._snapshots[1][0] < cutoff:
            self._snapshots.pop(0)
        return parsed

    def _baseline(self, window_s: float) -> dict:
        """Newest snapshot at least ``window_s`` old (or empty = since start)."""
        now = time.monotonic()
        base: dict = {}
        for t, snap in self._snapshots[:-1]:
            if now - t >= window_s:
                base = snap
            else:
                break
        return base

    def model_metrics(
        self,
        deployment_name: str,
        predictor_name: str,
        namespace: str,
        window_s: int = 60,
    ):
        from .base import ModelMetrics

        self._max_window_s = max(self._max_window_s, float(window_s))
        current = self._scrape()
        base = self._baseline(window_s)
        ident = {
            ("deployment_name", deployment_name),
            ("predictor_name", predictor_name),
            ("namespace", namespace),
        }

        def delta(name: str, key: str = "code"):
            """(current - base) per label value over series matching identity.

            Clamped at 0 per series: a counter that went BACKWARD means the
            series was reset (predictor removed and re-added, router
            restart) — PromQL's increase() treats that as a reset, and a
            negative count fed to the gate would make error_rate garbage.
            """
            out: dict[str, float] = {}
            for (n, labels), v in current.items():
                if n != name or not ident <= labels:
                    continue
                ld = dict(labels)
                k = ld.get(key, "")
                out[k] = out.get(k, 0.0) + max(0.0, v - base.get((n, labels), 0.0))
            return out

        bucket_deltas = delta(self._CLIENT + "_bucket", key="le")
        buckets = sorted(
            ((float(le), c) for le, c in bucket_deltas.items()),
            key=lambda x: x[0],
        )
        count = delta(self._CLIENT + "_count").get("", 0.0)
        total_sum = delta(self._CLIENT + "_sum").get("", 0.0)

        by_code = delta(self._SERVER + "_count")
        server_total = sum(by_code.values())
        errors = sum(v for code, v in by_code.items() if code != "200")
        # service="feedback" series from the router's own histograms —
        # the count the reference reads at mlflow_operator.py:410-415.
        feedback = delta(self._SERVER + "_count", key="service").get(
            "feedback", 0.0
        )

        return ModelMetrics(
            latency_p95=_histogram_quantile(0.95, buckets),
            error_responses=errors,
            error_rate=(errors / server_total) if server_total > 0 else None,
            latency_avg=(total_sum / count) if count > 0 else None,
            request_count=count,
            feedback_request_count=feedback,
        )


class RouterSync:
    """Push a SeldonDeployment manifest's traffic split into the router.

    In-cluster the manifest's ``traffic`` weights become Istio
    VirtualService weights via Seldon's controller; in local/router mode
    this class is that controller: it maps each predictor to a backend
    address via ``resolve(predictor_name) -> (host, port)`` and PUTs the
    router config.  Weights change on every promotion step; addresses and
    the predictor set change when versions roll.
    """

    def __init__(self, admin: "RouterAdmin", resolve):
        self.admin = admin
        self.resolve = resolve

    def sync_manifest(self, manifest: dict) -> None:
        spec = manifest.get("spec") or {}
        meta = manifest.get("metadata") or {}
        # Fleet trace plane: the builder stamps spec.fleet.observability.
        # journeyRing as a manifest annotation; the sync ALWAYS sends it
        # (absent = 0) so the manifest stays the source of truth — the
        # same keep-survivor trap the role field had (an omitted value
        # would pin a previously-enabled ring on forever after the CR
        # disables it).
        annotations = meta.get("annotations") or {}
        journey_ring = int(
            annotations.get("tpumlops.dev/fleet-journey-ring") or 0
        )
        # Multi-model multiplexing: same always-sent contract as the
        # journey ring (absent = 0) — an omitted toggle would pin a
        # previously-enabled mux mode on forever after the CR disables
        # it.  Per-backend attachments ride tpumlops.dev/mux-model-<name>
        # annotations (the multiplexer stamps them as it executes its
        # attach plan).
        mux_models = int(annotations.get("tpumlops.dev/mux-models") or 0)
        # Router timeseries ring: same always-sent contract (absent = 0)
        # — an omitted size would pin a previously-enabled ring on
        # forever after the CR disables it.
        timeseries_ring = int(
            annotations.get("tpumlops.dev/fleet-timeseries-ring") or 0
        )
        backends = []
        for pred in spec.get("predictors") or []:
            name = pred.get("name")
            weight = int(pred.get("traffic", 0))
            replicas = pred.get("replicas")
            if replicas is not None and int(replicas) == 0:
                # Scale-to-zero: the predictor holds NO capacity, so its
                # traffic share drops to 0 regardless of the split — with
                # every backend at weight 0 the router PARKS incoming
                # requests (or sheds typed 503s past the buffer) instead
                # of dialing a dead address.
                weight = 0
            try:
                host, port = self.resolve(name)
            except Exception:
                if weight > 0:
                    raise
                # Parked predictor with no resolvable replica: keep a
                # placeholder address (never dialed at weight 0) so the
                # backend — and its histograms — survive the park.
                host, port = "127.0.0.1", 9
            entry = {
                "name": name,
                "host": host,
                "port": port,
                "weight": weight,
            }
            # Disaggregated pools: whoever materializes the fleet
            # (tests / a local plane today — an in-cluster controller
            # reading the builder's tpumlops.dev/fleet-* annotations is
            # ROADMAP item 2's open end) stamps the pool role on the
            # predictor entry; the router needs it for ring membership
            # and relay targeting.  ALWAYS sent — to the router an
            # omitted role means "keep the survivor's role", which would
            # pin a backend once tagged prefill out of client traffic
            # forever after disaggregation is turned off.
            entry["role"] = str(pred.get("tpumlopsFleetRole") or "unified")
            if mux_models:
                # Attached-model table (explicit "" = detached): sent
                # ONLY with mux on so the config body — and the router's
                # survivor-keeping "model" semantics — stay byte-for-
                # byte for single-model fleets.
                entry["model"] = str(
                    pred.get("tpumlopsMuxModel")
                    or annotations.get(f"tpumlops.dev/mux-model-{name}")
                    or ""
                )
            backends.append(entry)
        if backends:
            self.admin.set_config(
                backends,
                namespace=meta.get("namespace"),
                deployment=meta.get("name"),
                journey_ring=journey_ring,
                mux_models=mux_models,
                timeseries_ring=timeseries_ring,
            )


class RouterProcess:
    """One supervised router instance.

    >>> with RouterProcess(port=9000, namespace="ns", deployment="bert",
    ...                    backends={"v1": ("127.0.0.1", 8001, 90),
    ...                              "v2": ("127.0.0.1", 8002, 10)}) as r:
    ...     r.admin.set_weights({"v1": 80, "v2": 20})
    """

    def __init__(
        self,
        port: int,
        backends: dict[str, tuple],
        namespace: str = "default",
        deployment: str = "router",
        binary: pathlib.Path | None = None,
        park_buffer: int = 0,
        park_timeout_s: float = 30.0,
        affinity_tokens: int = 0,
        kv_handoff: bool = True,
        handoff_retries: int = 1,
        health_probes: bool = False,
        health_threshold: int = 3,
        probe_interval_s: float = 0.5,
        failover_retries: int = 0,
        journey_ring: int = 0,
        access_log: bool = False,
        mux_models: int = 0,
        timeseries_ring: int = 0,
    ):
        self.port = port
        # Values are (host, port, weight) or (host, port, weight, role)
        # — role in {"unified", "prefill", "decode"} for disaggregated
        # fleets (prefill backends serve KV exports, not client traffic;
        # decode backends join the prefix-affinity ring).
        self.backends = backends
        self.namespace = namespace
        self.deployment = deployment
        self.binary = binary or build_router()
        # Scale-to-zero request parking: hold up to park_buffer requests
        # while no backend has positive weight (0 = old behavior, an
        # immediate 503), releasing them in arrival order when capacity
        # returns; each parked request waits at most park_timeout_s.
        self.park_buffer = int(park_buffer)
        self.park_timeout_s = float(park_timeout_s)
        # Prefix affinity + KV handoff relay: hash the first
        # affinity_tokens prompt ids onto a consistent-hash ring over
        # decode-role backends; cold prompts relay prefill→import→
        # forward, retrying on up to handoff_retries ADDITIONAL prefill
        # replicas after the first export fails before the unified
        # fallback.  0 (default) = old routing byte-for-byte.
        self.affinity_tokens = int(affinity_tokens)
        self.kv_handoff = bool(kv_handoff)
        self.handoff_retries = int(handoff_retries)
        # Failure containment (both default off = old router byte-for-
        # byte).  health_probes: consecutive connect/5xx failures trip a
        # per-backend circuit (ejected from SWRR + the affinity ring)
        # and half-open GET /healthz probes at a capped exponential
        # interval re-admit it.  failover_retries: a request whose
        # upstream dies before any response byte retries on up to N
        # other healthy backends, then sheds a TYPED 503
        # {reason: upstream_failed} — never a bare 502.
        self.health_probes = bool(health_probes)
        self.health_threshold = int(health_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self.failover_retries = int(failover_retries)
        # Fleet trace plane (both default off = old router byte-for-
        # byte).  journey_ring: adopt-or-mint X-Request-Id/traceparent,
        # propagate on every leg, keep a bounded JourneyRecord ring
        # served at /router/debug/requests + /router/debug/trace.
        # access_log: one JSON line per completed/shed request on
        # stderr (the server's tpumlops.request contract).  With the log
        # on, stderr goes to a FILE (access_log_path) — a supervised
        # PIPE nobody drains would fill and block the router's event
        # loop mid-request under sustained traffic.
        self.journey_ring = int(journey_ring)
        self.access_log = bool(access_log)
        # Per-backend 1 s history (default off = old router byte-for-
        # byte): leg wall p50/p99 + error/failover/park buckets served
        # at /router/debug/timeseries for the fleet anomaly observatory.
        self.timeseries_ring = int(timeseries_ring)
        # Multi-model multiplexing (default off = old router byte-for-
        # byte): the model id of a POST's /v2/models/<m>/ path joins the
        # routing decision — requests reach only replicas whose attached
        # model (per-backend "model" config key) matches, park per-model
        # otherwise, and the park release awaits the model's attach.
        self.mux_models = int(mux_models)
        self.access_log_path: pathlib.Path | None = None
        self._stderr_file = None
        self.proc: subprocess.Popen | None = None
        self.admin = RouterAdmin(port)

    def start(self, wait_s: float = 5.0) -> "RouterProcess":
        argv = [
            str(self.binary),
            "--port", str(self.port),
            "--namespace", self.namespace,
            "--deployment", self.deployment,
        ]
        if self.park_buffer > 0:
            argv += [
                "--park-buffer", str(self.park_buffer),
                "--park-timeout-s", str(self.park_timeout_s),
            ]
        if self.affinity_tokens > 0:
            argv += [
                "--affinity-tokens", str(self.affinity_tokens),
                "--kv-handoff", "1" if self.kv_handoff else "0",
                "--handoff-retries", str(self.handoff_retries),
            ]
        if self.health_probes:
            argv += [
                "--health-probes", "1",
                "--health-threshold", str(self.health_threshold),
                "--probe-interval-s", str(self.probe_interval_s),
            ]
        if self.failover_retries > 0:
            argv += ["--failover-retries", str(self.failover_retries)]
        if self.journey_ring > 0:
            argv += ["--journey-ring", str(self.journey_ring)]
        if self.timeseries_ring > 0:
            argv += ["--timeseries-ring", str(self.timeseries_ring)]
        if self.access_log:
            argv += ["--access-log", "1"]
        if self.mux_models:
            argv += ["--mux-models", "1"]
        for name, spec in self.backends.items():
            host, port, weight = spec[0], spec[1], spec[2]
            role = spec[3] if len(spec) > 3 else None
            arg = f"{name}={host}:{port}:{weight}"
            if role:
                arg += f":{role}"
            argv += ["--backend", arg]
        if self.access_log:
            import tempfile

            fd, path = tempfile.mkstemp(
                prefix="tpumlops-router-access-", suffix=".log"
            )
            self.access_log_path = pathlib.Path(path)
            self._stderr_file = os.fdopen(fd, "wb")
            stderr_target = self._stderr_file
        else:
            stderr_target = subprocess.PIPE
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.DEVNULL, stderr=stderr_target
        )
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            if self.admin.healthy():
                return self
            if self.proc.poll() is not None:
                if self.proc.stderr is not None:
                    err = self.proc.stderr.read().decode()
                elif self.access_log_path is not None:
                    err = self.access_log_path.read_text()
                else:
                    err = ""
                raise RuntimeError(f"router exited at startup: {err}")
            time.sleep(0.02)
        self.stop()
        raise TimeoutError("router did not become healthy")

    def access_log_lines(self) -> list[dict]:
        """Parsed ``tpumlops.router.access`` JSON lines written so far
        (requires ``access_log=True``)."""
        if self.access_log_path is None or not self.access_log_path.exists():
            return []
        out = []
        for line in self.access_log_path.read_text().splitlines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # startup banner / circuit logs ride stderr too
            if rec.get("logger") == "tpumlops.router.access":
                out.append(rec)
        return out

    def stop(self) -> None:
        if self.proc is not None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
            if self.proc.stderr:
                self.proc.stderr.close()
            self.proc = None
        if self._stderr_file is not None:
            self._stderr_file.close()
            self._stderr_file = None
        if self.access_log_path is not None:
            # Temp-file hygiene: repeated test/bench runs must not
            # litter the temp dir (read access_log_lines BEFORE stop).
            import contextlib

            with contextlib.suppress(OSError):
                self.access_log_path.unlink()
            self.access_log_path = None

    def __enter__(self) -> "RouterProcess":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
