"""Device-mesh construction from the CRD's ``meshShape`` field.

A ``meshShape`` like ``{"dp": 1, "tp": 8}`` (see ``TpuSpec``,
``utils/config.py``) becomes a ``jax.sharding.Mesh`` whose axes drive all
sharding in the data plane.  Axis names are fixed so model code, server
engine, and manifests agree:

- ``dp`` — data parallel (batch split; gradients/logits all-reduced)
- ``tp`` — tensor parallel (heads/mlp split; activations all-reduced over ICI)
- ``sp`` — sequence/context parallel (ring attention shifts KV blocks)
- ``pp`` — pipeline parallel (layer groups)
- ``ep`` — expert parallel (MoE experts)

Mesh axis order matters for ICI locality on a v5e slice: the innermost
(fastest-varying) axis gets neighboring chips, so ``tp`` — which carries the
per-layer all-reduces — is placed LAST, mirroring the physical torus.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "dp"
AXIS_PIPE = "pp"
AXIS_EXPERT = "ep"
AXIS_SEQ = "sp"
AXIS_TENSOR = "tp"

# Outer-to-inner canonical order: collectives-heavy axes innermost so they
# map onto adjacent chips (ICI hops) rather than across the slice.
MESH_AXIS_ORDER: tuple[str, ...] = (
    AXIS_DATA,
    AXIS_PIPE,
    AXIS_EXPERT,
    AXIS_SEQ,
    AXIS_TENSOR,
)


def build_mesh(
    mesh_shape: Mapping[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``Mesh`` from ``{axis: size}``.

    Axes are laid out in ``MESH_AXIS_ORDER`` regardless of dict order; axes
    of size 1 are kept (harmless, makes PartitionSpecs uniform).  The product
    of sizes must equal the device count.
    """
    unknown = set(mesh_shape) - set(MESH_AXIS_ORDER)
    if unknown:
        raise ValueError(
            f"unknown mesh axes {sorted(unknown)}; known: {list(MESH_AXIS_ORDER)}"
        )
    if devices is None:
        devices = jax.devices()
    axis_names = tuple(a for a in MESH_AXIS_ORDER if a in mesh_shape)
    sizes = tuple(int(mesh_shape[a]) for a in axis_names)
    if any(s < 1 for s in sizes):
        raise ValueError(f"mesh axis sizes must be >= 1, got {dict(mesh_shape)}")
    total = int(np.prod(sizes)) if sizes else 1
    if total != len(devices):
        raise ValueError(
            f"meshShape {dict(mesh_shape)} needs {total} devices, "
            f"have {len(devices)}"
        )
    dev_array = np.asarray(devices, dtype=object).reshape(sizes)
    return Mesh(dev_array, axis_names)


def local_mesh(mesh_shape: Mapping[str, int] | None = None) -> Mesh:
    """Mesh over all local devices; default one ``tp`` axis spanning them."""
    devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = {AXIS_TENSOR: len(devices)}
    return build_mesh(mesh_shape, devices)
