"""Logical-axis sharding rules.

Model code annotates arrays with *logical* axis names (``"batch"``,
``"heads"``, ``"mlp"`` ...); a ``ShardingRules`` table maps logical axes to
mesh axes (or to ``None`` = replicated).  Swapping the rules re-shards the
whole model without touching model code — the standard JAX/TPU recipe
(scaling-book style): pick a mesh, annotate shardings, let XLA insert the
collectives.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Mapping, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import AXIS_DATA, AXIS_SEQ, AXIS_TENSOR

# Logical axis names used across the model zoo.
LOGICAL_BATCH = "batch"
LOGICAL_SEQ = "seq"
LOGICAL_EMBED = "embed"  # model/residual dimension
LOGICAL_HEADS = "heads"  # attention heads (query)
LOGICAL_KV_HEADS = "kv_heads"  # attention heads (key/value, GQA)
LOGICAL_HEAD_DIM = "head_dim"
LOGICAL_MLP = "mlp"  # feed-forward hidden dimension
LOGICAL_VOCAB = "vocab"
LOGICAL_EXPERT = "expert"


@dataclass(frozen=True)
class ShardingRules:
    """Mapping logical axis -> mesh axis (or None for replicated)."""

    rules: Mapping[str, str | None]

    def mesh_axis(self, logical: str | None) -> str | None:
        if logical is None:
            return None
        return self.rules.get(logical)

    def spec(self, logical_axes: tuple[str | None, ...]) -> PartitionSpec:
        seen: list[str | None] = []
        for ax in logical_axes:
            mesh_ax = self.mesh_axis(ax)
            # A mesh axis may appear at most once in a PartitionSpec; later
            # occurrences fall back to replication.
            seen.append(mesh_ax if mesh_ax not in [s for s in seen if s] else None)
        return PartitionSpec(*seen)


# Default rules for transformer serving:
#  - batch over dp, sequence over sp (ring attention),
#  - heads/mlp/vocab over tp (Megatron-style column/row splits),
#  - embed replicated (the residual stream stays whole per chip).
TRANSFORMER_RULES = ShardingRules(
    rules={
        LOGICAL_BATCH: AXIS_DATA,
        LOGICAL_SEQ: AXIS_SEQ,
        LOGICAL_EMBED: None,
        LOGICAL_HEADS: AXIS_TENSOR,
        LOGICAL_KV_HEADS: AXIS_TENSOR,
        LOGICAL_HEAD_DIM: None,
        LOGICAL_MLP: AXIS_TENSOR,
        LOGICAL_VOCAB: AXIS_TENSOR,
        LOGICAL_EXPERT: None,
    }
)


def logical_spec(
    logical_axes: tuple[str | None, ...], rules: ShardingRules | None = None
) -> PartitionSpec:
    return (rules or TRANSFORMER_RULES).spec(logical_axes)


def logical_sharding(
    mesh: Mesh,
    logical_axes: tuple[str | None, ...],
    rules: ShardingRules | None = None,
) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(logical_axes, rules))


class PartitionRuleError(ValueError):
    """Typed failure of regex rule matching: a rule produced a
    PartitionSpec whose rank does not match the leaf it matched.  Raised
    at match time — BEFORE any device_put — so a bad rule table fails
    with the leaf path and both ranks in the message instead of an
    opaque XLA shape error at the first sharded dispatch."""


def _leaf_path(path) -> str:
    """jax key-path -> "a/b/0" (the regex namespace rule tables match)."""
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:  # pragma: no cover - future key types
            parts.append(str(entry))
    return "/".join(parts)


def match_partition_rules(
    rules: Sequence[tuple[str, PartitionSpec]], tree: Any
) -> Any:
    """Regex rule table -> PartitionSpec pytree (the SNIPPETS [2] shape).

    Each leaf's path (``/``-joined dict keys / sequence indices) is
    matched with ``re.search`` against the rules IN ORDER — the first
    match wins, so put specific rules above general ones.  Leaves no
    rule matches fall back to fully REPLICATED (``PartitionSpec()``):
    an unmatched auxiliary leaf (a norm, a scalar) must never silently
    shard, and must never fail the whole tree either.  Scalar leaves
    are always replicated regardless of rules.

    A matched NON-EMPTY spec whose rank differs from the leaf's raises
    :class:`PartitionRuleError` naming the path, the rule, and both
    ranks — rank drift between a rule table and the param tree it
    describes is a bug, not a fallback case, in BOTH directions: an
    under-rank spec would silently shard the wrong (leading) axis,
    which is worse than the over-rank crash.  ``PartitionSpec()`` (an
    explicit fully-replicated rule) is valid for any rank.
    """
    compiled = [(re.compile(pat), pat, spec) for pat, spec in rules]

    def _match(path, leaf):
        name = _leaf_path(path)
        ndim = getattr(leaf, "ndim", 0)
        if ndim == 0:
            return PartitionSpec()
        for creg, pat, spec in compiled:
            if creg.search(name) is None:
                continue
            if len(spec) != 0 and len(spec) != ndim:
                raise PartitionRuleError(
                    f"partition rule {pat!r} produced rank-{len(spec)} "
                    f"spec {spec} for rank-{ndim} leaf {name!r}"
                )
            return spec
        return PartitionSpec()

    return jax.tree_util.tree_map_with_path(_match, tree)


def shard_pytree(
    tree: Any,
    axes_tree: Any,
    mesh: Mesh,
    rules: ShardingRules | None = None,
) -> Any:
    """Device-put a parameter pytree according to a matching pytree of
    logical-axis tuples (``None`` leaf = fully replicated)."""

    def _put(x, axes):
        if axes is None:
            sh = NamedSharding(mesh, PartitionSpec())
        else:
            sh = logical_sharding(mesh, axes, rules)
        return jax.device_put(x, sh)

    return jax.tree.map(
        _put, tree, axes_tree, is_leaf=lambda t: t is None
    )
