"""Device meshes, sharding rules, collectives, and multi-host init.

The reference has no distributed-communication backend at all — its only
"parallelism" is weighted traffic between two predictors (SURVEY.md §2.3).
This package is the TPU-native equivalent mandated for the rebuild:
XLA collectives over ICI within a slice (driven by ``jax.jit`` with
``NamedSharding``/``shard_map`` over a ``Mesh``) and DCN across hosts via
``jax.distributed.initialize``.
"""

from .mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_TENSOR,
    MESH_AXIS_ORDER,
    build_mesh,
    local_mesh,
)
from .sharding import (
    LOGICAL_BATCH,
    LOGICAL_EMBED,
    LOGICAL_HEADS,
    LOGICAL_KV_HEADS,
    LOGICAL_MLP,
    LOGICAL_SEQ,
    LOGICAL_VOCAB,
    PartitionRuleError,
    ShardingRules,
    TRANSFORMER_RULES,
    logical_sharding,
    logical_spec,
    match_partition_rules,
    shard_pytree,
)
from .collectives import ring_shift, shard_map_compat
from .distributed import maybe_initialize_distributed

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_PIPE",
    "AXIS_SEQ",
    "AXIS_TENSOR",
    "MESH_AXIS_ORDER",
    "build_mesh",
    "local_mesh",
    "ShardingRules",
    "TRANSFORMER_RULES",
    "LOGICAL_BATCH",
    "LOGICAL_EMBED",
    "LOGICAL_HEADS",
    "LOGICAL_KV_HEADS",
    "LOGICAL_MLP",
    "LOGICAL_SEQ",
    "LOGICAL_VOCAB",
    "logical_spec",
    "logical_sharding",
    "match_partition_rules",
    "PartitionRuleError",
    "shard_pytree",
    "ring_shift",
    "shard_map_compat",
    "maybe_initialize_distributed",
]
