"""Multi-host initialization (DCN) for multi-host TPU slices.

A v5e predictor larger than one host (e.g. v5e-16) runs as N pods that must
form one JAX process group before any collective can cross hosts.  In the
manifests each pod gets ``TPU_WORKER_HOSTNAMES``/coordinator env from the
GKE TPU webhook; here we translate that into ``jax.distributed.initialize``.

Single-host (or test/CPU) processes are a no-op, so the same server code
runs everywhere.
"""

from __future__ import annotations

import logging
import os

_log = logging.getLogger(__name__)

_initialized = False


def maybe_initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize DCN process group if (and only if) multi-host env is set.

    Resolution order: explicit args > environment
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    or the GKE TPU defaults that jax reads natively).  Returns True when
    ``jax.distributed.initialize`` was called.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    if num_processes is None and env_np is not None:
        num_processes = int(env_np)
    if process_id is None and env_pid is not None:
        process_id = int(env_pid)

    if not coordinator_address or not num_processes or num_processes <= 1:
        _log.debug("single-process JAX (no coordinator configured)")
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    _log.info(
        "jax.distributed initialized: %d processes, this is process %s",
        num_processes,
        process_id,
    )
    return True
