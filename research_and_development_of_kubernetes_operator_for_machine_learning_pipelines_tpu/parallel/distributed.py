"""Multi-host initialization (DCN) for multi-host TPU slices.

A v5e predictor larger than one host (e.g. v5e-16) runs as N pods that must
form one JAX process group before any collective can cross hosts.  In the
manifests each pod gets ``TPU_WORKER_HOSTNAMES``/coordinator env from the
GKE TPU webhook; here we translate that into ``jax.distributed.initialize``.

Single-host (or test/CPU) processes are a no-op, so the same server code
runs everywhere.
"""

from __future__ import annotations

import logging
import os

_log = logging.getLogger(__name__)

_initialized = False


def configure_cpu_rehearsal(num_local_devices: int = 1) -> None:
    """Rehearse the multi-host (DCN) path on CPU processes.

    Selects the CPU backend and its cross-process collectives
    implementation (Gloo) so ``maybe_initialize_distributed`` can form a
    REAL ``jax.distributed`` group between OS processes on one machine:
    after it, ``jax.device_count() > jax.local_device_count()`` and
    ``psum``/``all_gather`` genuinely cross process boundaries — the same
    code path a v5e multi-host slice takes over DCN, minus the TPU
    transport.  Must run before the group forms; it drops any
    already-created backends because environments that pre-import JAX
    (or pytest's conftest) may have initialized a different platform.

    Proven by ``tests/test_distributed_group.py``: two processes, one
    coordinator, a cross-process ``psum`` with bitwise-checked results on
    both ranks (SURVEY §2.3 distributed-comm-backend obligation).
    """
    import jax
    from jax.extend import backend

    # Clear BEFORE the device-count update: with a backend already live
    # (pre-imported JAX), jax_num_cpu_devices raises "config should be
    # updated before backends are initialized".
    jax.config.update("jax_platforms", "cpu")
    backend.clear_backends()
    jax.config.update("jax_num_cpu_devices", num_local_devices)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")


def maybe_initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize DCN process group if (and only if) multi-host env is set.

    Resolution order: explicit args > environment
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    or the GKE TPU defaults that jax reads natively).  Returns True when
    ``jax.distributed.initialize`` was called.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    if num_processes is None and env_np is not None:
        num_processes = int(env_np)
    if process_id is None and env_pid is not None:
        process_id = int(env_pid)

    if not coordinator_address or not num_processes or num_processes <= 1:
        _log.debug("single-process JAX (no coordinator configured)")
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    _log.info(
        "jax.distributed initialized: %d processes, this is process %s",
        num_processes,
        process_id,
    )
    return True
