"""Collective helpers used inside ``shard_map``-ped kernels.

XLA emits the actual ICI/DCN traffic; these are thin, named wrappers so
model code reads as intent (``ring_shift`` for ring attention, etc.).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def ring_shift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Shift ``x`` around the mesh-axis ring by ``shift`` hops.

    Device i receives the block from device ``(i - shift) % n``.  On a TPU
    torus this is nearest-neighbor ICI traffic — the primitive under ring
    attention and pipelined all-gathers.
    """
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_gather_concat(x: jax.Array, axis_name: str, axis: int = 0) -> jax.Array:
    """All-gather shards and concatenate along ``axis`` (tiled=True)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def psum(x, axis_name: str):
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    return lax.pmean(x, axis_name)


def reduce_scatter(x: jax.Array, axis_name: str, axis: int = 0) -> jax.Array:
    """Sum across the axis and leave each device with its shard of the
    result (the memory-lean half of an all-reduce)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)
