"""Collective helpers used inside ``shard_map``-ped kernels.

XLA emits the actual ICI/DCN traffic; this module carries the
communication *patterns* the models compose:

- named primitives (``ring_shift``, ``psum``, ``reduce_scatter``) so
  kernel code reads as intent;
- :func:`ring_shift_bidirectional` — full-duplex torus links, both ring
  directions at once (the bandwidth-optimal ring-attention step);
- :func:`hierarchical_psum` — ICI-then-DCN all-reduce that crosses the
  slow links exactly once per byte (multi-host slices);
- :func:`all_to_all_swap` — the sequence-parallel head/sequence
  re-shard pivot (Ulysses-style).

Semantics are pinned by ``tests/test_parallel.py`` on the virtual
8-device mesh — the same SPMD program a v5e slice compiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def shard_map_compat(fn, mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` across jax versions.

    jax >= 0.5 exposes ``jax.shard_map`` with ``check_vma``; 0.4.x only
    has ``jax.experimental.shard_map.shard_map`` with the kwarg spelled
    ``check_rep``.  One call site, both APIs."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


def axis_size_compat(axis_name: str) -> int:
    """``lax.axis_size`` across jax versions (0.4.x lacks it; the bound
    axis env makes ``psum(1, name)`` a compile-time constant there)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def ring_shift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Shift ``x`` around the mesh-axis ring by ``shift`` hops.

    Device i receives the block from device ``(i - shift) % n``.  On a TPU
    torus this is nearest-neighbor ICI traffic — the primitive under ring
    attention and pipelined all-gathers.
    """
    n = axis_size_compat(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_gather_concat(x: jax.Array, axis_name: str, axis: int = 0) -> jax.Array:
    """All-gather shards and concatenate along ``axis`` (tiled=True)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def psum(x, axis_name: str):
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    return lax.pmean(x, axis_name)


def reduce_scatter(x: jax.Array, axis_name: str, axis: int = 0) -> jax.Array:
    """Sum across the axis and leave each device with its shard of the
    result (the memory-lean half of an all-reduce)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


def ring_shift_bidirectional(
    x: jax.Array, axis_name: str, axis: int = 0
) -> jax.Array:
    """One bandwidth-optimal ring step: both halves move at once.

    A torus link is full-duplex; a unidirectional ring step uses half the
    wire.  Splitting ``x`` along ``axis`` and shifting the halves in
    opposite directions doubles per-step ICI bandwidth — the standard
    trick under bidirectional ring attention.  After ``n // 2`` steps
    every device has seen every block (vs ``n - 1`` unidirectional).
    Returns the two halves re-concatenated: front half came from the left
    neighbor, back half from the right.
    """
    n = x.shape[axis]
    if n % 2:
        raise ValueError(f"axis {axis} of size {n} cannot split into halves")
    fwd, bwd = jnp.split(x, 2, axis=axis)
    return jnp.concatenate(
        [ring_shift(fwd, axis_name, 1), ring_shift(bwd, axis_name, -1)],
        axis=axis,
    )


def hierarchical_psum(
    x: jax.Array, fast_axis: str, slow_axis: str, scatter_axis: int = 0
) -> jax.Array:
    """All-reduce across two mesh axes, cheap-link-aware.

    For a multi-host mesh (``fast_axis`` = ICI within a slice,
    ``slow_axis`` = DCN across hosts) a flat ``psum`` over both axes makes
    every byte cross DCN ``fast-1`` redundant times.  The hierarchical
    form sends each byte over the slow links exactly once:

    1. reduce-scatter over ``fast_axis``  (each device owns 1/fast of the
       partial sum — pure ICI),
    2. psum the small shard over ``slow_axis``  (the only DCN traffic:
       ``|x| / fast`` bytes per device),
    3. all-gather over ``fast_axis``  (pure ICI again).

    Numerically identical to ``psum(psum(x, fast), slow)`` up to float
    reduction order; ``scatter_axis``'s size must divide by the fast-axis
    size.
    """
    shard = reduce_scatter(x, fast_axis, axis=scatter_axis)
    shard = psum(shard, slow_axis)
    return all_gather_concat(shard, fast_axis, axis=scatter_axis)


def all_to_all_swap(
    x: jax.Array, axis_name: str, split_axis: int, concat_axis: int
) -> jax.Array:
    """Transpose which dimension is sharded across ``axis_name``.

    The sequence-parallel pivot (DeepSpeed-Ulysses style): attention
    wants heads local and sequence sharded for QKV projections, but the
    softmax needs the full sequence per head.  ``all_to_all`` re-shards
    from split over ``split_axis`` to split over ``concat_axis`` with
    each device exchanging only ``1/n``-sized blocks — O(|x|) total
    traffic vs an all-gather's O(n * |x|).
    """
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True,
    )
