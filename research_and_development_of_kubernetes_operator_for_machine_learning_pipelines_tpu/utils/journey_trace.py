"""Journey-ring trace loader: the planner's replayable traffic input.

The router's ``GET /router/debug/requests`` export is a spec'd,
replayable record of real traffic — every request's arrival instant
(``ts_us``, microseconds since router start), request id, and journey
metadata.  The offline SLO planner (``operator/planner.py``) replays
those arrivals through an analytic cost model, so this loader is the
contract boundary between the live fleet and the planner: it parses the
export into typed :class:`TraceRequest` rows and rejects anything it
does not understand with :class:`TraceFormatError` instead of
mis-parsing a drifted export into a silently wrong plan.

Versioning: the export carries a top-level ``format_version`` (added in
the same change as this loader).  Absence is tolerated — every export
that predates the field IS version 1 — but a PRESENT version this
loader does not know is a typed rejection.  Unknown per-request keys
are ignored (the journey record grows fields routinely); the loader
additionally honors OPTIONAL extension keys the live export does not
emit (``prompt_tokens``, ``max_new_tokens``, ``slo_class``) so
hand-written and augmented fixture traces can carry the workload shape
the planner's cost model needs.  Requests missing those keys replay at
documented defaults.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Mapping

JOURNEY_TRACE_FORMAT_VERSION = 1

# Replay defaults for exports that carry arrivals only (the live router
# journey ring does not know token counts): a mid-size chat turn.
DEFAULT_PROMPT_TOKENS = 128
DEFAULT_MAX_NEW_TOKENS = 64
DEFAULT_SLO_CLASS = "interactive"

SLO_CLASSES = ("interactive", "batch", "best-effort")


class TraceFormatError(ValueError):
    """The trace payload is not a journey export this loader knows."""


@dataclass(frozen=True)
class TraceRequest:
    """One replayable arrival."""

    arrival_s: float  # seconds since the first request in the trace
    request_id: str = ""
    prompt_tokens: int = DEFAULT_PROMPT_TOKENS
    max_new_tokens: int = DEFAULT_MAX_NEW_TOKENS
    slo_class: str = DEFAULT_SLO_CLASS


@dataclass(frozen=True)
class JourneyTrace:
    """A parsed journey export: arrivals sorted ascending."""

    requests: tuple[TraceRequest, ...]
    started_unix: float = 0.0
    format_version: int = JOURNEY_TRACE_FORMAT_VERSION

    @property
    def span_s(self) -> float:
        """First-to-last arrival span (0 for <= 1 request)."""
        if not self.requests:
            return 0.0
        return self.requests[-1].arrival_s - self.requests[0].arrival_s


def _parse_request(entry, index: int) -> tuple[float, TraceRequest]:
    if not isinstance(entry, Mapping):
        raise TraceFormatError(
            f"journey trace requests[{index}] is not an object: "
            f"{type(entry).__name__}"
        )
    # Arrival instant: ts_us (journey-ring monotonic microseconds) is
    # authoritative; ``wall`` (unix seconds) is the fallback for
    # hand-written fixtures.  Neither present -> typed reject.
    if "ts_us" in entry:
        try:
            t = float(entry["ts_us"]) / 1e6
        except (TypeError, ValueError):
            raise TraceFormatError(
                f"journey trace requests[{index}].ts_us is not numeric: "
                f"{entry['ts_us']!r}"
            ) from None
    elif "wall" in entry:
        try:
            t = float(entry["wall"])
        except (TypeError, ValueError):
            raise TraceFormatError(
                f"journey trace requests[{index}].wall is not numeric: "
                f"{entry['wall']!r}"
            ) from None
    else:
        raise TraceFormatError(
            f"journey trace requests[{index}] has neither ts_us nor wall "
            "— not a journey-ring export"
        )
    slo_class = str(entry.get("slo_class", DEFAULT_SLO_CLASS))
    if slo_class not in SLO_CLASSES:
        raise TraceFormatError(
            f"journey trace requests[{index}].slo_class {slo_class!r} "
            f"not in {SLO_CLASSES}"
        )
    try:
        prompt_tokens = int(
            entry.get("prompt_tokens", DEFAULT_PROMPT_TOKENS)
        )
        max_new_tokens = int(
            entry.get("max_new_tokens", DEFAULT_MAX_NEW_TOKENS)
        )
    except (TypeError, ValueError):
        raise TraceFormatError(
            f"journey trace requests[{index}] token counts are not "
            "integers"
        ) from None
    if prompt_tokens <= 0 or max_new_tokens <= 0:
        raise TraceFormatError(
            f"journey trace requests[{index}] token counts must be "
            f"positive, got prompt_tokens={prompt_tokens} "
            f"max_new_tokens={max_new_tokens}"
        )
    return t, TraceRequest(
        arrival_s=0.0,  # rebased below once the minimum is known
        request_id=str(entry.get("request_id", "")),
        prompt_tokens=prompt_tokens,
        max_new_tokens=max_new_tokens,
        slo_class=slo_class,
    )


def load_journey_trace(source) -> JourneyTrace:
    """Parse a ``/router/debug/requests`` export (or fixture).

    ``source`` is a path (str / Path) to a JSON file, or the
    already-decoded dict.  Raises :class:`TraceFormatError` on anything
    that is not a journey export this loader understands — including a
    PRESENT ``format_version`` newer than
    :data:`JOURNEY_TRACE_FORMAT_VERSION`.
    """
    if isinstance(source, (str, Path)):
        try:
            payload = json.loads(Path(source).read_text())
        except json.JSONDecodeError as e:
            raise TraceFormatError(
                f"journey trace {source} is not valid JSON: {e}"
            ) from None
    else:
        payload = source
    if not isinstance(payload, Mapping):
        raise TraceFormatError(
            f"journey trace payload is not an object: "
            f"{type(payload).__name__}"
        )
    version = payload.get("format_version", JOURNEY_TRACE_FORMAT_VERSION)
    if not isinstance(version, int) or isinstance(version, bool):
        raise TraceFormatError(
            f"journey trace format_version is not an integer: {version!r}"
        )
    if version != JOURNEY_TRACE_FORMAT_VERSION:
        raise TraceFormatError(
            f"journey trace format_version {version} is not supported "
            f"(this loader knows version {JOURNEY_TRACE_FORMAT_VERSION}); "
            "refusing to mis-parse a drifted export"
        )
    raw = payload.get("requests")
    if not isinstance(raw, list):
        raise TraceFormatError(
            "journey trace has no 'requests' list — not a "
            "/router/debug/requests export"
        )
    parsed = [_parse_request(entry, i) for i, entry in enumerate(raw)]
    parsed.sort(key=lambda tr: tr[0])
    t0 = parsed[0][0] if parsed else 0.0
    requests = tuple(
        TraceRequest(
            arrival_s=t - t0,
            request_id=req.request_id,
            prompt_tokens=req.prompt_tokens,
            max_new_tokens=req.max_new_tokens,
            slo_class=req.slo_class,
        )
        for t, req in parsed
    )
    started = payload.get("started_unix", 0.0)
    try:
        started = float(started)
    except (TypeError, ValueError):
        raise TraceFormatError(
            f"journey trace started_unix is not numeric: {started!r}"
        ) from None
    return JourneyTrace(
        requests=requests,
        started_unix=started,
        format_version=version,
    )
