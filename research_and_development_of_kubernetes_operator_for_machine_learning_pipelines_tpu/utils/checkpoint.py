"""Checkpoint/resume for model parameters (SURVEY §5).

The reference's only persisted state is the CR status subresource; model
weights live in MLflow/MinIO and are pulled fresh by each predictor.  The
rebuild adds orbax-backed checkpointing for the cases the reference cannot
cover: sharded params written per-host from a multi-host slice, and local
warm-restart of a server without re-pulling the artifact store.

``save``/``restore`` round-trip arbitrary param pytrees; ``restore`` can
restore directly into a sharding (each host reads only its shards).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any


def save(path: str | Path, tree: Any) -> None:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=True)


def restore(path: str | Path, template: Any | None = None) -> Any:
    """Restore a pytree; ``template`` (abstract arrays or a matching pytree,
    optionally carrying shardings) restores sharded-on-load."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        if template is None:
            return ckptr.restore(path)
        return ckptr.restore(path, template)
