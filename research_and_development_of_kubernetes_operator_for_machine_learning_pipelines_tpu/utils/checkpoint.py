"""Checkpoint/resume subsystem (SURVEY §5).

The reference's only persisted state is the CR status subresource; model
weights live in MLflow/MinIO and are pulled fresh by each predictor
(``mlflow_operator.py:199,:214``).  The rebuild owns a data plane, so it
owes the piece the reference delegates: durable, versioned weight state
with sharded-on-load restore for multi-host predictors and warm restarts
that skip the artifact store.

Two layers:

- :func:`save` / :func:`restore` — one-shot pytree round-trip (orbax
  tensor I/O underneath; each host materializes only its shards when the
  template carries shardings).
- :class:`CheckpointManager` — the subsystem: a versioned step layout
  with atomic publish (write to a scratch name, fsync-rename, then a
  ``COMMITTED`` marker — a torn save is never listed), background/async
  saves so a serving process snapshots without stalling its decode loop,
  keep-N garbage collection, and JSON metadata per step (wall time,
  user tags) for operational forensics.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Any

try:
    import fcntl
except ImportError:  # non-POSIX: single-process use only
    fcntl = None

_log = logging.getLogger(__name__)

_COMMITTED = "COMMITTED"  # marker file: step directory is fully written


def _fsync_path(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_tree(root: Path) -> None:
    """fsync every file and directory under ``root`` (and root itself).

    The atomic-publish guarantee needs the DATA durable before the
    rename and the COMMITTED marker: a crash that persists the tiny
    marker but not the tensor writes would otherwise surface a torn
    checkpoint as restorable.
    """
    for dirpath, _dirnames, filenames in os.walk(root, topdown=False):
        for name in filenames:
            _fsync_path(Path(dirpath) / name)
        _fsync_path(Path(dirpath))


def save(path: str | Path, tree: Any) -> None:
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=True)


def restore(path: str | Path, template: Any | None = None) -> Any:
    """Restore a pytree; ``template`` (abstract arrays or a matching pytree,
    optionally carrying shardings) restores sharded-on-load."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        if template is None:
            return ckptr.restore(path)
        return ckptr.restore(path, template)


class AsyncSaveHandle:
    """Handle for a background save: ``wait()`` re-raises its failure."""

    def __init__(self, thread: threading.Thread):
        self._thread = thread
        self.error: BaseException | None = None

    def wait(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint save still running")
        if self.error is not None:
            raise self.error

    def done(self) -> bool:
        return not self._thread.is_alive()


class CheckpointManager:
    """Versioned checkpoints under one root: ``<root>/step_<N>/``.

    Guarantees:

    - **Atomic publish.**  A step is written to ``.tmp_step_<N>``, then
      renamed, then marked with a ``COMMITTED`` file.  ``steps()`` lists
      only committed steps, so a crash mid-save leaves garbage (cleaned
      on the next save) but never a restorable-looking torn checkpoint.
    - **Monotonic steps.**  Re-saving an existing step is refused unless
      ``overwrite=True`` — silent clobbering of a published version is
      how serving fleets end up with two weight sets under one name.
    - **Keep-N GC.**  After each successful save, committed steps beyond
      ``max_to_keep`` (oldest first) are deleted.
    - **Async.**  ``save_async`` runs the same path on a daemon thread;
      the returned handle's ``wait()`` surfaces errors.  One in-flight
      async save at a time (a second request waits) — concurrent orbax
      writes into one root interleave badly.
    """

    def __init__(self, root: str | Path, max_to_keep: int | None = 3):
        self.root = Path(root).absolute()
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._save_lock = threading.Lock()
        self._recover_interrupted()

    @contextlib.contextmanager
    def _os_lock(self):
        """Cross-PROCESS exclusion for every root-mutating section.

        ``_save_lock`` only serializes threads of one process; recovery
        at open time also mutates the root, so a second process opening
        the manager during another process's overwrite window (between
        ``final.rename(old)`` and ``tmp.rename(final)``) would "restore"
        the parked predecessor and break the in-flight saver's final
        rename.  An flock on ``<root>/.lock`` closes that window: saves
        and open-time recovery block each other across processes.  On
        platforms without fcntl this degrades to the documented
        single-writer-process assumption.
        """
        if fcntl is None:
            yield
            return
        fd = os.open(self.root / ".lock", os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _recover_interrupted(self) -> None:
        """Heal crash leftovers at open time, for EVERY step.

        An overwrite that crashed between parking the committed
        predecessor under ``.replaced_<step>`` and committing its
        replacement leaves the step's only committed bytes under a name
        ``steps()`` never lists.  Waiting for a same-step ``save()`` to
        notice would hide the step from ``restore()`` indefinitely (and
        leak the directory if that step is never re-saved) — so the scan
        runs on open: restore the predecessor when the step is
        uncommitted, scrap the leftover when the overwrite did commit.
        """
        with self._save_lock, self._os_lock():
            for old in self.root.glob(".replaced_step_*"):
                final = self.root / old.name[len(".replaced_"):]
                if (final / _COMMITTED).exists():
                    shutil.rmtree(old)  # overwrite committed; this is trash
                else:
                    if final.exists():
                        shutil.rmtree(final)  # uncommitted replacement
                    old.rename(final)
                    _fsync_path(self.root)

    # -- layout --------------------------------------------------------------

    def _step_dir(self, step: int) -> Path:
        return self.root / f"step_{step:08d}"

    def steps(self) -> list[int]:
        """Committed steps, ascending."""
        out = []
        for p in self.root.glob("step_*"):
            if (p / _COMMITTED).exists():
                try:
                    out.append(int(p.name.split("_", 1)[1]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def metadata(self, step: int) -> dict:
        return json.loads((self._step_dir(step) / _COMMITTED).read_text())

    # -- save ----------------------------------------------------------------

    def save(
        self,
        step: int,
        tree: Any,
        *,
        tags: dict | None = None,
        overwrite: bool = False,
    ) -> Path:
        with self._save_lock, self._os_lock():
            final = self._step_dir(step)
            tmp = self.root / f".tmp_{final.name}"
            old = self.root / f".replaced_{final.name}"
            if tmp.exists():  # torn leftover from a previous crash
                shutil.rmtree(tmp)
            if old.exists():
                if (final / _COMMITTED).exists():
                    # The previous overwrite committed; the leftover is
                    # just its trash.
                    shutil.rmtree(old)
                else:
                    # Crashed between renaming the predecessor away and
                    # committing its replacement: the .replaced_ copy is
                    # the ONLY committed data for this step.  Restore it
                    # before doing anything destructive — deleting it
                    # here and then failing the new write would lose a
                    # step save() once reported durable.
                    if final.exists():
                        shutil.rmtree(final)  # uncommitted replacement
                    old.rename(final)
                    _fsync_path(self.root)
            replacing = (final / _COMMITTED).exists()
            if replacing and not overwrite:
                raise FileExistsError(
                    f"step {step} already committed at {final} "
                    "(pass overwrite=True to replace)"
                )
            if final.exists() and not replacing:
                shutil.rmtree(final)  # renamed but never committed = torn

            t0 = time.time()
            save(tmp / "params", tree)
            # Durability order: data -> rename -> parent dir -> marker ->
            # parent dir.  Each fsync makes the previous step crash-safe
            # before the next makes it visible.  On overwrite the committed
            # predecessor stays in place (and restorable) until the
            # replacement's data is fully fsynced — the exposure window is
            # two renames + a marker write, not the multi-second orbax
            # save; a crash inside that window leaves both datasets on
            # disk (the predecessor under .replaced_*, scrapped next save).
            _fsync_tree(tmp)
            if replacing:
                final.rename(old)
                _fsync_path(self.root)
            tmp.rename(final)
            _fsync_path(self.root)
            # Marker goes through temp + rename so its existence is
            # all-or-nothing: a crash mid-write must not leave a
            # truncated COMMITTED file that steps() lists but
            # metadata() cannot parse.
            marker = final / _COMMITTED
            marker_tmp = final / (_COMMITTED + ".tmp")
            marker_tmp.write_text(
                json.dumps(
                    {
                        "step": step,
                        "written_at_unix": round(t0, 3),
                        "save_seconds": round(time.time() - t0, 3),
                        "tags": tags or {},
                    },
                    indent=1,
                )
            )
            _fsync_path(marker_tmp)
            marker_tmp.rename(marker)
            _fsync_path(final)
            if old.exists():
                shutil.rmtree(old)
            self._gc()
            return final

    def save_async(
        self, step: int, tree: Any, *, tags: dict | None = None,
        overwrite: bool = False,
    ) -> AsyncSaveHandle:
        """Snapshot without blocking the caller (e.g. a serving loop).

        The tree's device buffers are captured by reference; JAX arrays
        are immutable, so a concurrent decode step cannot mutate what
        this thread writes.
        """
        def run():
            try:
                self.save(step, tree, tags=tags, overwrite=overwrite)
            except BaseException as e:  # surfaced via handle.wait()
                handle.error = e
                _log.exception("async checkpoint save of step %d failed", step)

        t = threading.Thread(target=run, daemon=True, name=f"ckpt-save-{step}")
        handle = AsyncSaveHandle(t)
        t.start()
        return handle

    # -- restore -------------------------------------------------------------

    def restore(self, step: int | None = None, template: Any | None = None) -> Any:
        """Restore ``step`` (default: latest committed)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no committed checkpoints in {self.root}")
        final = self._step_dir(step)
        if not (final / _COMMITTED).exists():
            raise FileNotFoundError(f"step {step} is not committed in {self.root}")
        return restore(final / "params", template)

    # -- GC ------------------------------------------------------------------

    def _gc(self) -> None:
        if self.max_to_keep is None:
            return
        steps = self.steps()
        for step in steps[: max(0, len(steps) - self.max_to_keep)]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
