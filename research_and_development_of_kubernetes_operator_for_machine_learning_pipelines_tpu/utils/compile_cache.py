"""Persistent XLA compilation cache (SURVEY §7 hard part 3).

TPU cold-start is the canary killer: the first request into a freshly
scheduled 10%-traffic predictor triggers a 20–40 s XLA compile, which lands
in the Prometheus latency window and fails the promotion gate before the
model has served a single steady-state request.  The reference never faces
this (its Seldon ``MLFLOW_SERVER`` pods are interpreted CPU Python,
``mlflow_operator.py:198``); a TPU data plane must solve it.

Two layers of defense:

1. **Warmup before readiness** — the server compiles every batch bucket
   before answering the readiness probe (``server/app.py``), so no live
   request ever pays a compile.
2. **This module** — persists compiled executables to a node-local
   directory (the manifest builder mounts a ``hostPath`` volume, so the
   cache survives pod restarts and is shared between the stable and canary
   pods scheduled on the same TPU host).  Warmup on a warm node then takes
   ~100 ms of cache deserialization instead of tens of seconds of XLA work,
   which keeps time-to-ready — and therefore time-to-100%-traffic, the
   north-star metric — low.

JAX's own defaults are tuned for big training jobs: entries below 1 s of
compile time are not persisted.  Canary models (iris, xgboost, small BERT
buckets) compile faster than that, so we lower both floors to zero —
a cache miss on *any* bucket is a readiness-latency regression here.
"""

from __future__ import annotations

import logging
import os

_log = logging.getLogger("tpumlops.compile_cache")


def enable_persistent_compile_cache(
    cache_dir: str | None,
    *,
    min_compile_time_secs: float = 0.0,
    max_size_bytes: int = 10 * 1024**3,
) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns True when enabled.  ``cache_dir`` falsy → disabled (returns
    False); an unwritable directory logs a warning and disables rather
    than failing server startup — a cold compile is slow, not fatal.
    Must run before the first ``jit`` trace to cover warmup compiles.

    ``max_size_bytes`` caps the directory with JAX's LRU eviction: the
    hostPath volume outlives every pod and cache keys change with each
    model version, so without a cap the node disk would fill with dead
    versions' executables until kubelet disk-pressure evicts the very
    predictors the cache protects.
    """
    import jax

    if not cache_dir:
        # JAX reads JAX_COMPILATION_CACHE_DIR as this option's import-time
        # default; clear it so "disabled" really disables, even when the
        # manifest exported the env var.
        jax.config.update("jax_compilation_cache_dir", None)
        return False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        probe = os.path.join(cache_dir, ".tpumlops-probe")
        with open(probe, "w"):
            pass
        os.remove(probe)
    except OSError as exc:
        _log.warning(
            "compile cache dir %s unusable (%s); continuing without "
            "persistent cache",
            cache_dir,
            exc,
        )
        # The manifest also exports JAX_COMPILATION_CACHE_DIR, which JAX
        # reads as this option's default at import — clear it so "disabled"
        # really means disabled, not "retry cache I/O on every compile".
        jax.config.update("jax_compilation_cache_dir", None)
        return False

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Persist every executable regardless of size/compile time: canary
    # buckets are small and fast to compile but still too slow for a
    # latency-gated readiness window.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs
    )
    jax.config.update("jax_compilation_cache_max_size", max_size_bytes)
    _reset_jax_cache_singleton(jax)
    _log.info("persistent compile cache at %s", cache_dir)
    return True


def _reset_jax_cache_singleton(jax) -> None:
    """Drop jax's latched cache object so the new dir takes effect.

    jax initializes its persistent-cache singleton on the FIRST compile
    and never re-reads ``jax_compilation_cache_dir`` afterwards — if any
    jit ran before this helper (or the helper runs twice with different
    dirs), the config update is silently ignored without this reset."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # private API: absence degrades to the old behavior
        pass


def cache_entry_count(cache_dir: str) -> int:
    """Number of persisted executables (for tests and the warm-start metric)."""
    try:
        return sum(1 for n in os.listdir(cache_dir) if n.endswith("-cache"))
    except OSError:
        return 0
