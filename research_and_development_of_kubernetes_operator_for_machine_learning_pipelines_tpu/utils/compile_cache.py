"""Persistent XLA compilation cache (SURVEY §7 hard part 3).

TPU cold-start is the canary killer: the first request into a freshly
scheduled 10%-traffic predictor triggers a 20–40 s XLA compile, which lands
in the Prometheus latency window and fails the promotion gate before the
model has served a single steady-state request.  The reference never faces
this (its Seldon ``MLFLOW_SERVER`` pods are interpreted CPU Python,
``mlflow_operator.py:198``); a TPU data plane must solve it.

Two layers of defense:

1. **Warmup before readiness** — the server compiles every batch bucket
   before answering the readiness probe (``server/app.py``), so no live
   request ever pays a compile.
2. **This module** — persists compiled executables to a node-local
   directory (the manifest builder mounts a ``hostPath`` volume, so the
   cache survives pod restarts and is shared between the stable and canary
   pods scheduled on the same TPU host).  Warmup on a warm node then takes
   ~100 ms of cache deserialization instead of tens of seconds of XLA work,
   which keeps time-to-ready — and therefore time-to-100%-traffic, the
   north-star metric — low.

JAX's own defaults are tuned for big training jobs: entries below 1 s of
compile time are not persisted.  Canary models (iris, xgboost, small BERT
buckets) compile faster than that, so we lower both floors to zero —
a cache miss on *any* bucket is a readiness-latency regression here.
"""

from __future__ import annotations

import logging
import os
import threading

_log = logging.getLogger("tpumlops.compile_cache")
# One structured line per compilation (see install_compile_listeners).
_compile_log = logging.getLogger("tpumlops.compile")

# Process-wide compile/cache counters, fed by jax's monitoring events
# (install_compile_listeners).  "hits"/"misses" are persistent-cache
# outcomes of compile requests; "persists" counts misses taken while a
# cache dir was active (with our min-entry floors at zero, every such
# miss writes an entry); "compiles" counts backend compilations and
# "compile_seconds" their summed wall.
COUNTERS = {
    "hits": 0, "misses": 0, "persists": 0,
    "compiles": 0, "compile_seconds": 0.0,
}
_counters_lock = threading.Lock()
_listeners_installed = False
_reset_failure_logged = False
_observatory = None  # server.device_telemetry.CompileObservatory | None


def install_compile_listeners(observatory=None) -> None:
    """Hook jax's monitoring stream: persistent-cache hit/miss events and
    backend compile durations feed :data:`COUNTERS`, one structured
    ``tpumlops.compile`` log line fires per compilation, and — when a
    :class:`~..server.device_telemetry.CompileObservatory` is supplied —
    each event is attributed to the engine op that triggered it.

    Idempotent for the listeners (first call wins); the observatory
    reference is refreshed on every call so a server rebuild re-binds."""
    global _listeners_installed, _observatory
    if observatory is not None:
        _observatory = observatory
    if _listeners_installed:
        return
    try:
        from jax._src import monitoring
    except Exception as exc:  # private API moved: counters stay at 0
        _log.warning("jax monitoring unavailable (%s); compile/cache "
                     "counters disabled", exc)
        _listeners_installed = True
        return
    monitoring.register_event_listener(_on_jax_event)
    monitoring.register_event_duration_secs_listener(_on_jax_duration)
    _listeners_installed = True


def detach_observatory(observatory) -> None:
    """Unbind a CompileObservatory (server shutdown): the jax listeners
    stay (they are process-global and cheap) but stop attributing into
    a retired server's observatory — whose metrics hooks would
    otherwise keep incrementing a dead registry and pin the whole
    server object graph for the life of the process."""
    global _observatory
    if _observatory is observatory:
        _observatory = None


def _on_jax_event(name: str, **kwargs) -> None:
    if name == "/jax/compilation_cache/cache_hits":
        kind = "cache_hit"
        with _counters_lock:
            COUNTERS["hits"] += 1
    elif name == "/jax/compilation_cache/cache_misses":
        kind = "cache_miss"
        import jax

        with _counters_lock:
            COUNTERS["misses"] += 1
            if jax.config.jax_compilation_cache_dir:
                COUNTERS["persists"] += 1
    else:
        return
    if _observatory is not None:
        _observatory.on_event(kind)


def _on_jax_duration(name: str, duration: float, **kwargs) -> None:
    if name != "/jax/core/compile/backend_compile_duration":
        return
    with _counters_lock:
        COUNTERS["compiles"] += 1
        COUNTERS["compile_seconds"] += duration
        hits, misses = COUNTERS["hits"], COUNTERS["misses"]
    op = _observatory.current_op() if _observatory is not None else "other"
    _compile_log.info(
        "compiled op=%s wall_ms=%.1f cache_hits=%d cache_misses=%d",
        op, duration * 1000.0, hits, misses,
        extra={"compile_op": op, "compile_wall_s": duration},
    )
    if _observatory is not None:
        _observatory.on_event("compile", duration)


def counters_snapshot() -> dict:
    with _counters_lock:
        return dict(COUNTERS)


def enable_persistent_compile_cache(
    cache_dir: str | None,
    *,
    min_compile_time_secs: float = 0.0,
    max_size_bytes: int = 10 * 1024**3,
) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns True when enabled.  ``cache_dir`` falsy → disabled (returns
    False); an unwritable directory logs a warning and disables rather
    than failing server startup — a cold compile is slow, not fatal.
    Must run before the first ``jit`` trace to cover warmup compiles.

    ``max_size_bytes`` caps the directory with JAX's LRU eviction: the
    hostPath volume outlives every pod and cache keys change with each
    model version, so without a cap the node disk would fill with dead
    versions' executables until kubelet disk-pressure evicts the very
    predictors the cache protects.
    """
    import jax

    # Counters + the per-compile tpumlops.compile log line are a
    # compile-cache feature, not a telemetry-gated one: every server that
    # configures caching (the CLI default) gets them; DeviceTelemetry
    # re-binds its observatory for per-op attribution on top.
    install_compile_listeners()
    if not cache_dir:
        # JAX reads JAX_COMPILATION_CACHE_DIR as this option's import-time
        # default; clear it so "disabled" really disables, even when the
        # manifest exported the env var.
        jax.config.update("jax_compilation_cache_dir", None)
        return False
    try:
        os.makedirs(cache_dir, exist_ok=True)
        probe = os.path.join(cache_dir, ".tpumlops-probe")
        with open(probe, "w"):
            pass
        os.remove(probe)
    except OSError as exc:
        _log.warning(
            "compile cache dir %s unusable (%s); continuing without "
            "persistent cache",
            cache_dir,
            exc,
        )
        # The manifest also exports JAX_COMPILATION_CACHE_DIR, which JAX
        # reads as this option's default at import — clear it so "disabled"
        # really means disabled, not "retry cache I/O on every compile".
        jax.config.update("jax_compilation_cache_dir", None)
        return False

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Persist every executable regardless of size/compile time: canary
    # buckets are small and fast to compile but still too slow for a
    # latency-gated readiness window.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs
    )
    jax.config.update("jax_compilation_cache_max_size", max_size_bytes)
    _reset_jax_cache_singleton(jax)
    _log.info("persistent compile cache at %s", cache_dir)
    return True


def _reset_jax_cache_singleton(jax) -> None:
    """Drop jax's latched cache object so the new dir takes effect.

    jax initializes its persistent-cache singleton on the FIRST compile
    and never re-reads ``jax_compilation_cache_dir`` afterwards — if any
    jit ran before this helper (or the helper runs twice with different
    dirs), the config update is silently ignored without this reset."""
    global _reset_failure_logged
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as exc:  # private API: absence degrades to the old
        # behavior — but say so ONCE, with the directory that will be
        # silently ignored if a jit already ran; the old bare ``pass``
        # made an in-process cache re-point look successful while every
        # compile kept writing to the previous dir.
        if not _reset_failure_logged:
            _reset_failure_logged = True
            _log.warning(
                "could not reset jax's persistent-cache singleton "
                "(%s: %s); if any jit ran before this point, the cache "
                "dir change to %r is silently ignored",
                type(exc).__name__, exc,
                jax.config.jax_compilation_cache_dir,
            )


def cache_entry_count(cache_dir: str) -> int:
    """Number of persisted executables (for tests and the warm-start metric)."""
    try:
        return sum(1 for n in os.listdir(cache_dir) if n.endswith("-cache"))
    except OSError:
        return 0
