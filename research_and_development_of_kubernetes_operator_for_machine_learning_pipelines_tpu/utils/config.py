"""Typed configuration parsed from the ``MlflowModel`` CRD spec.

The reference hardcodes every operating parameter as a constant —
Prometheus URL (``mlflow_operator.py:47``), artifact bucket root
(``:125``), gate thresholds (``:175-179``), canary step/interval/attempts
(``:290-294``) — which SURVEY.md §3.5(5) flags as a rebuild obligation.
Here every one of those constants becomes a spec field with the reference
value as its default, so an unannotated CR behaves exactly like the
reference while everything is tunable per-model.

New TPU-native spec fields (north star): ``backend``, ``tpuTopology``,
``meshShape``, plus server batching knobs.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Mapping

from .journey_trace import SLO_CLASSES

# Reference defaults (file:line cites into /root/reference/mlflow_operator.py)
DEFAULT_MONITORING_INTERVAL_S = 60  # :31
DEFAULT_ARTIFACT_ROOT = "s3://mlflow"  # :125
DEFAULT_PROMETHEUS_URL = (
    "http://seldon-monitoring-prometheus.seldon-monitoring.svc.cluster.local:9090"  # :47
)
DEFAULT_TRAFFIC_STEP = 10  # :291
DEFAULT_STEP_INTERVAL_S = 60  # :292
DEFAULT_MAX_ATTEMPTS = 10  # :293
DEFAULT_ATTEMPT_DELAY_S = 10  # :294
DEFAULT_INITIAL_CANARY_TRAFFIC = 10  # :187
DEFAULT_METRICS_WINDOW_S = 60  # :363 (elapsed_time=60)

# Canonical TPU topology table: CRD tpuTopology value -> placement facts.
# Chip count must equal the mesh device count or the pod's google.com/tpu
# request is unschedulable.  Topologies with hosts > 1 are *multi-host
# slices*: one predictor = ``hosts`` pods forming one JAX process group
# (SURVEY §7 hard part 5); the builder emits the unit wiring and the chips
# request is per-host (``chips_per_host``), not per-slice.


@dataclass(frozen=True)
class TopologyInfo:
    accelerator: str  # GKE nodeSelector cloud.google.com/gke-tpu-accelerator
    gke_topology: str  # GKE nodeSelector cloud.google.com/gke-tpu-topology
    chips: int  # total chips in the slice
    hosts: int = 1  # VMs in the slice (pods per predictor unit)

    @property
    def chips_per_host(self) -> int:
        return self.chips // self.hosts

    # tuple-style indexing kept for the original (accelerator, topology,
    # chips) consumers — exactly 3 elements so legacy 3-way unpacking
    # (`acc, topo, chips = info`) still works; ``hosts`` is attribute-only
    def __getitem__(self, i: int):
        return (self.accelerator, self.gke_topology, self.chips)[i]


# HBM per chip by GKE accelerator name (the operator's capacity-summary
# fact; the data plane measures its own via device.memory_stats()).
TPU_HBM_GIB_PER_CHIP: dict[str, int] = {
    "tpu-v5-lite-podslice": 16,
}

TPU_TOPOLOGIES: dict[str, TopologyInfo] = {
    "v5e-1": TopologyInfo("tpu-v5-lite-podslice", "1x1", 1),
    "v5e-4": TopologyInfo("tpu-v5-lite-podslice", "2x2", 4),
    "v5e-8": TopologyInfo("tpu-v5-lite-podslice", "2x4", 8),
    # multi-host slices: 4-chip VMs (ct5lp-hightpu-4t node shape)
    "v5e-16": TopologyInfo("tpu-v5-lite-podslice", "4x4", 16, hosts=4),
    "v5e-32": TopologyInfo("tpu-v5-lite-podslice", "4x8", 32, hosts=8),
    "v5e-64": TopologyInfo("tpu-v5-lite-podslice", "8x8", 64, hosts=16),
}


@dataclass(frozen=True)
class GateThresholds:
    """Relative regression tolerances for the promotion gate.

    Semantics match ``should_promote_model`` (``mlflow_operator.py:175-179``):
    promote only if new <= old * (1 + threshold) for each metric.

    Hardening extensions beyond the reference (SURVEY §3.5(4)):

    - ``min_sample_count``: both predictors must have served at least this
      many requests in the window before the gate will pass; avoids judging
      on noise.  0 keeps reference behavior (any non-None metric counts).
    - ``error_rate_floor``: absolute error-rate slack.  The reference's
      purely relative check (``:447``) deadlocks when the old model has 0
      errors: a single canary error fails ``new <= 0 * 1.02``.  With a
      floor f, the gate passes if ``new_err <= max(old_err * (1+tol), f)``.
      0.0 keeps reference behavior.
    """

    latency_p95: float = 0.05  # :176
    error_rate: float = 0.02  # :177
    latency_avg: float = 0.05  # :178
    min_sample_count: int = 0
    error_rate_floor: float = 0.0

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "GateThresholds":
        spec = spec or {}
        return cls(
            latency_p95=float(spec.get("latencyP95", spec.get("latency_95th", 0.05))),
            error_rate=float(spec.get("errorRate", 0.02)),
            latency_avg=float(spec.get("latencyAvg", 0.05)),
            min_sample_count=int(spec.get("minSampleCount", 0)),
            error_rate_floor=float(spec.get("errorRateFloor", 0.0)),
        )


@dataclass(frozen=True)
class CanaryPolicy:
    """Traffic-shifting schedule (reference constants at
    ``mlflow_operator.py:290-294``) plus rollback policy.

    ``rollback_on_failure=False`` reproduces the reference, which stops and
    leaves weights frozen after ``max_attempts`` gate failures (the rollback
    is an acknowledged TODO at ``:345``).  True enables the real
    rollback-on-SLO-breach path (north-star requirement).
    """

    step: int = DEFAULT_TRAFFIC_STEP
    step_interval_s: float = DEFAULT_STEP_INTERVAL_S
    max_attempts: int = DEFAULT_MAX_ATTEMPTS
    attempt_delay_s: float = DEFAULT_ATTEMPT_DELAY_S
    initial_traffic: int = DEFAULT_INITIAL_CANARY_TRAFFIC
    metrics_window_s: int = DEFAULT_METRICS_WINDOW_S
    rollback_on_failure: bool = False
    warmup_requests: int = 0  # synthetic warm-up traffic per predictor (0 = off)

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "CanaryPolicy":
        spec = spec or {}
        return cls(
            step=int(spec.get("step", DEFAULT_TRAFFIC_STEP)),
            step_interval_s=float(spec.get("stepInterval", DEFAULT_STEP_INTERVAL_S)),
            max_attempts=int(spec.get("maxAttempts", DEFAULT_MAX_ATTEMPTS)),
            attempt_delay_s=float(spec.get("attemptDelay", DEFAULT_ATTEMPT_DELAY_S)),
            initial_traffic=int(spec.get("initialTraffic", DEFAULT_INITIAL_CANARY_TRAFFIC)),
            metrics_window_s=int(spec.get("metricsWindow", DEFAULT_METRICS_WINDOW_S)),
            rollback_on_failure=bool(spec.get("rollbackOnFailure", False)),
            warmup_requests=int(spec.get("warmupRequests", 0)),
        )

    def __post_init__(self):
        if not (0 < self.step <= 100):
            raise ValueError(f"canary step must be in (0, 100], got {self.step}")
        if not (0 < self.initial_traffic <= 100):
            raise ValueError(
                f"initialTraffic must be in (0, 100], got {self.initial_traffic}"
            )
        if self.max_attempts < 1:
            raise ValueError("maxAttempts must be >= 1")


def _reject_unknown_keys(
    spec: Mapping[str, Any], allowed: frozenset, path: str
) -> None:
    """Fail loudly on unknown spec keys at reconcile time.

    The CRD schema is permissive about extra properties, so a typo'd
    knob (``draftToken`` for ``draftTokens``) used to be SILENTLY
    ignored — the CR applied cleanly and served with the default, the
    worst failure mode for a performance knob.  Rejecting here lands the
    error in CR status (and in the server log at startup), naming both
    the bad key and the accepted set."""
    unknown = sorted(set(spec) - allowed)
    if unknown:
        raise ValueError(
            f"unknown key(s) {unknown} in {path}; "
            f"allowed: {sorted(allowed)}"
        )


def _parse_prefill_batch(value) -> int:
    """``spec.tpu.prefillBatch``: concurrent admissions whose next prompt
    chunks batch into ONE prefill call per engine tick (1 = today's
    one-at-a-time pipeline, byte-for-byte)."""
    batch = int(value) if value is not None else 1
    if batch < 1:
        raise ValueError(
            f"spec.tpu.prefillBatch must be >= 1, got {value!r}"
        )
    return batch


def _parse_prefill_token_budget(value) -> int:
    """``spec.tpu.prefillTokenBudget``: Sarathi-style cap on prompt tokens
    prefilled per engine tick (0 = uncapped); bounds the decode-cadence
    jitter a burst of long prompts can inject."""
    budget = int(value) if value is not None else 0
    if budget < 0:
        raise ValueError(
            f"spec.tpu.prefillTokenBudget must be >= 0, got {value!r}"
        )
    return budget


def _parse_sp_prefill_threshold(value) -> int:
    """``spec.tpu.spPrefillThreshold``: minimum cold-prompt length (in
    tokens) that routes through sequence-parallel ring-attention prefill
    when meshShape carries sp > 1.  Ignored at sp == 1."""
    threshold = int(value) if value is not None else 1024
    if threshold < 1:
        raise ValueError(
            f"spec.tpu.spPrefillThreshold must be >= 1, got {value!r}"
        )
    return threshold


def _parse_prefill_chunk(value) -> int | None:
    """Positivity is checkable here; divisibility into the model's KV
    capacity is not (max_seq lives in the artifact, not the CR) — that
    check runs at server startup, where a violation fails readiness with
    a clear error in the pod log."""
    if not value:
        return None
    chunk = int(value)
    if chunk <= 0:
        raise ValueError(f"spec.tpu.prefillChunk must be positive, got {value!r}")
    return chunk


def _parse_decode_steps(value) -> int:
    """``spec.tpu.decodeSteps``: decode iterations fused into ONE device
    dispatch per engine tick (a ``lax.scan`` with on-device sampling and
    an EOS latch, paired with lag-1 async token readback).  1 — the
    default — is the single-step tick loop byte-for-byte.  Capped at 16:
    over-run work past EOS/budget is bounded by K, and host token
    cadence (SSE flushes, cancellation latency) coarsens with K — past
    16 the dispatch amortization has long since saturated.

    ``decodeSteps`` > 1 combined with ``speculative.enabled`` is NOT an
    error: ticks holding draft proposals run verify (acceptance beats a
    fixed-K scan on draftable text) and draft-less ticks fuse — a
    documented per-slot fallback, not a contradiction."""
    steps = int(value) if value is not None else 1
    if not (1 <= steps <= 16):
        raise ValueError(
            f"spec.tpu.decodeSteps must be in [1, 16], got {value!r}"
        )
    return steps


def _parse_admission_budget(value) -> int:
    """``spec.tpu.admissionQueueBudget``: estimated-token bound on
    queued-but-unadmitted generation work (0 = unbounded, the old
    behavior byte-for-byte); beyond it the server sheds with 429."""
    budget = int(value) if value is not None else 0
    if budget < 0:
        raise ValueError(
            f"spec.tpu.admissionQueueBudget must be >= 0, got {value!r}"
        )
    return budget


def _parse_drain_grace(value) -> float:
    """``spec.tpu.drainGraceSeconds``: in-flight completion bound of the
    lossless drain protocol (SIGTERM / POST /admin/drain).

    Default 20: with the 3s endpoint-removal lag it fits inside
    Kubernetes' DEFAULT 30s terminationGracePeriodSeconds with margin —
    a default-config drain must never be SIGKILLed mid-flight.  Larger
    values make the builder emit a matching pod grace override."""
    grace = float(value) if value is not None else 20.0
    if grace < 0:
        raise ValueError(
            f"spec.tpu.drainGraceSeconds must be >= 0, got {value!r}"
        )
    return grace


@dataclass(frozen=True)
class PrefixCacheSpec:
    """``spec.tpu.prefixCache``: radix-tree prompt-prefix KV reuse.

    ``chunk_tokens`` is the reuse unit and must equal ``prefillChunk``
    when both are set (the server rejects a mismatch at startup); when
    ``prefillChunk`` is unset, enabling the cache turns on chunked
    prefill at ``chunk_tokens``.  Disabled by default: an unannotated CR
    behaves exactly as before.
    """

    enabled: bool = False
    budget_mb: int = 256
    chunk_tokens: int = 64
    # Second-tier host-RAM pool: chunks the first tier evicts spill here
    # (LRU under this budget) and promote back on a radix-walk miss.
    # 0 — the default — is the single-tier behavior byte-for-byte.
    l2_budget_mb: int = 0

    @classmethod
    def from_spec(
        cls,
        spec: Mapping[str, Any] | None,
        prefill_chunk: int | None = None,
    ) -> "PrefixCacheSpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec,
            frozenset({"enabled", "budgetMB", "chunkTokens", "l2BudgetMB"}),
            "spec.tpu.prefixCache",
        )
        enabled = bool(spec.get("enabled", False))
        # Unset chunkTokens follows prefillChunk (the common case: one
        # knob already set); an EXPLICIT mismatch is rejected HERE, at
        # reconcile time, so it lands in CR status — not as a server
        # CrashLoopBackOff from GenerationEngine's own guard.
        chunk_tokens = spec.get("chunkTokens")
        if chunk_tokens is None:
            chunk_tokens = prefill_chunk or 64
        chunk_tokens = int(chunk_tokens)
        if (
            enabled
            and prefill_chunk is not None
            and chunk_tokens != prefill_chunk
        ):
            raise ValueError(
                f"prefixCache.chunkTokens {chunk_tokens} must equal "
                f"prefillChunk {prefill_chunk} (the prefill chunk is the "
                "prefix reuse unit); omit chunkTokens to follow prefillChunk"
            )
        return cls(
            enabled=enabled,
            budget_mb=int(spec.get("budgetMB", 256)),
            chunk_tokens=chunk_tokens,
            l2_budget_mb=int(spec.get("l2BudgetMB", 0)),
        )

    def __post_init__(self):
        if self.enabled:
            # Reject at reconcile time, not as a pod CrashLoopBackOff.
            if self.budget_mb < 1:
                raise ValueError(
                    f"prefixCache.budgetMB must be >= 1, got {self.budget_mb}"
                )
            if self.chunk_tokens < 1:
                raise ValueError(
                    "prefixCache.chunkTokens must be >= 1, got "
                    f"{self.chunk_tokens}"
                )
            if self.l2_budget_mb < 0:
                raise ValueError(
                    "prefixCache.l2BudgetMB must be >= 0, got "
                    f"{self.l2_budget_mb}"
                )


@dataclass(frozen=True)
class SpeculativeSpec:
    """``spec.tpu.speculative``: self-speculative n-gram decoding.

    A host-side "prompt lookup" drafter proposes up to ``draft_tokens``
    continuations per slot from the sequence's own history (no draft
    model), and ONE batched verify forward scores all of them — tokens
    emitted per HBM weight stream multiply by the acceptance length
    while output stays bit-identical to plain greedy decode (exact
    argmax acceptance).  Disabled by default: an unannotated CR behaves
    exactly as before.  Greedy traffic only — a tick with any sampling
    slot falls back to the single-token step.
    """

    enabled: bool = False
    draft_tokens: int = 4
    ngram_min: int = 1
    ngram_max: int = 4
    adaptive: bool = True

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "SpeculativeSpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec,
            frozenset(
                {"enabled", "draftTokens", "ngramMin", "ngramMax", "adaptive"}
            ),
            "spec.tpu.speculative",
        )
        return cls(
            enabled=bool(spec.get("enabled", False)),
            draft_tokens=int(spec.get("draftTokens", 4)),
            ngram_min=int(spec.get("ngramMin", 1)),
            ngram_max=int(spec.get("ngramMax", 4)),
            adaptive=bool(spec.get("adaptive", True)),
        )

    def __post_init__(self):
        if self.enabled:
            # Reject at reconcile time, not as a pod CrashLoopBackOff.
            if not (1 <= self.draft_tokens <= 64):
                raise ValueError(
                    "speculative.draftTokens must be in [1, 64], got "
                    f"{self.draft_tokens}"
                )
            if not (1 <= self.ngram_min <= self.ngram_max):
                raise ValueError(
                    "speculative ngram bounds must satisfy 1 <= ngramMin "
                    f"<= ngramMax, got [{self.ngram_min}, {self.ngram_max}]"
                )


@dataclass(frozen=True)
class SnapshotSpec:
    """``spec.tpu.snapshot``: pre-baked weight snapshots (scale-to-zero
    fast restore, ``server/snapshot.py``).

    When enabled, the server bakes the post-shard, post-quantize device
    tree into ``dir`` after its first successful cold load and restores
    from it on every later boot/attach with zero transform work; the
    snapshot is invalidated by a content hash of (model version/URI,
    quantize mode, mesh shape).  Required for ``autoscaling.minReplicas:
    0`` — without a restorable snapshot a woken CR would pay the full
    cold path while a request is parked.  Disabled by default: an
    unannotated CR's manifest and load path stay byte-for-byte.
    """

    enabled: bool = False
    dir: str = "/var/cache/tpumlops/snapshots"

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "SnapshotSpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec, frozenset({"enabled", "dir"}), "spec.tpu.snapshot"
        )
        return cls(
            enabled=bool(spec.get("enabled", False)),
            dir=str(spec.get("dir", "/var/cache/tpumlops/snapshots")),
        )

    def __post_init__(self):
        if self.enabled and not self.dir:
            # Reject at reconcile time, not as a pod CrashLoopBackOff.
            raise ValueError(
                "snapshot.enabled requires a non-empty snapshot.dir"
            )


@dataclass(frozen=True)
class ObservabilitySpec:
    """``spec.tpu.observability``: engine flight-recorder sizing and the
    device telemetry layer.

    ``trace_ring`` is the bounded in-memory journal's capacity (one ring
    each for engine ticks, request lifecycle events, and completed
    request traces; served at ``/debug/engine`` and ``/debug/trace``).
    0 — the default — creates no recorder at all, so the engine loop
    stays byte-for-byte unobserved.

    ``device_telemetry`` turns on the HBM ledger + compile observatory +
    per-tick MFU/bandwidth accounting (``server/device_telemetry.py``:
    ``GET /debug/device``, ``tpumlops_device_*`` /
    ``tpumlops_compile_*`` series, utilization fields on recorder
    ticks, and a ``status.capacity`` summary on the CR).  False — the
    default — constructs none of it: ticks, metric families, status
    patches, and ``/debug/*`` payloads stay byte-for-byte.

    ``timeseries_ring`` sizes the per-second serving time-series ring
    (``server/timeseries.py``: per-tick-kind wall quantiles, ITL, queue
    depth, MFU/HBM-bandwidth, shed/poison counts, served at
    ``GET /debug/timeseries`` — the anomaly detector's input plane).
    0 — the default — constructs no ring: callbacks, routes, and
    payloads stay byte-for-byte.
    """

    trace_ring: int = 0
    device_telemetry: bool = False
    timeseries_ring: int = 0

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "ObservabilitySpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec,
            frozenset({"traceRing", "deviceTelemetry", "timeseriesRing"}),
            "spec.tpu.observability",
        )
        return cls(
            trace_ring=int(spec.get("traceRing", 0)),
            device_telemetry=bool(spec.get("deviceTelemetry", False)),
            timeseries_ring=int(spec.get("timeseriesRing", 0)),
        )

    def __post_init__(self):
        if self.trace_ring < 0:
            # Reject at reconcile time, not as a pod CrashLoopBackOff.
            raise ValueError(
                "observability.traceRing must be >= 0, got "
                f"{self.trace_ring}"
            )
        # One day of 1 s samples is already ~86 KB of JSON per replica
        # per fleet-overview scrape; anything larger is a typo, not a
        # window.
        if not (0 <= self.timeseries_ring <= 86400):
            raise ValueError(
                "observability.timeseriesRing must be in [0, 86400], got "
                f"{self.timeseries_ring}"
            )


@dataclass(frozen=True)
class AutoscalingSpec:
    """``spec.autoscaling``: SLO-driven horizontal replica scaling.

    The autoscaler (``operator/autoscaler.py``) reads the stable
    predictor's engine saturation signals — queue depth, admission wait,
    TTFT p95 — from the CR's Prometheus and sizes ``replicas`` between
    ``min_replicas`` and ``max_replicas``:

    - ``target_queue_depth_per_replica``: desired replicas =
      ceil(total queue depth / target) — the primary saturation signal;
    - ``target_ttft_seconds``: a TTFT p95 above this adds one replica
      even when the queue target is met (latency pressure without a
      visible backlog, e.g. long prompts);
    - asymmetric hysteresis: scale-up jumps straight to the desired
      count once the demand has persisted ``scale_up_stabilization_s``
      (0 = immediately); scale-down steps ONE replica at a time and only
      after ``scale_down_cooldown_s`` since the last scale event in
      either direction;
    - ``min_replicas: 0`` is serverless scale-to-zero: an idle CR's
      Deployment parks at zero replicas (requires
      ``spec.tpu.snapshot.enabled`` so the wake restore is fast, and is
      rejected on multi-host topologies), the router parks incoming
      requests, and a parked/queued request wakes the CR immediately —
      no stabilization window, a waiting user has already paid it;
    - ``warm_pool_size`` reserves that many ``--warm-pool`` replicas
      (booted, compile-swept, weightless) the wake path can attach a
      snapshot to instead of booting a pod from scratch.

    Disabled (the default) keeps manifests, status patches, and engine
    admission behavior byte-for-byte what they were.
    """

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 1
    target_queue_depth_per_replica: float = 0.0  # <= 0: signal unused
    target_ttft_seconds: float = 0.0  # <= 0: signal unused
    scale_up_stabilization_s: float = 0.0
    scale_down_cooldown_s: float = 300.0
    warm_pool_size: int = 0  # 0 = no warm pool

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "AutoscalingSpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec,
            frozenset(
                {
                    "enabled", "minReplicas", "maxReplicas",
                    "targetQueueDepthPerReplica", "targetTTFTSeconds",
                    "scaleUpStabilizationSeconds",
                    "scaleDownCooldownSeconds",
                    "warmPoolSize",
                }
            ),
            "spec.autoscaling",
        )
        return cls(
            enabled=bool(spec.get("enabled", False)),
            min_replicas=int(spec.get("minReplicas", 1)),
            max_replicas=int(spec.get("maxReplicas", 1)),
            target_queue_depth_per_replica=float(
                spec.get("targetQueueDepthPerReplica", 0.0)
            ),
            target_ttft_seconds=float(spec.get("targetTTFTSeconds", 0.0)),
            scale_up_stabilization_s=float(
                spec.get("scaleUpStabilizationSeconds", 0.0)
            ),
            scale_down_cooldown_s=float(
                spec.get("scaleDownCooldownSeconds", 300.0)
            ),
            warm_pool_size=int(spec.get("warmPoolSize", 0)),
        )

    def __post_init__(self):
        # Contradictory specs are rejected at reconcile time so they land
        # in CR status, not as an autoscaler oscillating or parked.
        if self.min_replicas < 0:
            raise ValueError(
                f"autoscaling.minReplicas must be >= 0 (0 = serverless "
                f"scale-to-zero), got {self.min_replicas}"
            )
        if self.max_replicas < 1:
            raise ValueError(
                f"autoscaling.maxReplicas must be >= 1, got "
                f"{self.max_replicas}"
            )
        if not (0 <= self.warm_pool_size <= 16):
            raise ValueError(
                f"autoscaling.warmPoolSize must be in [0, 16], got "
                f"{self.warm_pool_size}"
            )
        if self.min_replicas > self.max_replicas:
            raise ValueError(
                f"autoscaling.minReplicas {self.min_replicas} > "
                f"maxReplicas {self.max_replicas}"
            )
        if self.scale_up_stabilization_s < 0:
            raise ValueError(
                "autoscaling.scaleUpStabilizationSeconds must be >= 0, "
                f"got {self.scale_up_stabilization_s}"
            )
        if self.scale_down_cooldown_s < 0:
            raise ValueError(
                "autoscaling.scaleDownCooldownSeconds must be >= 0, got "
                f"{self.scale_down_cooldown_s}"
            )
        if (
            self.enabled
            and self.target_queue_depth_per_replica <= 0
            and self.target_ttft_seconds <= 0
        ):
            raise ValueError(
                "autoscaling.enabled requires a scaling target: set "
                "targetQueueDepthPerReplica > 0 and/or "
                "targetTTFTSeconds > 0"
            )
        if (
            self.enabled
            and self.min_replicas == 0
            and self.target_queue_depth_per_replica <= 0
        ):
            # The wake signal for a CR at zero is backlog (router-parked
            # + queued requests); a TTFT-only config samples nothing at
            # zero traffic and could never wake.
            raise ValueError(
                "autoscaling.minReplicas: 0 requires "
                "targetQueueDepthPerReplica > 0 (parked/queued backlog "
                "is the wake signal; TTFT alone cannot wake a CR at "
                "zero)"
            )


@dataclass(frozen=True)
class PrefixAffinitySpec:
    """``spec.fleet.prefixAffinity``: route repeat prefixes to the decode
    replica already holding their KV.

    The router hashes the first ``tokens`` prompt ids onto a consistent-
    hash ring over decode-role backends, so a shared template prefix
    lands on the same replica every time — cache hit rate survives
    scale-out instead of diluting 1/N per replica."""

    enabled: bool = True
    tokens: int = 64  # leading prompt ids hashed onto the decode ring

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "PrefixAffinitySpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec, frozenset({"enabled", "tokens"}), "spec.fleet.prefixAffinity"
        )
        return cls(
            enabled=bool(spec.get("enabled", True)),
            tokens=int(spec.get("tokens", 64)),
        )

    def __post_init__(self):
        if self.enabled and not (1 <= self.tokens <= 4096):
            raise ValueError(
                f"fleet.prefixAffinity.tokens must be in [1, 4096], got "
                f"{self.tokens}"
            )


@dataclass(frozen=True)
class KvTransferSpec:
    """``spec.fleet.kvTransfer``: the prefill→decode KV handoff relay.

    ``retries`` is the number of ADDITIONAL prefill replicas the router
    tries after the first export fails (total export attempts =
    1 + retries) before falling back to unified serving — the decode
    replica prefills locally: slower, never lost."""

    enabled: bool = True
    retries: int = 1

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "KvTransferSpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec, frozenset({"enabled", "retries"}), "spec.fleet.kvTransfer"
        )
        return cls(
            enabled=bool(spec.get("enabled", True)),
            retries=int(spec.get("retries", 1)),
        )

    def __post_init__(self):
        if not (0 <= self.retries <= 8):
            raise ValueError(
                f"fleet.kvTransfer.retries must be in [0, 8], got "
                f"{self.retries}"
            )


@dataclass(frozen=True)
class FleetObservabilitySpec:
    """``spec.fleet.observability``: the router's fleet trace plane.

    ``journey_ring`` sizes the router's bounded per-request
    JourneyRecord ring (``--journey-ring`` via the
    ``tpumlops.dev/fleet-journey-ring`` manifest annotation and
    RouterSync).  With the ring on, the router adopts-or-mints
    ``X-Request-Id`` + W3C ``traceparent`` on every inbound request,
    propagates them on every outbound leg (forwards, KV relay legs,
    failover retries, park releases), echoes the id on every response,
    and serves the ring at ``/router/debug/requests`` +
    ``/router/debug/trace``.  0 — the default — keeps the router
    byte-for-byte: no header minting, no new metric families, 404 on
    the debug endpoints."""

    journey_ring: int = 0

    @classmethod
    def from_spec(
        cls, spec: Mapping[str, Any] | None
    ) -> "FleetObservabilitySpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec, frozenset({"journeyRing"}), "spec.fleet.observability"
        )
        return cls(journey_ring=int(spec.get("journeyRing", 0)))

    def __post_init__(self):
        # The router serializes the whole ring per debug scrape on its
        # single-threaded event loop; the cap bounds that stall.
        if not (0 <= self.journey_ring <= 1 << 16):
            raise ValueError(
                "fleet.observability.journeyRing must be in "
                f"[0, {1 << 16}], got {self.journey_ring}"
            )


@dataclass(frozen=True)
class FleetSpec:
    """``spec.fleet``: disaggregated prefill/decode replica pools.

    ``disaggregation: true`` splits the predictor into two pools — a
    prefill-heavy one that computes prompt K/V and a decode-heavy one
    that streams tokens — connected by the KV handoff relay
    (``server/kv_transfer.py``) and fronted by the prefix-affinity
    router.  Per-pool ``min``/``max`` bounds let the autoscaler size
    each pool on its own signal (prefill: admission wait; decode:
    queue depth / ITL) instead of one count serving two workloads.

    Disabled (the default) keeps manifests, router behavior, and engine
    ticks byte-for-byte what they were.
    """

    disaggregation: bool = False
    prefill_replicas: int = 1
    decode_replicas: int = 2
    prefill_min_replicas: int = 1
    prefill_max_replicas: int = 1
    decode_min_replicas: int = 1
    decode_max_replicas: int = 1
    # Prefill pool's own scaling signal (0 = pool fixed at its count):
    # admission wait p95 above this adds a prefill replica.
    prefill_target_admission_wait_ms: float = 0.0
    prefix_affinity: PrefixAffinitySpec = field(
        default_factory=PrefixAffinitySpec
    )
    kv_transfer: KvTransferSpec = field(default_factory=KvTransferSpec)
    # Router trace plane: valid WITHOUT disaggregation (a plain canary
    # router benefits from request journeys just as much as a fleet).
    observability: FleetObservabilitySpec = field(
        default_factory=FleetObservabilitySpec
    )

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "FleetSpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec,
            frozenset(
                {
                    "disaggregation", "prefillReplicas", "decodeReplicas",
                    "prefillMinReplicas", "prefillMaxReplicas",
                    "decodeMinReplicas", "decodeMaxReplicas",
                    "prefillTargetAdmissionWaitMs",
                    "prefixAffinity", "kvTransfer", "observability",
                }
            ),
            "spec.fleet",
        )
        disagg = bool(spec.get("disaggregation", False))
        prefill = int(spec.get("prefillReplicas", 1 if disagg else 0))
        decode = int(spec.get("decodeReplicas", 2 if disagg else 0))
        if not disagg:
            # A pool size without the mode is a contradiction the CR
            # author must resolve — silently ignoring it would leave
            # them believing a prefill pool exists.
            for key in (
                "prefillReplicas", "decodeReplicas", "prefillMinReplicas",
                "prefillMaxReplicas", "decodeMinReplicas",
                "decodeMaxReplicas",
            ):
                if spec.get(key) is not None:
                    raise ValueError(
                        f"fleet.{key} requires fleet.disaggregation: true"
                    )
        return cls(
            disaggregation=disagg,
            prefill_replicas=prefill,
            decode_replicas=decode,
            prefill_min_replicas=int(
                spec.get("prefillMinReplicas", min(1, prefill))
            ),
            prefill_max_replicas=int(
                spec.get("prefillMaxReplicas", prefill)
            ),
            decode_min_replicas=int(
                spec.get("decodeMinReplicas", min(1, decode))
            ),
            decode_max_replicas=int(spec.get("decodeMaxReplicas", decode)),
            prefill_target_admission_wait_ms=float(
                spec.get("prefillTargetAdmissionWaitMs", 0.0)
            ),
            prefix_affinity=PrefixAffinitySpec.from_spec(
                spec.get("prefixAffinity")
            ),
            kv_transfer=KvTransferSpec.from_spec(spec.get("kvTransfer")),
            observability=FleetObservabilitySpec.from_spec(
                spec.get("observability")
            ),
        )

    def __post_init__(self):
        if not self.disaggregation:
            return
        # Reject contradictions at reconcile time so they land in CR
        # status, not as an empty pool serving 503s.
        if self.prefill_replicas < 1:
            raise ValueError(
                "fleet.disaggregation requires prefillReplicas >= 1, got "
                f"{self.prefill_replicas}"
            )
        if self.decode_replicas < 1:
            raise ValueError(
                "fleet.disaggregation requires decodeReplicas >= 1, got "
                f"{self.decode_replicas}"
            )
        for label, lo, hi, count in (
            (
                "prefill", self.prefill_min_replicas,
                self.prefill_max_replicas, self.prefill_replicas,
            ),
            (
                "decode", self.decode_min_replicas,
                self.decode_max_replicas, self.decode_replicas,
            ),
        ):
            if lo < 0:
                raise ValueError(
                    f"fleet.{label}MinReplicas must be >= 0, got {lo}"
                )
            if hi < 1:
                raise ValueError(
                    f"fleet.{label}MaxReplicas must be >= 1, got {hi}"
                )
            if lo > hi:
                raise ValueError(
                    f"fleet.{label}MinReplicas {lo} > {label}MaxReplicas "
                    f"{hi}"
                )
            if not (lo <= count <= hi):
                raise ValueError(
                    f"fleet.{label}Replicas {count} outside "
                    f"[{label}MinReplicas {lo}, {label}MaxReplicas {hi}]"
                )
        if self.prefill_target_admission_wait_ms < 0:
            raise ValueError(
                "fleet.prefillTargetAdmissionWaitMs must be >= 0, got "
                f"{self.prefill_target_admission_wait_ms}"
            )


@dataclass(frozen=True)
class RolloutObservability:
    """``spec.observability``: rollout decision-journal surfacing on the CR.

    ``history_limit`` bounds ``status.history`` — the per-CR journal of
    gate evaluations and phase transitions the reconciler appends so
    ``kubectl get -o yaml`` alone explains a stalled canary.  0 — the
    default — writes neither ``status.history`` nor ``status.lastGate``,
    keeping status patches byte-for-byte what they were.  The cap of 64
    exists because status lives in etcd (~1.5 MB object limit): a full
    gate record with two raw metric readings is ~1 KB.
    """

    history_limit: int = 0

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "RolloutObservability":
        spec = spec or {}
        _reject_unknown_keys(
            spec, frozenset({"historyLimit"}), "spec.observability"
        )
        return cls(history_limit=int(spec.get("historyLimit", 0)))

    def __post_init__(self):
        if not (0 <= self.history_limit <= 64):
            # Reject at reconcile time so it lands in CR status.
            raise ValueError(
                "observability.historyLimit must be in [0, 64], got "
                f"{self.history_limit}"
            )


@dataclass(frozen=True)
class SloSpec:
    """``spec.slo``: serving objectives the operator accounts against.

    Each configured target becomes one SLO the operator evaluates per
    reconcile step from the metrics it already scrapes — TTFT p99 and
    ITL p99 from the engine series, availability from the router's
    gate histograms — over a rolling ``window_minutes`` window:

    - attainment: fraction of in-window samples meeting the target;
    - burn rate: (1 − attainment) / (1 − objective), where the shared
      objective is ``availability_pct`` (burn 1.0 = consuming the error
      budget exactly as fast as the objective allows);
    - error budget remaining: max(0, 1 − burn rate).

    Exported as ``tpumlops_operator_slo_{attainment,
    error_budget_remaining,burn_rate}{slo=...}`` and journaled as
    ``SloRecord``s beside gate/scale records when budget state changes.
    Absent (the default) — no tracker, no series, no status writes:
    byte-for-byte.
    """

    enabled: bool = False
    ttft_p99_ms: float = 0.0  # 0 = latency target not tracked
    itl_p99_ms: float = 0.0   # 0 = not tracked
    availability_pct: float = 99.0  # the objective percent (all SLOs)
    window_minutes: float = 60.0

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "SloSpec":
        if spec is None:
            return cls()
        _reject_unknown_keys(
            spec,
            frozenset(
                {
                    "ttftP99Ms", "itlP99Ms", "availabilityPct",
                    "windowMinutes",
                }
            ),
            "spec.slo",
        )
        return cls(
            enabled=True,
            ttft_p99_ms=float(spec.get("ttftP99Ms", 0.0)),
            itl_p99_ms=float(spec.get("itlP99Ms", 0.0)),
            availability_pct=float(spec.get("availabilityPct", 99.0)),
            window_minutes=float(spec.get("windowMinutes", 60.0)),
        )

    def __post_init__(self):
        if not self.enabled:
            return
        if self.ttft_p99_ms < 0 or self.itl_p99_ms < 0:
            raise ValueError(
                "slo.ttftP99Ms / slo.itlP99Ms must be >= 0, got "
                f"{self.ttft_p99_ms} / {self.itl_p99_ms}"
            )
        if not (50.0 <= self.availability_pct < 100.0):
            # 100% leaves a zero error budget (division by zero in the
            # burn rate) and below 50% is a typo, not an objective.
            raise ValueError(
                "slo.availabilityPct must be in [50, 100), got "
                f"{self.availability_pct}"
            )
        if not (1.0 <= self.window_minutes <= 1440.0):
            raise ValueError(
                "slo.windowMinutes must be in [1, 1440], got "
                f"{self.window_minutes}"
            )

    @property
    def slo_names(self) -> tuple:
        """The SLOs this spec tracks, in evaluation order (values of the
        ``slo`` metric label and ``SloRecord.slo``)."""
        names = []
        if self.ttft_p99_ms > 0:
            names.append("ttft_p99")
        if self.itl_p99_ms > 0:
            names.append("itl_p99")
        names.append("availability")  # always tracked when enabled
        return tuple(names)


@dataclass(frozen=True)
class AnomalySpec:
    """``spec.anomaly``: the fleet anomaly detector (operator/anomaly.py).

    Present (any value, even ``{}``) arms a per-reconcile detection pass
    over the fleet's time-series ring snapshots: robust peer comparison
    (median/MAD z-score of each replica's ITL / MFU / queue slope
    against the other replicas of the same pool → straggler verdicts)
    plus self-baseline drift (the current window vs the post-warmup /
    post-attach baseline window).  Verdicts are journaled as
    ``AnomalyRecord``s, published at ``status.anomalies``, exported as
    ``tpumlops_operator_anomaly_{active,events_total}``, and fed into
    the multiplexer's eviction scoring and the autoscaler's scale-down
    victim choice.  Requires ``spec.tpu.observability.timeseriesRing``
    > 0 (the rings ARE the input plane).  Absent (the default) — no
    detector, no series, no status writes, identical mux/autoscaler
    decisions: byte-for-byte.
    """

    enabled: bool = False
    mad_threshold: float = 3.5  # |robust z| beyond which a peer straggles
    drift_pct: float = 25.0  # self-baseline drift trigger (0 = off)
    min_peers: int = 3  # below this: no peer verdicts at all
    window_s: int = 30  # trailing comparison window (ring seconds)
    baseline_s: int = 30  # baseline window (post-warmup/attach seconds)

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "AnomalySpec":
        if spec is None:
            return cls()
        _reject_unknown_keys(
            spec,
            frozenset(
                {
                    "madThreshold", "driftPct", "minPeers", "windowSeconds",
                    "baselineSeconds",
                }
            ),
            "spec.anomaly",
        )
        return cls(
            enabled=True,
            mad_threshold=float(spec.get("madThreshold", 3.5)),
            drift_pct=float(spec.get("driftPct", 25.0)),
            min_peers=int(spec.get("minPeers", 3)),
            window_s=int(spec.get("windowSeconds", 30)),
            baseline_s=int(spec.get("baselineSeconds", 30)),
        )

    def __post_init__(self):
        if not self.enabled:
            return
        if self.mad_threshold <= 0:
            raise ValueError(
                "anomaly.madThreshold must be > 0, got "
                f"{self.mad_threshold}"
            )
        if self.drift_pct < 0:
            raise ValueError(
                f"anomaly.driftPct must be >= 0 (0 disables drift "
                f"detection), got {self.drift_pct}"
            )
        if self.min_peers < 3:
            # Median/MAD of two peers is degenerate (MAD of a pair is
            # half their spread; every pair member is its own outlier) —
            # the detector hard-refuses verdicts below 3, so a smaller
            # spec value is a contradiction, not a tuning choice.
            raise ValueError(
                f"anomaly.minPeers must be >= 3, got {self.min_peers}"
            )
        if not (5 <= self.window_s <= 3600):
            raise ValueError(
                f"anomaly.windowSeconds must be in [5, 3600], got "
                f"{self.window_s}"
            )
        if not (5 <= self.baseline_s <= 3600):
            raise ValueError(
                f"anomaly.baselineSeconds must be in [5, 3600], got "
                f"{self.baseline_s}"
            )


# Objective keys the offline planner (operator/planner.py) can search
# against.  Unknown keys reject HERE (a typo'd objective must land in CR
# status); an objective the knob space cannot meet rejects in the planner
# as a typed InfeasibleObjectiveError.
PLANNER_OBJECTIVE_KEYS = frozenset({"ttftP99Ms"})


@dataclass(frozen=True)
class PlannerSpec:
    """``spec.planner``: the offline SLO planner (operator/planner.py).

    The planner replays a journey-ring trace (``/router/debug/requests``
    export: ``tracePath`` to a file, or ``trace`` inline) through an
    analytic cost model and searches the knob space — decodeSteps,
    speculative, prefillBatch/prefillTokenBudget, quantize, cache slots,
    meshShape chips-vs-replicas — for the cheapest configuration
    (chip-seconds) meeting ``objective``.  ``applyMode: suggest`` (the
    default) writes the costed plan to ``status.plan`` and nothing else
    — manifests stay byte-for-byte; ``apply`` also rebuilds the data
    plane with the chosen knobs.  Disabled (the default) — no plan, no
    status writes: byte-for-byte.
    """

    enabled: bool = False
    apply_mode: str = "suggest"  # suggest | apply
    objective: Mapping[str, float] = field(default_factory=dict)
    trace_path: str | None = None
    trace: Mapping[str, Any] | None = None
    # Optional model-profile overrides for the analytic cost model
    # (layers/hidden/heads/...); absent fields take the planner's
    # 7B-class defaults.
    model: Mapping[str, Any] | None = None

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "PlannerSpec":
        if spec is None:
            return cls()
        _reject_unknown_keys(
            spec,
            frozenset(
                {"enabled", "applyMode", "objective", "tracePath",
                 "trace", "model"}
            ),
            "spec.planner",
        )
        objective = dict(spec.get("objective") or {})
        _reject_unknown_keys(
            objective, PLANNER_OBJECTIVE_KEYS, "spec.planner.objective"
        )
        return cls(
            enabled=bool(spec.get("enabled", False)),
            apply_mode=str(spec.get("applyMode", "suggest")),
            objective={k: float(v) for k, v in objective.items()},
            trace_path=(
                str(spec["tracePath"])
                if spec.get("tracePath") is not None
                else None
            ),
            trace=spec.get("trace"),
            model=spec.get("model"),
        )

    def __post_init__(self):
        if self.apply_mode not in ("suggest", "apply"):
            raise ValueError(
                "planner.applyMode must be 'suggest' or 'apply', got "
                f"{self.apply_mode!r}"
            )
        if not self.enabled:
            return
        if not self.objective:
            raise ValueError(
                "planner.enabled requires planner.objective (e.g. "
                "{ttftP99Ms: 250})"
            )
        for key, value in self.objective.items():
            if value <= 0:
                raise ValueError(
                    f"planner.objective.{key} must be > 0, got {value}"
                )
        if self.trace_path is None and self.trace is None:
            raise ValueError(
                "planner.enabled requires a trace source: tracePath (a "
                "/router/debug/requests export on disk) or trace (the "
                "export inline)"
            )


# Mirrors parallel.mesh.MESH_AXIS_ORDER without importing jax into the
# operator process (tests pin the two tuples equal).
MESH_AXES = ("dp", "pp", "ep", "sp", "tp")


def _parse_mesh_shape(value) -> dict:
    """Structural meshShape validation at reconcile time: unknown axis
    names and non-positive sizes must land in CR status, not as a pod
    CrashLoopBackOff at the server's build_mesh.

    An absent meshShape defaults to ``{"dp": 1, "tp": 1}`` — product 1,
    i.e. NO mesh — matching the server's ``--mesh-shape`` default, so
    the manifest the operator renders and the engine the pod builds
    agree byte-for-byte when the field is omitted (the old ``tp: 8``
    fallback silently demanded an 8-chip slice from a CR that never
    asked for sharding)."""
    mesh = dict(value or {"dp": 1, "tp": 1})
    unknown = set(mesh) - set(MESH_AXES)
    if unknown:
        raise ValueError(
            f"spec.tpu.meshShape has unknown axes {sorted(unknown)}; "
            f"known: {list(MESH_AXES)}"
        )
    out = {}
    for axis, size in mesh.items():
        try:
            n = int(size)
        except (TypeError, ValueError):
            raise ValueError(
                f"spec.tpu.meshShape.{axis} must be a positive integer, "
                f"got {size!r}"
            ) from None
        if n < 1:
            raise ValueError(
                f"spec.tpu.meshShape.{axis} must be >= 1, got {n}"
            )
        out[axis] = n
    return out


def validate_mesh_for_model(
    mesh_shape: Mapping[str, int] | None,
    *,
    num_kv_heads: int | None = None,
    num_heads: int | None = None,
    intermediate_size: int | None = None,
    vocab_size: int | None = None,
    cache_rows: int | None = None,
    prefill_chunk: int | None = None,
    chip_count: int | None = None,
) -> None:
    """Reject a ``meshShape`` the model/serving geometry cannot shard —
    typed, naming the knob and the offending count.

    Without this the mismatch surfaces as an opaque XLA shape error at
    the first warmup dispatch (after the weights already streamed).  The
    KV-head count is the binding constraint for ``tp`` (the cache's
    heads axis is what decode shards); heads/mlp/vocab ride along so
    every sharded matrix is covered by one message shape.  ``dp`` must
    divide the cache-row count (``cache_rows``, i.e. maxSlots — each dp
    shard owns B/dp rows), ``sp`` the prefill chunk size
    (``prefill_chunk`` — ring attention splits the sequence axis
    evenly), and the total ``dp*pp*ep*sp*tp`` must fit ``chip_count``
    when given.  Called by the server loader and the generation engine
    with the artifact's geometry in hand; the operator applies the
    structural half (:func:`_parse_mesh_shape`) at reconcile, where the
    artifact is not yet readable.
    """
    mesh = dict(mesh_shape or {})
    tp = int(mesh.get("tp", 1))
    dp = int(mesh.get("dp", 1))
    sp = int(mesh.get("sp", 1))
    if chip_count is not None:
        total = 1
        for v in mesh.values():
            total *= int(v)
        if total > int(chip_count):
            raise ValueError(
                f"spec.tpu.meshShape {mesh} uses {total} devices but the "
                f"topology provides only {int(chip_count)} chips; "
                "dp*pp*ep*sp*tp must not exceed the slice or the pod is "
                "unschedulable"
            )
    if dp > 1 and cache_rows is not None and int(cache_rows) % dp != 0:
        raise ValueError(
            f"spec.tpu.meshShape dp={dp} does not divide the KV-cache "
            f"row count (maxSlots) = {int(cache_rows)}; each dp shard "
            "owns rows/dp cache rows — pick a maxSlots that dp divides "
            "(or dp: 1)"
        )
    if sp > 1 and prefill_chunk is not None and int(prefill_chunk) % sp != 0:
        raise ValueError(
            f"spec.tpu.meshShape sp={sp} does not divide the prefill "
            f"chunk size (prefillChunk) = {int(prefill_chunk)}; ring "
            "attention splits the sequence axis into sp equal shards — "
            "pick a chunk that sp divides (or sp: 1)"
        )
    if tp <= 1:
        return
    checks = (
        ("KV-head count (num_kv_heads)", num_kv_heads),
        ("attention-head count (num_heads)", num_heads),
        ("MLP width (intermediate_size)", intermediate_size),
        ("vocab size (vocab_size)", vocab_size),
    )
    for label, count in checks:
        if count is None:
            continue
        if int(count) % tp != 0:
            raise ValueError(
                f"spec.tpu.meshShape tp={tp} does not divide the model's "
                f"{label} = {int(count)}; pick a tp that divides it (or "
                "tp: 1) — an indivisible axis cannot shard and would "
                "fail as an XLA shape error at first dispatch"
            )


def _parse_quantize(value) -> str:
    """Reject bad quantize values at reconcile time — a typo'd CR field must
    surface in status, not as a pod CrashLoopBackOff at argparse."""
    mode = str(value).lower()
    if mode not in ("none", "int8", "int8kv"):
        raise ValueError(
            f"spec.tpu.quantize must be 'none', 'int8', or 'int8kv', "
            f"got {value!r}"
        )
    return mode


@dataclass(frozen=True)
class TpuSpec:
    """TPU data-plane placement and sharding (north-star CRD additions).

    ``mesh_shape`` maps logical mesh axis names to sizes, e.g.
    ``{"dp": 1, "tp": 8}`` for a Llama-2-7B tensor-sharded across a v5e-8
    slice.  ``topology`` selects the node pool (e.g. ``v5e-8``); the builder
    turns it into nodeSelector/toleration entries.
    """

    topology: str = "v5e-8"
    mesh_shape: Mapping[str, int] = field(default_factory=lambda: {"dp": 1, "tp": 1})
    replicas: int = 1
    dtype: str = "bfloat16"
    max_batch_size: int = 32
    max_batch_delay_ms: float = 5.0
    # Continuous-batching decode slots.  None = min(max_batch_size, 8), a
    # conservative latency-first default; throughput deployments should
    # raise it — decode streams the full weights per step, so tok/s rises
    # near-linearly with slots until the KV cache dominates HBM traffic
    # (measured curve in bench.py llama_decode.slot_ladder).
    max_slots: int | None = None
    # Batches allowed in flight on the device at once (async dispatch
    # double-buffering): while batch N executes, batch N+1 is stacked and
    # dispatched.  1 = fully serial (the pre-pipelining behavior).
    max_inflight_batches: int = 2
    compile_cache_dir: str | None = "/tmp/jax_compile_cache"
    quantize: str = "none"  # none | int8 (weights) | int8kv (weights+KV cache)
    prefill_chunk: int | None = None  # chunked prefill (decode interleaving)
    # Packed multi-admission prefill: concurrent admissions' next chunks
    # batch into ONE prefill call, amortizing the per-chunk HBM weight
    # stream across waiting prompts (TTFT under bursty load).  1 = the
    # single-admission pipeline, byte-for-byte.  > 1 requires chunked
    # prefill (prefillChunk, or prefixCache which implies it).
    prefill_batch: int = 1
    # Prompt tokens prefilled per engine tick (0 = uncapped): caps how
    # much prefill work a tick may batch so in-flight decode streams
    # keep their token cadence under long-prompt bursts (Sarathi-style).
    prefill_token_budget: int = 0
    # Sequence-parallel ring-attention prefill (meshShape sp > 1): cold
    # prompts at least this many tokens long prefill with the sequence
    # axis split across the sp chips (ops/ring_attention.py) instead of
    # the chunked/fused single-device path.  Ignored when sp == 1.
    sp_prefill_threshold: int = 1024
    # Radix prefix KV cache: shared prompt prefixes (system prompts, chat
    # templates) prefill once and are copied thereafter.
    prefix_cache: PrefixCacheSpec = field(default_factory=PrefixCacheSpec)
    # Pre-baked weight snapshots (server/snapshot.py): the post-shard,
    # post-quantize device tree on disk, restored with zero transform
    # work — the scale-to-zero wake path's fast restore.
    snapshot: SnapshotSpec = field(default_factory=SnapshotSpec)
    # Self-speculative n-gram decoding: batched multi-token verify
    # amortizes the per-tick HBM weight stream over accepted drafts.
    speculative: SpeculativeSpec = field(default_factory=SpeculativeSpec)
    # Fused multi-step decode: K decode iterations per device dispatch
    # (on-device sampling chain + EOS latch) with lag-1 async token
    # readback — collapses per-token host dispatch overhead by ~K when
    # the scheduler owes nothing else.  1 = single-step loop,
    # byte-for-byte.  Composes with speculative per slot (draft ticks
    # verify, draft-less ticks fuse) — see _parse_decode_steps.
    decode_steps: int = 1
    # Unified ragged super-step: ONE jit program per engine tick covers
    # packed-prefill chunk commits, fused-K decode with on-device
    # sampling chains, and speculative verify simultaneously (per-row
    # role tensors), collapsing the warmup sweep to one variant per
    # (window-bucket x sampling-mode).  False — the default — keeps the
    # split-program legacy engine byte-for-byte.
    unified_step: bool = False
    # Engine flight recorder (per-tick journal + request traces at
    # /debug/engine and /debug/trace); traceRing 0 = off, zero overhead.
    observability: ObservabilitySpec = field(default_factory=ObservabilitySpec)
    # Warm the FULL batch x seq-length compile grid at startup instead of
    # the edges (batch 1 / max per length).  Costs |batch buckets| x
    # |length buckets| cold compiles; buys zero first-hit compile stalls
    # even with a cold persistent cache.
    warmup_full_grid: bool = False
    # Server-side admission control: shed /generate submissions with
    # 429 + Retry-After once the estimated tokens (prompt + max_new) of
    # queued-but-unadmitted work would exceed this budget.  0 (default)
    # = unbounded queue, byte-for-byte the old admission behavior.
    # Sheds keep p99 TTFT bounded under overload and give the replica
    # autoscaler a loss-free pressure valve while new replicas boot.
    admission_queue_budget: int = 0
    # Lossless-drain window: on SIGTERM / POST /admin/drain the server
    # stops admissions (new requests shed 429), flips /readyz, and waits
    # up to this many seconds for in-flight sequences to finish before
    # teardown — scale-down and rollout teardown never drop a request.
    # 20 (not 30): + the 3s endpoint lag it fits Kubernetes' default
    # 30s termination grace; larger values emit a pod grace override.
    drain_grace_s: float = 20.0
    # Default SLO class for requests that don't carry one (interactive |
    # batch | best-effort).  Setting it arms the engine's priority
    # admission queues: higher classes drain first, lower classes shed
    # at a fraction of the admission budget.  None (the default) leaves
    # the single-queue admission path byte-for-byte.  Top-level
    # spec.sloClass is the CRD spelling; spec.tpu.sloClass the low-level
    # one (top-level wins when both are set).
    slo_class: str | None = None
    # Mid-decode preemption: a waiting higher-class request may evict a
    # lower-class slot at a tick boundary — its K/V is written back
    # through the radix prefix cache, the record requeued at the front
    # of its class, and restored on re-admission with no lost work.
    # Requires prefixCache.enabled (the cache IS the parking surface).
    preemption: bool = False

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "TpuSpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec,
            frozenset(
                {
                    "tpuTopology", "meshShape", "replicas", "dtype",
                    "maxBatchSize", "maxBatchDelayMs", "maxSlots",
                    "maxInflightBatches", "compileCacheDir", "quantize",
                    "prefillChunk", "prefillBatch", "prefillTokenBudget",
                    "spPrefillThreshold",
                    "prefixCache", "speculative", "decodeSteps",
                    "unifiedStep", "observability", "snapshot",
                    "warmupFullGrid", "admissionQueueBudget",
                    "drainGraceSeconds", "sloClass", "preemption",
                }
            ),
            "spec.tpu",
        )
        mesh = _parse_mesh_shape(spec.get("meshShape"))
        prefill_chunk = _parse_prefill_chunk(spec.get("prefillChunk"))
        prefill_batch = _parse_prefill_batch(spec.get("prefillBatch"))
        prefix_cache = PrefixCacheSpec.from_spec(
            spec.get("prefixCache"), prefill_chunk=prefill_chunk
        )
        if (
            prefill_batch > 1
            and prefill_chunk is None
            and not prefix_cache.enabled
        ):
            # Reject at reconcile time, not as a pod CrashLoopBackOff:
            # packed admission batches CHUNKS, so a chunk size must exist.
            raise ValueError(
                f"spec.tpu.prefillBatch {prefill_batch} requires chunked "
                "prefill: set prefillChunk (or enable prefixCache, which "
                "implies it)"
            )
        slo_class = spec.get("sloClass")
        if slo_class is not None:
            slo_class = str(slo_class)
            if slo_class not in SLO_CLASSES:
                raise ValueError(
                    f"spec.tpu.sloClass must be one of {list(SLO_CLASSES)}, "
                    f"got {slo_class!r}"
                )
        preemption = bool(spec.get("preemption", False))
        if preemption and not prefix_cache.enabled:
            # The evicted slot's K/V parks in the radix cache; without it
            # preemption would have to discard decoded work.
            raise ValueError(
                "spec.tpu.preemption requires spec.tpu.prefixCache.enabled "
                "(an evicted slot's K/V is written back through the radix "
                "prefix cache and restored from it on re-admission)"
            )
        return cls(
            topology=str(spec.get("tpuTopology", "v5e-8")),
            mesh_shape=mesh,
            replicas=int(spec.get("replicas", 1)),
            dtype=str(spec.get("dtype", "bfloat16")),
            max_batch_size=int(spec.get("maxBatchSize", 32)),
            max_batch_delay_ms=float(spec.get("maxBatchDelayMs", 5.0)),
            max_slots=(
                int(spec["maxSlots"]) if spec.get("maxSlots") is not None else None
            ),
            max_inflight_batches=int(spec.get("maxInflightBatches", 2)),
            compile_cache_dir=spec.get("compileCacheDir", "/tmp/jax_compile_cache"),
            quantize=_parse_quantize(spec.get("quantize", "none")),
            prefill_chunk=prefill_chunk,
            prefill_batch=prefill_batch,
            prefill_token_budget=_parse_prefill_token_budget(
                spec.get("prefillTokenBudget")
            ),
            sp_prefill_threshold=_parse_sp_prefill_threshold(
                spec.get("spPrefillThreshold")
            ),
            prefix_cache=prefix_cache,
            snapshot=SnapshotSpec.from_spec(spec.get("snapshot")),
            speculative=SpeculativeSpec.from_spec(spec.get("speculative")),
            decode_steps=_parse_decode_steps(spec.get("decodeSteps")),
            unified_step=bool(spec.get("unifiedStep", False)),
            observability=ObservabilitySpec.from_spec(
                spec.get("observability")
            ),
            warmup_full_grid=bool(spec.get("warmupFullGrid", False)),
            admission_queue_budget=_parse_admission_budget(
                spec.get("admissionQueueBudget")
            ),
            drain_grace_s=_parse_drain_grace(spec.get("drainGraceSeconds")),
            slo_class=slo_class,
            preemption=preemption,
        )

    @property
    def num_devices(self) -> int:
        n = 1
        for v in self.mesh_shape.values():
            n *= int(v)
        return n


@dataclass(frozen=True)
class ServerConfig:
    """Config for one inference-server process (the data plane)."""

    model_name: str = "model"
    model_uri: str = ""
    predictor_name: str = "v1"
    deployment_name: str = ""
    namespace: str = "default"
    host: str = "0.0.0.0"
    port: int = 9000
    metrics_port: int = 6000
    tpu: TpuSpec = field(default_factory=TpuSpec)
    # Warm-pool boot (server --warm-pool): start with compiled programs
    # pre-baked (the warmup sweep runs against the persistent compile
    # cache using the snapshot manifest's geometry) but NO weights;
    # POST /admin/attach snapshot-restores a model on demand.
    warm_pool: bool = False
    # Disaggregated-fleet role of this replica (server --fleet-role):
    # "prefill" computes prompt K/V for handoff, "decode" receives
    # handoffs and streams tokens, "unified" (the default) does both —
    # advisory identity surfaced on /readyz and in logs; the KV
    # endpoints exist on every role (the router decides who does what).
    fleet_role: str = "unified"
    # Scheduler-loop watchdog (server --watchdog-deadline-s): a tick
    # exceeding the deadline flips /readyz unready and journals a
    # ``watchdog`` flight-recorder event; if the stall persists past the
    # grace the process exits so Kubernetes restarts the pod.  0 (the
    # default) constructs no watchdog — the engine loop is byte-for-byte.
    watchdog_deadline_s: float = 0.0
    watchdog_grace_s: float = 30.0


@dataclass(frozen=True)
class MultiplexSpec:
    """``spec.multiplex``: opt this CR into a shared warm-pool fleet.

    ``poolRef`` names the shared pool (a plain convention string — every
    CR naming the same pool in the same namespace is bin-packed onto
    that pool's warm replicas by ``operator/multiplexer.py``).
    ``weight`` biases the packer's traffic score: a weight-2 model wins
    a replica over a weight-1 model at equal observed traffic.

    A multiplexed model owns NO replica of its own: with zero traffic
    it holds nothing (its requests park at the router), and the packer
    attaches it to a pool replica via the warm-pool admin endpoint when
    parked/queued traffic appears.  Absent (the default) keeps
    manifests, router behavior, and metrics byte-for-byte unchanged.
    """

    pool_ref: str | None = None
    weight: float = 1.0

    @property
    def enabled(self) -> bool:
        return self.pool_ref is not None

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "MultiplexSpec":
        spec = spec or {}
        _reject_unknown_keys(
            spec, frozenset({"poolRef", "weight"}), "spec.multiplex"
        )
        pool_ref = spec.get("poolRef")
        if pool_ref is not None:
            pool_ref = str(pool_ref)
            if not pool_ref:
                raise ValueError("multiplex.poolRef must be non-empty")
        elif spec.get("weight") is not None:
            # A weight without a pool is a contradiction the CR author
            # must resolve — silently ignoring it would leave them
            # believing the model is multiplexed.
            raise ValueError("multiplex.weight requires multiplex.poolRef")
        return cls(
            pool_ref=pool_ref,
            weight=float(spec.get("weight", 1.0)),
        )

    def __post_init__(self):
        if self.enabled and not (self.weight > 0):
            raise ValueError(
                f"multiplex.weight must be > 0, got {self.weight}"
            )


@dataclass(frozen=True)
class OperatorConfig:
    """Full parsed ``MlflowModel`` spec.

    Reference spec fields (``crd.yaml:17-25``): ``modelName``, ``modelAlias``,
    ``monitoringInterval``, ``minioSecret``.  Everything else is a rebuild
    addition with reference-equivalent defaults.
    """

    model_name: str
    model_alias: str
    monitoring_interval_s: float = DEFAULT_MONITORING_INTERVAL_S
    minio_secret: str | None = None
    backend: str = "seldon"  # "seldon" (reference parity) | "tpu" (first-party)
    artifact_root: str = DEFAULT_ARTIFACT_ROOT
    prometheus_url: str = DEFAULT_PROMETHEUS_URL
    thresholds: GateThresholds = field(default_factory=GateThresholds)
    canary: CanaryPolicy = field(default_factory=CanaryPolicy)
    tpu: TpuSpec = field(default_factory=TpuSpec)
    server_image: str = "tpumlops/jax-server:latest"
    # Rollout journal surfacing on CR status (status.lastGate/history);
    # distinct from spec.tpu.observability, which sizes the data plane's
    # engine flight recorder.
    observability: RolloutObservability = field(
        default_factory=RolloutObservability
    )
    # SLO-driven replica autoscaling (operator/autoscaler.py); disabled
    # default = manifests and status byte-for-byte unchanged.
    autoscaling: AutoscalingSpec = field(default_factory=AutoscalingSpec)
    # Disaggregated prefill/decode pools with KV handoff and prefix-
    # affinity routing; disabled default = byte-for-byte.
    fleet: FleetSpec = field(default_factory=FleetSpec)
    # Serving objectives (error-budget accounting in operator/slo.py);
    # absent default = no tracker, no series, byte-for-byte.
    slo: SloSpec = field(default_factory=SloSpec)
    # Offline SLO planner (operator/planner.py): trace replay + knob
    # search behind spec.planner; disabled default = byte-for-byte.
    planner: PlannerSpec = field(default_factory=PlannerSpec)
    # Multi-model multiplexing on a shared warm pool
    # (operator/multiplexer.py); absent default = byte-for-byte.
    multiplex: MultiplexSpec = field(default_factory=MultiplexSpec)
    # Fleet anomaly detector (operator/anomaly.py): straggler + drift
    # verdicts over time-series ring snapshots; absent default = no
    # detector, no series, byte-for-byte.
    anomaly: AnomalySpec = field(default_factory=AnomalySpec)

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any]) -> "OperatorConfig":
        model_name = spec.get("modelName")
        model_alias = spec.get("modelAlias")
        if not model_name or not model_alias:
            raise ValueError("spec.modelName and spec.modelAlias are required")
        backend = str(spec.get("backend", "seldon"))
        if backend not in ("seldon", "tpu"):
            raise ValueError(f"spec.backend must be 'seldon' or 'tpu', got {backend!r}")
        tpu = TpuSpec.from_spec(spec.get("tpu"))
        # Top-level spec.sloClass is the CRD spelling of the data plane's
        # default class — authoritative over spec.tpu.sloClass when both
        # are set (the tpu key exists so the server CLI round-trips).
        top_slo = spec.get("sloClass")
        if top_slo is not None:
            top_slo = str(top_slo)
            if top_slo not in SLO_CLASSES:
                raise ValueError(
                    f"spec.sloClass must be one of {list(SLO_CLASSES)}, "
                    f"got {top_slo!r}"
                )
            tpu = replace(tpu, slo_class=top_slo)
        autoscaling = AutoscalingSpec.from_spec(spec.get("autoscaling"))
        fleet = FleetSpec.from_spec(spec.get("fleet"))
        if fleet.disaggregation:
            if backend != "tpu":
                raise ValueError(
                    "fleet.disaggregation requires backend: tpu (the "
                    "Seldon backend has no KV handoff data plane)"
                )
            if not tpu.prefix_cache.enabled:
                # The handoff wire format IS the radix cache's chunk —
                # without the cache there is nothing to export, seed, or
                # route affinity for.
                raise ValueError(
                    "fleet.disaggregation requires spec.tpu.prefixCache."
                    "enabled (handed-off K/V re-enters the decode replica "
                    "through the radix prefix cache's seed path)"
                )
            if fleet.prefill_min_replicas == 0 and not tpu.snapshot.enabled:
                raise ValueError(
                    "fleet.prefillMinReplicas: 0 requires spec.tpu."
                    "snapshot.enabled (a prefill pool woken from zero "
                    "must restore pre-baked weights while the cold "
                    "prompt waits; without a snapshot it pays the full "
                    "cold load)"
                )
        anomaly = AnomalySpec.from_spec(spec.get("anomaly"))
        if anomaly.enabled and tpu.observability.timeseries_ring <= 0:
            # The detector's ONLY input plane is the per-replica ring —
            # without one it would silently never fire, the worst
            # failure mode for a health check.
            raise ValueError(
                "spec.anomaly requires spec.tpu.observability."
                "timeseriesRing > 0 (the detector compares replicas over "
                "their time-series ring snapshots; without rings there "
                "is nothing to detect from)"
            )
        multiplex = MultiplexSpec.from_spec(spec.get("multiplex"))
        if multiplex.enabled:
            if backend != "tpu":
                raise ValueError(
                    "spec.multiplex requires backend: tpu (the Seldon "
                    "backend has no warm-pool attach data plane)"
                )
            if not tpu.snapshot.enabled:
                raise ValueError(
                    "spec.multiplex requires spec.tpu.snapshot.enabled "
                    "(the shared pool attaches models by snapshot "
                    "restore; without one every swap pays the full "
                    "cold load)"
                )
            if fleet.disaggregation:
                raise ValueError(
                    "spec.multiplex with fleet.disaggregation is not "
                    "supported: the shared pool multiplexes unified "
                    "replicas, not split prefill/decode pools"
                )
        if (
            autoscaling.enabled
            and autoscaling.min_replicas == 0
            and not tpu.snapshot.enabled
        ):
            # Scale-to-zero without a restorable snapshot means every
            # wake pays the full cold path while a request is parked —
            # the exact failure scale-to-zero exists to prevent.
            raise ValueError(
                "autoscaling.minReplicas: 0 requires spec.tpu.snapshot."
                "enabled (the wake path restores pre-baked weights; "
                "without a snapshot the parked request would wait out a "
                "full cold load)"
            )
        if autoscaling.warm_pool_size > 0 and not tpu.snapshot.enabled:
            raise ValueError(
                "autoscaling.warmPoolSize > 0 requires spec.tpu."
                "snapshot.enabled (warm-pool replicas attach models by "
                "snapshot restore)"
            )
        if backend == "tpu":
            info = TPU_TOPOLOGIES.get(tpu.topology)
            if info is None:
                raise ValueError(
                    f"unknown tpuTopology {tpu.topology!r}; known: "
                    f"{sorted(TPU_TOPOLOGIES)}"
                )
            if tpu.num_devices > info.chips:
                # Over-subscription only: a mesh SMALLER than the slice
                # is legal (the server builds it over a device prefix —
                # a {dp:1, tp:1} debug CR on a v5e-8 pool runs fine,
                # idle chips and all); a mesh larger than the slice can
                # never schedule.  "must match" was the old rule — it
                # made the absent-meshShape default unschedulable on
                # every topology but v5e-8.
                raise ValueError(
                    f"meshShape {dict(tpu.mesh_shape)} uses {tpu.num_devices} "
                    f"devices but tpuTopology {tpu.topology!r} provides "
                    f"only {info.chips} chips; dp*pp*ep*sp*tp must not "
                    "exceed the slice or the pod is unschedulable"
                )
            # Serving-geometry axes are checkable at reconcile (the
            # model's head counts are not — the loader re-validates with
            # the artifact in hand): dp must divide the cache-row count,
            # sp the prefill chunk.
            validate_mesh_for_model(
                tpu.mesh_shape,
                cache_rows=tpu.max_slots,
                prefill_chunk=tpu.prefill_chunk,
                chip_count=info.chips,
            )
            if info.hosts > 1 and tpu.replicas > 1:
                raise ValueError(
                    f"replicas={tpu.replicas} with multi-host topology "
                    f"{tpu.topology!r} is not supported yet: one worker "
                    "unit per predictor version; scale out with more "
                    "MlflowModel CRs or a larger slice"
                )
            if info.hosts > 1 and autoscaling.max_replicas > 1:
                # Same constraint the builder enforces for replicas > 1:
                # a multi-host unit is one StatefulSet per predictor, so
                # the autoscaler cannot fan it out either.
                raise ValueError(
                    f"autoscaling.maxReplicas={autoscaling.max_replicas} "
                    f"with multi-host topology {tpu.topology!r} is not "
                    "supported: one worker unit per predictor version; "
                    "scale out with more MlflowModel CRs or a larger "
                    "slice"
                )
            if info.hosts > 1 and fleet.disaggregation:
                # A pool replica is one pod; a multi-host unit is N pods
                # forming one process group — neither pool machinery nor
                # the per-replica KV handoff models that.
                raise ValueError(
                    f"fleet.disaggregation with multi-host topology "
                    f"{tpu.topology!r} is not supported: pools scale "
                    "single-host replicas; use a larger slice or more "
                    "MlflowModel CRs"
                )
            if info.hosts > 1 and multiplex.enabled:
                raise ValueError(
                    f"spec.multiplex with multi-host topology "
                    f"{tpu.topology!r} is not supported: the shared "
                    "pool attaches by single-host snapshot restore"
                )
            if info.hosts > 1 and (
                autoscaling.min_replicas == 0
                or autoscaling.warm_pool_size > 0
            ):
                # Snapshots store a single-device tree; a multi-host
                # unit's weights are distributed across hosts, so wake-
                # from-zero cannot restore it (and a parked unit would
                # strand the follower process group mid-collective).
                raise ValueError(
                    f"scale-to-zero (autoscaling.minReplicas: 0 / "
                    f"warmPoolSize > 0) with multi-host topology "
                    f"{tpu.topology!r} is not supported: the snapshot "
                    "restore path is single-host; scale out with more "
                    "MlflowModel CRs or keep minReplicas >= 1"
                )
        return cls(
            model_name=str(model_name),
            model_alias=str(model_alias),
            monitoring_interval_s=float(
                spec.get("monitoringInterval", DEFAULT_MONITORING_INTERVAL_S)
            ),
            minio_secret=spec.get("minioSecret"),
            backend=backend,
            artifact_root=str(spec.get("artifactRoot", DEFAULT_ARTIFACT_ROOT)),
            prometheus_url=str(spec.get("prometheusUrl", DEFAULT_PROMETHEUS_URL)),
            thresholds=GateThresholds.from_spec(spec.get("thresholds")),
            canary=CanaryPolicy.from_spec(spec.get("canary")),
            tpu=tpu,
            server_image=str(spec.get("serverImage", "tpumlops/jax-server:latest")),
            observability=RolloutObservability.from_spec(
                spec.get("observability")
            ),
            autoscaling=autoscaling,
            fleet=fleet,
            slo=SloSpec.from_spec(spec.get("slo")),
            planner=PlannerSpec.from_spec(spec.get("planner")),
            multiplex=multiplex,
            anomaly=anomaly,
        )
