"""Logging helpers.

The reference configures global INFO logging at import time
(``mlflow_operator.py:16``) and creates one child logger per model named
``f"{name}-{namespace}"`` (``:38-41``), prefixing messages with
``[namespace/name]``.  We keep the per-resource logger convention but make
the prefix part of the logger itself.
"""

from __future__ import annotations

import logging


class _PrefixAdapter(logging.LoggerAdapter):
    def process(self, msg, kwargs):
        return f"{self.extra['resource']} {msg}", kwargs


def model_logger(name: str, namespace: str) -> logging.LoggerAdapter:
    """Per-resource logger with the reference's ``[ns/name]`` message prefix."""
    base = logging.getLogger(f"tpumlops.{namespace}.{name}")
    return _PrefixAdapter(base, {"resource": f"[{namespace}/{name}]"})


def configure(level: int = logging.INFO) -> None:
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
