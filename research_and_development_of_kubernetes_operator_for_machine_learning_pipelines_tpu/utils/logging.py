"""Logging helpers.

The reference configures global INFO logging at import time
(``mlflow_operator.py:16``) and creates one child logger per model named
``f"{name}-{namespace}"`` (``:38-41``), prefixing messages with
``[namespace/name]``.  We keep the per-resource logger convention but make
the prefix part of the logger itself.

``configure(json_format=True)`` (the ``--log-format json`` CLI flag on the
server and operator entrypoints) switches every line to one JSON object
carrying ``request_id`` when the record has one — the per-request
completion lines the server emits become machine-parseable without
regexing the ``[ns/name]`` prefix convention away.
"""

from __future__ import annotations

import json
import logging


class _PrefixAdapter(logging.LoggerAdapter):
    def process(self, msg, kwargs):
        return f"{self.extra['resource']} {msg}", kwargs


def model_logger(name: str, namespace: str) -> logging.LoggerAdapter:
    """Per-resource logger with the reference's ``[ns/name]`` message prefix."""
    base = logging.getLogger(f"tpumlops.{namespace}.{name}")
    return _PrefixAdapter(base, {"resource": f"[{namespace}/{name}]"})


class JsonFormatter(logging.Formatter):
    """One JSON object per line; ``request_id`` rides along when present
    (loggers pass it via ``extra={"request_id": ...}``)."""

    def format(self, record: logging.LogRecord) -> str:
        from datetime import datetime, timezone

        # UTC with millisecond precision and an explicit offset: whole
        # local seconds can't order two completion lines from one burst,
        # and offset-less stamps from pods in different TZ configs don't
        # merge.
        ts = datetime.fromtimestamp(
            record.created, timezone.utc
        ).isoformat(timespec="milliseconds")
        out = {
            "ts": ts,
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        request_id = getattr(record, "request_id", None)
        if request_id:
            out["request_id"] = str(request_id)
        if record.exc_info:
            out["exc_info"] = self.formatException(record.exc_info)
        # default=str: a log call with a non-serializable extra must
        # degrade to its repr, never throw inside the logging machinery.
        return json.dumps(out, default=str)


def configure(level: int = logging.INFO, json_format: bool = False) -> None:
    if json_format:
        handler = logging.StreamHandler()
        handler.setFormatter(JsonFormatter())
        logging.basicConfig(level=level, handlers=[handler], force=True)
        return
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
