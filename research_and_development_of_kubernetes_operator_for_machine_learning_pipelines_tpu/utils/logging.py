"""Logging helpers.

The reference configures global INFO logging at import time
(``mlflow_operator.py:16``) and creates one child logger per model named
``f"{name}-{namespace}"`` (``:38-41``), prefixing messages with
``[namespace/name]``.  We keep the per-resource logger convention but make
the prefix part of the logger itself.

``configure(json_format=True)`` (the ``--log-format json`` CLI flag on the
server and operator entrypoints) switches every line to one JSON object
carrying ``request_id`` when the record has one — the per-request
completion lines the server emits become machine-parseable without
regexing the ``[ns/name]`` prefix convention away.
"""

from __future__ import annotations

import json
import logging


class _PrefixAdapter(logging.LoggerAdapter):
    """Per-CR adapter: the ``[ns/name]`` text prefix (with the observed
    ``metadata.generation`` when the reconciler has one), and namespace /
    name / generation as record attributes so ``--log-format json``
    carries them as structured fields — the operator's counterpart of
    the server's per-request ``request_id`` convention."""

    def process(self, msg, kwargs):
        # Record attributes are cr_-prefixed because bare "name" is a
        # reserved LogRecord attribute (logging rejects it in extra);
        # JsonFormatter renders them back as namespace/name/generation.
        extra = dict(kwargs.get("extra") or {})
        extra.setdefault("cr_namespace", self.extra["namespace"])
        extra.setdefault("cr_name", self.extra["name"])
        generation = self.extra.get("generation")
        prefix = self.extra["resource"]
        if generation is not None:
            extra.setdefault("cr_generation", generation)
            prefix = (
                f"[{self.extra['namespace']}/{self.extra['name']}"
                f" gen={generation}]"
            )
        kwargs["extra"] = extra
        return f"{prefix} {msg}", kwargs

    def set_generation(self, generation) -> None:
        """Stamp the CR generation the current reconcile step observed."""
        self.extra["generation"] = generation


def model_logger(name: str, namespace: str) -> logging.LoggerAdapter:
    """Per-resource logger with the reference's ``[ns/name]`` message prefix."""
    base = logging.getLogger(f"tpumlops.{namespace}.{name}")
    return _PrefixAdapter(
        base,
        {
            "resource": f"[{namespace}/{name}]",
            "namespace": namespace,
            "name": name,
            "generation": None,
        },
    )


class JsonFormatter(logging.Formatter):
    """One JSON object per line; ``request_id`` rides along when present
    (loggers pass it via ``extra={"request_id": ...}``)."""

    def format(self, record: logging.LogRecord) -> str:
        from datetime import datetime, timezone

        # UTC with millisecond precision and an explicit offset: whole
        # local seconds can't order two completion lines from one burst,
        # and offset-less stamps from pods in different TZ configs don't
        # merge.
        ts = datetime.fromtimestamp(
            record.created, timezone.utc
        ).isoformat(timespec="milliseconds")
        out = {
            "ts": ts,
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        request_id = getattr(record, "request_id", None)
        if request_id:
            out["request_id"] = str(request_id)
        # CR identity (the operator's analogue of request_id): attached
        # by the per-CR _PrefixAdapter, one set per reconcile log line.
        # cr_-prefixed on the record (bare "name" is reserved there),
        # clean names in the JSON output.
        for attr, key in (
            ("cr_namespace", "namespace"),
            ("cr_name", "name"),
            ("cr_generation", "generation"),
        ):
            value = getattr(record, attr, None)
            if value is not None:
                out[key] = value
        if record.exc_info:
            out["exc_info"] = self.formatException(record.exc_info)
        # default=str: a log call with a non-serializable extra must
        # degrade to its repr, never throw inside the logging machinery.
        return json.dumps(out, default=str)


def configure(level: int = logging.INFO, json_format: bool = False) -> None:
    if json_format:
        handler = logging.StreamHandler()
        handler.setFormatter(JsonFormatter())
        logging.basicConfig(level=level, handlers=[handler], force=True)
        return
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
