"""Cross-cutting utilities: clocks, configuration, logging, tracing."""

from .clock import Clock, FakeClock, SystemClock
from .config import (
    CanaryPolicy,
    GateThresholds,
    OperatorConfig,
    ServerConfig,
    TpuSpec,
)

__all__ = [
    "Clock",
    "FakeClock",
    "SystemClock",
    "CanaryPolicy",
    "GateThresholds",
    "OperatorConfig",
    "ServerConfig",
    "TpuSpec",
]
