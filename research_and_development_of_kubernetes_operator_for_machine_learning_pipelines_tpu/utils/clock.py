"""Injectable time source.

The reference drives all pacing with ``await asyncio.sleep(...)`` inside an
infinite handler (``mlflow_operator.py:92,:154,:340,:352``), which makes the
promotion loop untestable without real wall time.  The rebuild injects a
``Clock`` everywhere time is read so the whole canary state machine can be
unit-tested with a ``FakeClock``.
"""

from __future__ import annotations

import time
from typing import Protocol, runtime_checkable


@runtime_checkable
class Clock(Protocol):
    def now(self) -> float:
        """Seconds since an arbitrary epoch; must be monotonic non-decreasing."""
        ...


class SystemClock:
    """Wall-clock backed by ``time.monotonic`` (promotion pacing never needs
    calendar time, and monotonic survives NTP steps)."""

    def now(self) -> float:
        return time.monotonic()


class FakeClock:
    """Deterministic clock for tests; advance manually."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def advance(self, seconds: float) -> None:
        if seconds < 0:
            raise ValueError("cannot move a monotonic clock backwards")
        self._t += seconds
