"""Fleet trace stitching: merge per-component Chrome traces into ONE.

The fleet trace plane leaves three journals behind for any request:
the router's journey ring (``GET /router/debug/trace``) and each
replica's engine flight recorder (``GET /debug/trace``).  All three
export Chrome trace-event JSON whose timestamps are microseconds since
*that process's own start* — useless side by side until they share a
clock.  Every component also exports its ``started_unix`` anchor
(``/router/debug/requests`` and ``/debug/engine``), so stitching is a
pure shift-and-merge:

1. fetch each component's chrome trace + ``started_unix``;
2. pick the earliest anchor as the common epoch;
3. shift each component's ``ts`` by its anchor delta and re-home its
   events under a distinct ``pid`` (one "process" per component in
   Perfetto's UI);
4. concatenate.

Because the router propagates one ``X-Request-Id``/trace id across every
leg (forwards, KV export/import relays, failover retries, park
releases), the async request spans emitted by the router and by every
replica the request touched carry the SAME ``id`` — Perfetto renders
them as one coherent request story across process tracks, which is the
acceptance bar for the chaos e2e (relay → failover → park as one tree).

Consumed three ways: ``scripts/stitch_trace.py`` (CLI), the operator
telemetry listener's ``GET /debug/fleet-trace`` (fans out to the
endpoints listed for it), and tests.  The journey export format is
documented in docs/OBSERVABILITY.md — it doubles as the replayable
traffic trace ROADMAP item 3's offline planner consumes.
"""

from __future__ import annotations

import json
import urllib.request


def fetch_json(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def fetch_source(name: str, base_url: str, kind: str = "replica",
                 timeout: float = 10.0) -> dict:
    """One component's trace + clock anchor.

    ``kind`` is ``"router"`` (native router admin surface) or
    ``"replica"`` (server ``/debug/*``).  Raises on unreachable
    endpoints — a stitcher silently dropping a component would present a
    partial story as the whole one.  The chrome payloads carry their
    ``started_unix`` anchor top-level; the raw-ring/snapshot endpoint is
    fetched only as a fallback for older components, so a stitch does
    not download a potentially multi-MB ring twice.
    """
    base = base_url.rstrip("/")
    if kind == "router":
        trace = fetch_json(f"{base}/router/debug/trace?format=chrome", timeout)
        anchor = trace.get("started_unix")
        if anchor is None:
            anchor = fetch_json(
                f"{base}/router/debug/requests", timeout
            )["started_unix"]
    else:
        trace = fetch_json(f"{base}/debug/trace?format=chrome", timeout)
        anchor = trace.get("started_unix")
        if anchor is None:
            anchor = fetch_json(f"{base}/debug/engine", timeout)[
                "started_unix"
            ]
    return {
        "name": name,
        "kind": kind,
        "trace": trace,
        "started_unix": float(anchor),
    }


def stitch_chrome_traces(sources: list[dict]) -> dict:
    """Merge fetched sources (see :func:`fetch_source`) into one Chrome
    trace on a common timeline.

    Each source becomes its own ``pid`` (1-based, in input order) with a
    ``process_name`` metadata event named after the source, its events
    shifted onto the earliest source's clock.  ``tid`` values are left
    alone — they are already scoped per process in the trace format.
    """
    if not sources:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(float(s["started_unix"]) for s in sources)
    out: list[dict] = []
    for pid, src in enumerate(sources, start=1):
        shift_us = int((float(src["started_unix"]) - base) * 1e6)
        named = False
        for ev in src["trace"].get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") != "M":
                ev["ts"] = int(ev.get("ts", 0)) + shift_us
            elif ev.get("name") == "process_name":
                # One process per component, named by the stitcher so
                # two replicas don't both render as "tpumlops-engine".
                ev["args"] = {"name": str(src.get("name") or f"pid {pid}")}
                named = True
            out.append(ev)
        if not named:
            out.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": str(src.get("name") or f"pid {pid}")},
                }
            )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def filter_request(trace: dict, request_id: str) -> dict:
    """Reduce a stitched trace to one request's span tree (metadata
    events kept so the track names survive)."""
    keep = []
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M":
            keep.append(ev)
            continue
        rid = ev.get("id") or (ev.get("args") or {}).get("request_id")
        if rid is not None and str(rid) == request_id:
            keep.append(ev)
    return {"traceEvents": keep, "displayTimeUnit": "ms"}


def request_ids_by_pid(trace: dict) -> dict[int, set]:
    """``{pid: {request ids}}`` over a stitched trace — the coherence
    check the e2e uses: a propagated id must appear under the router's
    pid AND every replica pid that served one of its legs."""
    out: dict[int, set] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M":
            continue
        rid = ev.get("id") or (ev.get("args") or {}).get("request_id")
        if rid is None:
            continue
        out.setdefault(int(ev.get("pid", 0)), set()).add(str(rid))
    return out


def fleet_trace(source_specs: list[dict], timeout: float = 10.0) -> dict:
    """Fetch + stitch in one call.  ``source_specs`` entries carry
    ``name``, ``base_url``, and optional ``kind`` (default replica)."""
    sources = [
        fetch_source(
            str(spec["name"]),
            str(spec["base_url"]),
            str(spec.get("kind", "replica")),
            timeout,
        )
        for spec in source_specs
    ]
    return stitch_chrome_traces(sources)
