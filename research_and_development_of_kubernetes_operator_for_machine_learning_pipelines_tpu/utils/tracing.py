"""Tracing and profiling (SURVEY §5: absent in the reference — added here).

Two layers:

- lightweight spans for the control plane: ``span("reconcile")`` records
  wall-time stats per name (count/total/max), queryable for logs or export —
  promotion-loop step timing the reference never had;
- JAX profiler hooks for the data plane: ``jax_profile(dir)`` wraps
  ``jax.profiler.trace`` so a server can capture XLA/TPU traces on demand
  (e.g. via a debug endpoint), and ``annotate`` marks named regions that
  show up on the TPU timeline.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from dataclasses import dataclass


@dataclass
class SpanStats:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class Tracer:
    def __init__(self):
        self._stats: dict[str, SpanStats] = defaultdict(SpanStats)
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._stats[name].observe(dt)

    def stats(self) -> dict[str, SpanStats]:
        """Point-in-time snapshot.  The values are COPIES taken under the
        lock: handing out the live mutable ``SpanStats`` let ``report()``
        read torn counts mid-``observe`` (count bumped, total_s not yet)."""
        with self._lock:
            return {
                name: SpanStats(s.count, s.total_s, s.max_s)
                for name, s in self._stats.items()
            }

    def as_dict(self) -> dict[str, dict]:
        """JSON-ready stats (the ``/debug/spans`` payload shape on both
        the server and the operator's metrics listener)."""
        return {
            name: {
                "count": s.count,
                "total_s": round(s.total_s, 6),
                "mean_ms": round(s.mean_s * 1e3, 3),
                "max_ms": round(s.max_s * 1e3, 3),
            }
            for name, s in sorted(self.stats().items())
        }

    def report(self) -> str:
        lines = []
        for name, s in sorted(self.stats().items()):
            lines.append(
                f"{name}: n={s.count} mean={s.mean_s*1e3:.2f}ms "
                f"max={s.max_s*1e3:.2f}ms total={s.total_s:.3f}s"
            )
        return "\n".join(lines)


GLOBAL_TRACER = Tracer()
span = GLOBAL_TRACER.span


@contextlib.contextmanager
def jax_profile(log_dir: str):
    """Capture a JAX/XLA profile (TensorBoard format) for the duration."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region on the device timeline (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
