"""TPU-native ML deployment framework.

A ground-up rebuild of the capabilities of the reference MLflow->Seldon
Kubernetes operator (see SURVEY.md), designed TPU-first:

- ``operator``  -- the control plane: a level-triggered reconciler that watches
  ``MlflowModel`` custom resources, resolves MLflow registry aliases to model
  versions, and runs metric-gated canary rollouts with resumable promotion
  state and rollback-on-SLO-breach.  (Reference behavior:
  ``mlflow_operator.py:26-361``; rebuilt as a state machine, not a poll loop.)
- ``server``    -- the data plane the reference outsourced to Seldon's
  ``MLFLOW_SERVER`` image: a first-party JAX/XLA inference server that
  jit/pjit-compiles model predict functions and serves the V2 (kfserving)
  protocol from TPU node pools, exporting Seldon-compatible Prometheus
  metrics.
- ``models``    -- the model zoo backing the baseline configs: linear/iris,
  tabular, ResNet-50, BERT-base, Llama-2 (tensor-parallel over v5e-8).
- ``ops``       -- Pallas TPU kernels (flash attention, rmsnorm, ring
  attention) with XLA fallbacks.
- ``parallel``  -- device meshes, sharding rules, collectives, multi-host
  initialization.
- ``clients``   -- protocol interfaces + real REST clients + in-memory fakes
  for Kubernetes, the MLflow registry, and Prometheus.

Import as::

    import research_and_development_of_kubernetes_operator_for_machine_learning_pipelines_tpu as rdko
    # or the short alias
    import tpumlops
"""

__version__ = "0.1.0"

# Subpackages are imported lazily so that the pure control-plane core can be
# used without pulling in jax (and vice versa).
_SUBPACKAGES = (
    "operator",
    "clients",
    "server",
    "models",
    "ops",
    "parallel",
    "utils",
)


def __getattr__(name):
    if name in _SUBPACKAGES:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBPACKAGES))
