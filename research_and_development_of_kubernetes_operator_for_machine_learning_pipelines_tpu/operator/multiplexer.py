"""Multi-model multiplexer: bin-packs N CRs onto a shared warm pool.

ROADMAP item 4, λScale/Cicada-style serverless serving: one CR per model
wastes chips on the long tail of rarely-hit models — most hold a whole
replica for near-zero traffic.  This module closes that gap by treating
warm-pool replicas (PR 11: booted, compile-swept, NO weights until
``POST /admin/attach``) as a *shared* substrate: every ``MlflowModel``
that names the same ``spec.multiplex.poolRef`` competes for the pool's
replicas by observed traffic, and the packer swaps models in seconds via
snapshot restore instead of holding one pod per model forever.

Division of labor (same shape as the autoscaler):

- :func:`plan` is a **pure function** of (pool, models, replicas, wall):
  score each model ``weight × (parked + queue_depth)``, rank, keep every
  attachment already serving a winner (minimal moves — a convergence
  pass over a settled pool emits NOTHING), assign the remaining winners
  to empty replicas first and lowest-scored losers last.  A model with
  zero traffic holds no replica: its requests park at the router, and
  the parked gauge's ``model`` label is exactly the wake signal that
  puts it back in the ranking next pass.
- :class:`Multiplexer` owns the pool-level I/O: refresh observations
  (router parked breakdown + ``/readyz`` attached-model reports),
  execute the plan's moves through the *existing* warm-pool admin
  endpoint, and buffer the resulting :class:`MuxRecord`\\ s per model so
  each CR's reconciler journals its own slice into ``status.history`` /
  ``/debug/rollouts``.  The reconciler drives it (``_multiplex_step``
  pumps the shared coordinator), so the control loop stays: observe →
  plan → execute → journal.

Safety comes from the server's attach identity contract: an attach of
the uri+snapshot-hash already on the device is an idempotent no-op (the
packer can re-emit its plan every pass), and a geometry-incompatible
replace is a typed 409 that leaves the attached model serving.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Sequence

from .rollout_recorder import _iso

# Typed reasons on hold/error MuxRecords ("why did this model not get
# (or keep) a replica"), mirrored by the ``action`` label on
# tpumlops_operator_mux_moves.
HOLD_POOL_FULL = "pool_full"
ERR_ATTACH_FAILED = "attach_failed"


@dataclass(frozen=True)
class MuxModel:
    """One multiplexed model as the packer observes it."""

    name: str  # CR / model id (the router's model key)
    uri: str  # artifact URI the pool attaches (snapshot-keyed)
    weight: float = 1.0  # spec.multiplex.weight: packer bias
    parked: int = 0  # router park-buffer entries for this model
    queue_depth: float = 0.0  # engine queue depth where it serves

    @property
    def score(self) -> float:
        """Traffic pressure: what the packer ranks by.  Zero = the
        model holds nothing (scale-to-zero is the default state)."""
        return self.weight * (self.parked + self.queue_depth)


@dataclass(frozen=True)
class MuxReplica:
    """One shared warm-pool replica and what it currently holds."""

    name: str
    url: str = ""  # admin base url, e.g. http://127.0.0.1:9001
    attached_uri: str | None = None  # /readyz attached-model report


@dataclass(frozen=True)
class MuxRecord:
    """One packer decision, journaled beside gate/scale records
    (``kind: "mux"``) so a swap ladder is reconstructable from
    ``status.history`` or ``GET /debug/rollouts`` alone."""

    wall: float
    action: str  # "attach" | "replace" | "noop" | "hold" | "error"
    pool: str = ""
    model: str = ""
    model_uri: str = ""
    replica: str | None = None  # None on holds (no replica involved)
    displaced: str | None = None  # uri a replace evicted
    reason: str = ""
    score: float = 0.0
    parked: int = 0
    snapshot_hash: str | None = None  # echoed by the attach endpoint

    def as_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "kind": "mux",
            "ts": self.wall,
            "time": _iso(self.wall),
            "action": self.action,
            "pool": self.pool,
            "model": self.model,
            "modelUri": self.model_uri,
            "reason": self.reason,
            "score": self.score,
            "parked": self.parked,
        }
        # Optional keys omitted — not nulled — so hold records stay as
        # compact as autoscaler holds.
        if self.replica is not None:
            out["replica"] = self.replica
        if self.displaced is not None:
            out["displaced"] = self.displaced
        if self.snapshot_hash is not None:
            out["snapshotHash"] = self.snapshot_hash
        return out


@dataclass(frozen=True)
class MuxMove:
    """One attach/replace the plan wants executed."""

    replica: MuxReplica
    model: MuxModel
    replace: bool
    displaced: str | None  # uri being evicted (None on empty replica)


@dataclass(frozen=True)
class MuxPlan:
    pool: str
    moves: tuple = ()
    holds: tuple = ()  # MuxRecords for wanted-but-unplaced models

    @property
    def converged(self) -> bool:
        return not self.moves


def plan(
    pool: str,
    models: Sequence[MuxModel],
    replicas: Sequence[MuxReplica],
    wall: float,
    stragglers: frozenset = frozenset(),
) -> MuxPlan:
    """Pure bin-pack pass: who should hold what, expressed as moves.

    Minimal-move by construction: a replica already serving a winner is
    never touched, so re-running the plan against a settled pool yields
    zero moves (and the attach endpoint's idempotent no-op backstops
    even a re-emitted one).  Ties rank by name for determinism.

    ``stragglers`` (anomaly-observatory verdicts, operator/anomaly.py)
    demotes the named replicas to LAST choice as attach targets: a
    model newly winning capacity should not land on the pool's slowest
    device.  The empty set leaves every decision byte-identical.
    """
    ranked = sorted(
        (m for m in models if m.score > 0),
        key=lambda m: (-m.score, m.name),
    )
    winners = ranked[: len(replicas)]
    winner_uris = {m.uri for m in winners}
    score_by_uri = {m.uri: m.score for m in models}
    satisfied = {
        r.attached_uri for r in replicas if r.attached_uri in winner_uris
    }
    # Free list: healthy replicas before stragglers, then empty replicas
    # first, then losers cheapest-first (evict the attachment with the
    # least traffic behind it).
    free = sorted(
        (r for r in replicas if r.attached_uri not in winner_uris),
        key=lambda r: (
            r.name in stragglers,
            r.attached_uri is not None,
            score_by_uri.get(r.attached_uri, 0.0),
            r.name,
        ),
    )
    moves = []
    holds = []
    for m in winners:
        if m.uri in satisfied:
            continue
        if not free:
            # Cannot happen with distinct uris (|winners| <= |replicas|)
            # but two CRs sharing one uri make it reachable; journal it.
            holds.append(
                MuxRecord(
                    wall=wall, action="hold", pool=pool, model=m.name,
                    model_uri=m.uri, reason=HOLD_POOL_FULL,
                    score=m.score, parked=m.parked,
                )
            )
            continue
        r = free.pop(0)
        moves.append(
            MuxMove(
                replica=r,
                model=m,
                replace=r.attached_uri is not None,
                displaced=r.attached_uri,
            )
        )
    for m in ranked[len(replicas):]:
        holds.append(
            MuxRecord(
                wall=wall, action="hold", pool=pool, model=m.name,
                model_uri=m.uri, reason=HOLD_POOL_FULL,
                score=m.score, parked=m.parked,
            )
        )
    return MuxPlan(pool=pool, moves=tuple(moves), holds=tuple(holds))


def http_attach(
    replica: MuxReplica,
    model_uri: str,
    replace: bool,
    wake_start_wall: float,
    timeout_s: float = 300.0,
) -> dict:
    """Default transport: the existing warm-pool admin endpoint."""
    body = json.dumps(
        {
            "model_uri": model_uri,
            "replace": replace,
            "wake_start_wall": wake_start_wall,
        }
    ).encode()
    req = urllib.request.Request(
        f"{replica.url}/admin/attach",
        data=body,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def http_ready(replica: MuxReplica, timeout_s: float = 5.0) -> dict:
    """Attached-model report: GET /readyz (any lifecycle state)."""
    try:
        with urllib.request.urlopen(
            f"{replica.url}/readyz", timeout=timeout_s
        ) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:  # 503 carries the body too
        try:
            return json.loads(e.read().decode())
        except Exception:
            return {}
    except Exception:
        return {}


class Multiplexer:
    """Pool-level coordinator: observe, plan, execute, buffer records.

    One instance per shared pool, shared by every member CR's
    reconciler (each pumps it; a min-interval gate keeps N members from
    N-folding the convergence rate).  All I/O seams are injected:
    ``attach`` executes a move (default: HTTP against the replica's
    admin endpoint), ``ready`` refreshes a replica's attached-model
    report, ``parked`` returns the router's per-model parked breakdown
    (``RouterAdmin.parked()["models"]``).
    """

    def __init__(
        self,
        pool: str,
        replicas: Sequence[MuxReplica] = (),
        attach: Callable[..., dict] | None = None,
        ready: Callable[[MuxReplica], dict] | None = None,
        parked: Callable[[], Mapping[str, int]] | None = None,
        min_interval_s: float = 0.0,
        wall: Callable[[], float] = time.time,
        on_move: Callable[[str, str], None] | None = None,  # (model, action)
    ):
        self.pool = pool
        self.replicas: list[MuxReplica] = list(replicas)
        self._attach = attach or http_attach
        self._ready = ready or http_ready
        self._parked = parked
        self._min_interval_s = float(min_interval_s)
        self._wall = wall
        self._on_move = on_move
        self._lock = threading.Lock()
        self._members: dict[str, MuxModel] = {}
        self._pending: dict[str, list[MuxRecord]] = {}
        self._last_pass = 0.0
        self.moves_total = 0
        # Straggler verdicts from the anomaly observatory (reconciler
        # _anomaly_step): replica names to treat as last-choice attach
        # targets.  Empty (the default) = byte-identical planning.
        self._stragglers: frozenset = frozenset()

    # -- membership / observation -------------------------------------------

    def register(
        self, name: str, uri: str, weight: float = 1.0
    ) -> None:
        """(Re-)register a member CR; idempotent, called every pump."""
        with self._lock:
            cur = self._members.get(name)
            if cur is not None and cur.uri == uri and cur.weight == weight:
                return
            parked = cur.parked if cur is not None else 0
            depth = cur.queue_depth if cur is not None else 0.0
            self._members[name] = MuxModel(
                name=name, uri=uri, weight=float(weight),
                parked=parked, queue_depth=depth,
            )

    def deregister(self, name: str) -> None:
        with self._lock:
            self._members.pop(name, None)
            self._pending.pop(name, None)

    def set_stragglers(self, names) -> None:
        """Replace the straggler set the next plan will avoid."""
        with self._lock:
            self._stragglers = frozenset(names)

    def observe(
        self,
        parked: Mapping[str, int] | None = None,
        queue_depth: Mapping[str, float] | None = None,
    ) -> None:
        """Fold fresh traffic signals into the member table."""
        with self._lock:
            for name, m in list(self._members.items()):
                new_parked = (
                    int(parked.get(name, 0)) if parked is not None
                    else m.parked
                )
                new_depth = (
                    float(queue_depth.get(name, 0.0))
                    if queue_depth is not None
                    else m.queue_depth
                )
                if new_parked != m.parked or new_depth != m.queue_depth:
                    self._members[name] = MuxModel(
                        name=m.name, uri=m.uri, weight=m.weight,
                        parked=new_parked, queue_depth=new_depth,
                    )

    def refresh_replicas(self) -> None:
        """Re-read every replica's /readyz attached-model report — the
        device is the source of truth, not the packer's memory (a
        replica restarted by the kubelet comes back empty)."""
        fresh = []
        for r in self.replicas:
            body = self._ready(r)
            fresh.append(
                MuxReplica(
                    name=r.name, url=r.url,
                    attached_uri=body.get("model") or None,
                )
            )
        with self._lock:
            self.replicas = fresh

    # -- convergence ----------------------------------------------------------

    def pump(self, force: bool = False) -> list[MuxRecord]:
        """One observe→plan→execute pass (rate-limited); returns the
        records it produced (they are ALSO buffered per model for
        :meth:`take_records`)."""
        now = self._wall()
        with self._lock:
            if not force and now - self._last_pass < self._min_interval_s:
                return []
            self._last_pass = now
            members = list(self._members.values())
        if not members or not self.replicas:
            return []
        if self._parked is not None:
            try:
                self.observe(parked=self._parked())
            except Exception:
                pass  # blind = plan on last observation, same as scaler
            with self._lock:
                members = list(self._members.values())
        self.refresh_replicas()
        with self._lock:
            stragglers = self._stragglers
        p = plan(self.pool, members, self.replicas, now, stragglers)
        records = list(p.holds)
        for mv in p.moves:
            records.append(self._execute(mv, now))
        with self._lock:
            for rec in records:
                self._pending.setdefault(rec.model, []).append(rec)
        return records

    def _execute(self, mv: MuxMove, wall: float) -> MuxRecord:
        action = "replace" if mv.replace else "attach"
        try:
            resp = self._attach(
                mv.replica, mv.model.uri,
                replace=mv.replace, wake_start_wall=wall,
            )
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = str(json.loads(e.read().decode()).get("reason", ""))
            except Exception:
                pass
            return MuxRecord(
                wall=wall, action="error", pool=self.pool,
                model=mv.model.name, model_uri=mv.model.uri,
                replica=mv.replica.name, displaced=mv.displaced,
                reason=f"{ERR_ATTACH_FAILED}:{e.code}"
                + (f":{detail}" if detail else ""),
                score=mv.model.score, parked=mv.model.parked,
            )
        except Exception as e:
            return MuxRecord(
                wall=wall, action="error", pool=self.pool,
                model=mv.model.name, model_uri=mv.model.uri,
                replica=mv.replica.name, displaced=mv.displaced,
                reason=f"{ERR_ATTACH_FAILED}:{e}",
                score=mv.model.score, parked=mv.model.parked,
            )
        if resp.get("noop"):
            action = "noop"
        else:
            self.moves_total += 1
            if self._on_move is not None:
                self._on_move(mv.model.name, action)
        # Commit the packer's view of the replica; the next pass's
        # refresh re-reads the device anyway.
        with self._lock:
            self.replicas = [
                MuxReplica(
                    name=r.name, url=r.url, attached_uri=mv.model.uri
                )
                if r.name == mv.replica.name
                else r
                for r in self.replicas
            ]
        return MuxRecord(
            wall=wall, action=action, pool=self.pool,
            model=mv.model.name, model_uri=mv.model.uri,
            replica=mv.replica.name, displaced=mv.displaced,
            reason="traffic", score=mv.model.score,
            parked=mv.model.parked,
            snapshot_hash=resp.get("snapshot_hash"),
        )

    # -- per-CR surfaces (what _multiplex_step reads) -------------------------

    def take_records(self, model: str) -> list[MuxRecord]:
        """Drain the buffered records for one member CR (its reconciler
        journals them into that CR's status.history)."""
        with self._lock:
            return self._pending.pop(model, [])

    def model_status(self, model: str) -> dict[str, Any]:
        """This member's live pool view for ``status.multiplex``."""
        with self._lock:
            m = self._members.get(model)
            attached = [
                r.name
                for r in self.replicas
                if m is not None and r.attached_uri == m.uri
            ]
            out: dict[str, Any] = {
                "poolReplicas": len(self.replicas),
                "attachedReplicas": attached,
            }
            if m is not None:
                out["parked"] = m.parked
                out["score"] = m.score
            return out
