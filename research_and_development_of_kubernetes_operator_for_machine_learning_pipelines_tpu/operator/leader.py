"""Lease-based leader election: makes ``replicas > 1`` safe.

The reference pins the operator at one replica
(``mlflow-operator-deployment.yaml:7``) and has no election — a second
replica would double-reconcile every CR and race the promotion loops.
This module implements the standard Kubernetes pattern on
``coordination.k8s.io/v1`` Leases (what client-go's leaderelection and
kopf's peering provide) over the same generic object client the operator
already uses, so FakeKube serves tests unchanged:

- acquire: create the Lease, or take it over when expired; optimistic
  concurrency (resourceVersion on replace) makes simultaneous takeovers
  resolve to exactly one winner — the loser sees 409;
- renew: the holder refreshes ``renewTime`` every ``renew_interval_s``;
- step-down: if renewing fails past ``renew_deadline_s`` (strictly less
  than the lease duration, client-go style) the elector reports loss so
  the caller stops reconciling BEFORE any challenger may act on the
  expired lease; SIGTERM additionally releases the lease so successors
  need not wait out the expiry.

The runtime composes, not inherits: ``LeaderElector.run(on_started,
on_stopped)`` brackets ``OperatorRuntime.serve()``.
"""

from __future__ import annotations

import datetime
import logging
import os
import re
import socket
import threading
import uuid

from ..clients.base import ApiError, Conflict, NotFound, ObjectRef
from ..utils.clock import Clock, SystemClock

_log = logging.getLogger(__name__)

LEASE = dict(group="coordination.k8s.io", version="v1", plural="leases")


def _now_iso(clock: Clock) -> str:
    # Lease timestamps are RFC3339 micro-time.  A FakeClock's epoch maps
    # through fromtimestamp so tests stay deterministic.
    return (
        datetime.datetime.fromtimestamp(clock.now(), datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%f")
    ) + "Z"


def _parse_iso(ts: str | None) -> float | None:
    # A renewTime written by another client with no fractional seconds,
    # RFC3339Nano's nine digits, or a numeric UTC offset instead of 'Z'
    # (e.g. ``...+00:00``) must NOT parse to None, or the challenger
    # treats a live lease as takeable and two leaders run concurrently.
    # The fraction is normalized to microseconds by hand: fromisoformat
    # only accepts arbitrary precision (and 'Z') from 3.11 on, and this
    # package supports 3.10.
    if not ts:
        return None
    try:
        s = ts.strip().rstrip("Zz")
        offset_s = 0
        m = re.search(r"([+-])(\d{2}):?(\d{2})$", s)
        if m:
            offset_s = (int(m.group(2)) * 3600 + int(m.group(3)) * 60) * (
                1 if m.group(1) == "+" else -1
            )
            s = s[: m.start()]
        base, frac = s, "0"
        if "." in base:
            base, frac = base.split(".", 1)
            frac = (frac + "000000")[:6]
        dt = datetime.datetime.strptime(base, "%Y-%m-%dT%H:%M:%S")
        return dt.replace(
            microsecond=int(frac), tzinfo=datetime.timezone.utc
        ).timestamp() - offset_s
    except ValueError:
        return None


class LeaderElector:
    def __init__(
        self,
        kube,
        name: str = "tpumlops-operator",
        namespace: str = "tpumlops-system",
        identity: str | None = None,
        lease_duration_s: float = 15.0,
        renew_interval_s: float = 5.0,
        retry_interval_s: float = 2.0,
        renew_deadline_s: float | None = None,
        clock: Clock | None = None,
    ):
        if renew_interval_s >= lease_duration_s:
            raise ValueError(
                f"renew_interval_s ({renew_interval_s}) must be < "
                f"lease_duration_s ({lease_duration_s}) or the lease "
                "expires between renewals"
            )
        # The holder must give up STRICTLY before a challenger may take
        # over (client-go's renewDeadline < leaseDuration): challengers
        # act at renewTime + lease_duration; the holder abandons at
        # last_renew + renew_deadline, one renew interval earlier.
        self.renew_deadline_s = (
            renew_deadline_s
            if renew_deadline_s is not None
            else lease_duration_s - renew_interval_s
        )
        if not (renew_interval_s <= self.renew_deadline_s < lease_duration_s):
            raise ValueError(
                f"renew_deadline_s ({self.renew_deadline_s}) must be in "
                f"[renew_interval_s, lease_duration_s)"
            )
        self.kube = kube
        self.ref = ObjectRef(namespace=namespace, name=name, **LEASE)
        self.identity = identity or (
            f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        )
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s
        self.retry_interval_s = retry_interval_s
        self.clock = clock or SystemClock()
        self.is_leader = False
        self._stop = threading.Event()

    # -- lease mechanics -----------------------------------------------------

    def _lease_body(self, prior: dict | None) -> dict:
        spec_prior = (prior or {}).get("spec") or {}
        transitions = int(spec_prior.get("leaseTransitions") or 0)
        if spec_prior.get("holderIdentity") not in (None, self.identity):
            transitions += 1
        now = _now_iso(self.clock)
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {
                "name": self.ref.name,
                "namespace": self.ref.namespace,
            },
            "spec": {
                "holderIdentity": self.identity,
                # ceil: the API field is an integer; truncation would
                # advertise 0 for sub-second (test) durations, which
                # reads as an expired lease.
                "leaseDurationSeconds": max(1, int(-(-self.lease_duration_s // 1))),
                "acquireTime": (
                    spec_prior.get("acquireTime")
                    if spec_prior.get("holderIdentity") == self.identity
                    else now
                )
                or now,
                "renewTime": now,
                "leaseTransitions": transitions,
            },
        }
        if prior is not None:
            body["metadata"]["resourceVersion"] = (
                prior.get("metadata") or {}
            ).get("resourceVersion")
        return body

    def try_acquire_or_renew(self) -> bool:
        """One election round.  Returns True iff we hold the lease now.

        Never raises: a transport blip or API 5xx is a failed round
        (False), handled by the renew-deadline grace in ``_hold`` —
        crashing the election loop on the first flaky read would take
        the whole operator down with it.
        """
        try:
            return self._acquire_or_renew_once()
        except Exception as e:
            _log.warning("leader election round failed: %s", e)
            return False

    def _acquire_or_renew_once(self) -> bool:
        try:
            lease = self.kube.get(self.ref)
        except NotFound:
            try:
                self.kube.create(self.ref, self._lease_body(None))
                return True
            except (Conflict, ApiError):
                return False  # someone else created it first
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        if holder == "":
            pass  # explicitly released (see release()): take immediately
        elif holder not in (None, self.identity):
            renew = _parse_iso(spec.get("renewTime"))
            raw_duration = spec.get("leaseDurationSeconds")
            # 0 is meaningful (a released lease) — `or` would eat it.
            duration = float(
                self.lease_duration_s if raw_duration is None else raw_duration
            )
            if renew is not None and self.clock.now() < renew + duration:
                return False  # held and fresh
            # expired: fall through and try to take it over
        try:
            self.kube.replace(self.ref, self._lease_body(lease))
            return True
        except (Conflict, NotFound):
            return False  # lost the takeover race
        except ApiError:
            return False

    # -- lifecycle -----------------------------------------------------------

    def run(self, on_started, on_stopped) -> None:
        """Block until stopped: wait for leadership, hold it, step down.

        ``on_started()`` runs when leadership is gained (typically starts
        the runtime's serve loop on this thread's behalf);
        ``on_stopped()`` runs when leadership is lost or ``stop()`` is
        called.  If renewals keep failing past the lease duration we
        step down proactively — a new leader may already be running.
        """
        while not self._stop.is_set():
            if self.try_acquire_or_renew():
                _log.info("leader election: %s acquired the lease", self.identity)
                self.is_leader = True
                try:
                    on_started()
                    self._hold()
                finally:
                    self.is_leader = False
                    _log.warning(
                        "leader election: %s stepping down", self.identity
                    )
                    on_stopped()
            else:
                self._stop.wait(self.retry_interval_s)

    def _hold(self) -> None:
        """Renew until stop or sustained failure.

        Abandons at ``renew_deadline_s`` after the last successful renew
        — strictly before challengers may act on the expired lease, so
        two leaders never reconcile concurrently (modulo clock skew
        beyond one renew interval, the standard Lease caveat).
        """
        last_renew = self.clock.now()
        while not self._stop.is_set():
            self._stop.wait(self.renew_interval_s)
            if self._stop.is_set():
                return
            if self.try_acquire_or_renew():
                last_renew = self.clock.now()
            elif self.clock.now() - last_renew >= self.renew_deadline_s:
                _log.error(
                    "leader election: renewals failing for >= %.0fs "
                    "(deadline); stepping down before the lease expires",
                    self.renew_deadline_s,
                )
                return

    def release(self) -> None:
        """Best-effort lease release (SIGTERM path): zero out renewTime so
        a successor's expiry check passes immediately instead of waiting
        out the remaining lease duration on every rolling update."""
        try:
            lease = self.kube.get(self.ref)
        except Exception:
            return
        if ((lease.get("spec") or {}).get("holderIdentity")) != self.identity:
            return  # not ours to release
        body = self._lease_body(lease)
        # Duration 0 is expired under ANY clock (now < renew + 0 is never
        # true) — epoch-zero renewTime would not be, e.g. for a FakeClock
        # still at time 0.
        body["spec"]["leaseDurationSeconds"] = 0
        body["spec"]["holderIdentity"] = ""
        try:
            self.kube.replace(self.ref, body)
            _log.info("leader election: lease released")
        except Exception as e:
            _log.warning("lease release failed (successor waits expiry): %s", e)

    def stop(self) -> None:
        self._stop.set()
