"""Deployment manifest construction.

Two backends:

- ``seldon`` — byte-compatible with the reference's SeldonDeployment shape
  (``mlflow_operator.py:193-238``): ``MLFLOW_SERVER`` graph nodes, protocol
  ``kfserving``, predictor names ``v<version>``, weighted ``traffic``.
- ``tpu``    — the north-star first-party data plane: each predictor is our
  JAX/XLA inference server (``server/``) pinned to a TPU node pool via
  nodeSelector/tolerations, with mesh shape and topology passed through the
  container environment.  The Seldon CR shape (predictor list + traffic
  weights + Istio split) is retained so the promotion loop and metric
  identity (``deployment_name``/``predictor_name``/``namespace``,
  ``mlflow_operator.py:367``) are unchanged.

Owner references (``:158-169``) make the cluster GC the deployment when the
``MlflowModel`` CR is deleted.
"""

from __future__ import annotations

import json
from typing import Any, Mapping

from ..utils.config import OperatorConfig, TpuSpec, TPU_TOPOLOGIES

SELDON_API_VERSION = "machinelearning.seldon.io/v1"
MLFLOWMODEL_API_VERSION = "mlflow.nizepart.com/v1alpha1"


def owner_reference(name: str, uid: str) -> list[dict[str, Any]]:
    """Reference ``mlflow_operator.py:162-169``."""
    return [
        {
            "apiVersion": MLFLOWMODEL_API_VERSION,
            "kind": "MlflowModel",
            "name": name,
            "uid": uid,
            "controller": True,
            "blockOwnerDeletion": True,
        }
    ]


def _seldon_predictor(
    version: str,
    model_uri: str,
    traffic: int,
    config: OperatorConfig,
    replicas: int | None = None,
) -> dict[str, Any]:
    """Reference-parity predictor (``mlflow_operator.py:195-222``).

    ``replicas`` is the autoscaler's override (None — the default — keeps
    the reference's fixed 1, byte-for-byte)."""
    return {
        "graph": {
            "name": f"classifier-{version}",
            "implementation": "MLFLOW_SERVER",
            "modelUri": model_uri,
            "envSecretRefName": config.minio_secret,
            "children": [],
        },
        "name": f"v{version}",
        "replicas": 1 if replicas is None else int(replicas),
        "traffic": traffic,
    }


COORDINATOR_PORT = 8476  # jax.distributed coordinator (leader pod)


def worker_unit_name(deployment_name: str, version: str) -> str:
    """Name of the pod unit (and its headless Service) for one predictor."""
    return f"{deployment_name}-v{version}-workers"


def _topology_info(config: OperatorConfig):
    info = TPU_TOPOLOGIES.get(config.tpu.topology)
    if info is None:
        raise ValueError(
            f"unknown tpuTopology {config.tpu.topology!r}; "
            f"known: {sorted(TPU_TOPOLOGIES)}"
        )
    return info


def _tpu_pod_spec(
    version: str,
    model_uri: str,
    config: OperatorConfig,
    deployment_name: str,
    namespace: str,
) -> dict[str, Any]:
    """Pod spec for one host of a TPU predictor (shared by the predictor's
    componentSpecs and the multi-host StatefulSet template)."""
    tpu: TpuSpec = config.tpu
    info = _topology_info(config)
    accelerator, gke_topology = info.accelerator, info.gke_topology
    container = {
        "name": f"tpu-server-{version}",
        "image": config.server_image,
        "args": [
            "--model-uri", model_uri,
            "--model-name", config.model_name,
            "--predictor-name", f"v{version}",
            "--deployment-name", deployment_name,
            "--namespace", namespace,
            "--mesh-shape", json.dumps(dict(tpu.mesh_shape)),
            "--dtype", tpu.dtype,
            "--max-batch-size", str(tpu.max_batch_size),
            "--max-batch-delay-ms", str(tpu.max_batch_delay_ms),
            "--compile-cache-dir", tpu.compile_cache_dir or "",
            "--quantize", tpu.quantize,
            "--prefill-chunk", str(tpu.prefill_chunk or 0),
            "--prefill-batch", str(tpu.prefill_batch),
            "--prefill-token-budget", str(tpu.prefill_token_budget),
            "--sp-prefill-threshold", str(tpu.sp_prefill_threshold),
            "--prefix-cache", "1" if tpu.prefix_cache.enabled else "0",
            "--prefix-cache-budget-mb", str(tpu.prefix_cache.budget_mb),
            "--prefix-cache-chunk", str(tpu.prefix_cache.chunk_tokens),
            "--speculative", "1" if tpu.speculative.enabled else "0",
            "--speculative-draft-tokens", str(tpu.speculative.draft_tokens),
            "--speculative-ngram-min", str(tpu.speculative.ngram_min),
            "--speculative-ngram-max", str(tpu.speculative.ngram_max),
            "--speculative-adaptive", "1" if tpu.speculative.adaptive else "0",
            "--trace-ring", str(tpu.observability.trace_ring),
        ],
        "env": [
            {"name": "TPU_TOPOLOGY", "value": tpu.topology},
            {"name": "JAX_PLATFORMS", "value": "tpu"},
            {
                "name": "JAX_COMPILATION_CACHE_DIR",
                "value": tpu.compile_cache_dir or "",
            },
        ],
        "ports": [
            {"name": "http", "containerPort": 9000},
            {"name": "metrics", "containerPort": 6000},
        ],
        "resources": {
            # per-host request: a multi-host slice schedules hosts pods of
            # chips_per_host each, not one pod asking for the whole slice
            "limits": {"google.com/tpu": str(info.chips_per_host)},
            "requests": {"google.com/tpu": str(info.chips_per_host)},
        },
        "readinessProbe": {
            "httpGet": {"path": "/v2/health/ready", "port": 9000},
            # TPU cold-start: first jit compile can take tens of seconds;
            # generous window so a canary isn't killed mid-compile
            # (SURVEY §7 hard part 3).
            "initialDelaySeconds": 10,
            "periodSeconds": 5,
            "failureThreshold": 60,
        },
    }
    # Admission-control / drain flags are appended ONLY when non-default:
    # unlike the always-emitted knobs above, these arrived after PR 7 and
    # an unannotated CR's manifest must stay byte-for-byte identical.
    if tpu.decode_steps != 1:
        # Appended only when fused decode is on (same byte-identity
        # contract as the admission/drain flags): an unannotated CR's
        # manifest must stay byte-for-byte what it was.
        container["args"] += ["--decode-steps", str(tpu.decode_steps)]
    if tpu.unified_step:
        # Unified ragged super-step engine. Emitted only when true —
        # same byte-identity contract: an unannotated CR's manifest (and
        # the unifiedStep: false default) keeps the legacy split-program
        # engine byte-for-byte.
        container["args"] += ["--unified-step", "1"]
    if tpu.admission_queue_budget > 0:
        container["args"] += [
            "--admission-queue-budget", str(tpu.admission_queue_budget),
        ]
    if tpu.drain_grace_s != 20.0:
        container["args"] += [
            "--drain-grace-seconds", str(tpu.drain_grace_s),
        ]
    if tpu.prefix_cache.l2_budget_mb > 0:
        # Host-RAM second prefix-cache tier. Appended only when a budget
        # is set — same byte-identity contract as the flags above.
        container["args"] += [
            "--prefix-cache-l2-budget-mb", str(tpu.prefix_cache.l2_budget_mb),
        ]
    if tpu.slo_class:
        # Priority admission classes (spec.sloClass). Appended only when
        # a default class is set — same byte-identity contract.
        container["args"] += ["--slo-class", tpu.slo_class]
    if tpu.preemption:
        # Mid-decode preemption of lower-class slots. Appended only when
        # enabled — same byte-identity contract.
        container["args"] += ["--preemption", "1"]
    if tpu.observability.device_telemetry:
        # Appended only when enabled (same byte-identity contract as the
        # admission/drain flags): an unannotated CR's manifest must stay
        # byte-for-byte what it was before the device telemetry layer.
        container["args"] += ["--device-telemetry", "1"]
    if tpu.observability.timeseries_ring > 0:
        # Per-second serving time-series ring (the anomaly detector's
        # input plane).  Appended only when sized — same byte-identity
        # contract.
        container["args"] += [
            "--timeseries-ring", str(tpu.observability.timeseries_ring)
        ]
    if tpu.snapshot.enabled:
        # Pre-baked weight snapshots (scale-to-zero fast restore).
        # Appended only when enabled — same byte-identity contract.  The
        # snapshot dir is node-local like the XLA cache: a woken pod on
        # the same host restores without re-downloading or re-quantizing.
        container["args"] += ["--snapshot-dir", tpu.snapshot.dir]
    if info.hosts > 1:
        unit = worker_unit_name(deployment_name, version)
        container["env"] += [
            # pod 0 of the indexed unit hosts the jax.distributed
            # coordinator; its stable DNS name comes from the headless
            # Service the materializer creates for the unit
            {
                "name": "JAX_COORDINATOR_ADDRESS",
                "value": f"{unit}-0.{unit}.{namespace}.svc.cluster.local:{COORDINATOR_PORT}",
            },
            {"name": "JAX_NUM_PROCESSES", "value": str(info.hosts)},
            # pod index -> JAX process id (k8s >=1.28 sets this label on
            # StatefulSet/indexed-Job pods)
            {
                "name": "JAX_PROCESS_ID",
                "valueFrom": {
                    "fieldRef": {
                        "fieldPath": "metadata.labels['apps.kubernetes.io/pod-index']"
                    }
                },
            },
        ]
    if config.minio_secret:
        container["envFrom"] = [{"secretRef": {"name": config.minio_secret}}]
    pod: dict[str, Any] = {}
    if tpu.drain_grace_s != 20.0:
        # The drain is only lossless if kubelet lets it finish: pod
        # termination grace must cover the endpoint-removal lag (3s
        # --drain-s default) + the in-flight drain window + margin, or
        # Kubernetes' default 30s grace SIGKILLs the server mid-drain
        # and drops exactly the requests the protocol exists to save.
        # Emitted only alongside the non-default flag (byte-identity).
        pod["terminationGracePeriodSeconds"] = int(tpu.drain_grace_s) + 15
    if tpu.compile_cache_dir:
        # Node-local persistent XLA cache (SURVEY §7 hard part 3): hostPath
        # outlives the pod, so a rescheduled canary — or the *other* version's
        # pod on the same TPU host — warms up from deserialized executables
        # instead of recompiling, keeping time-to-ready off the latency gate.
        container["volumeMounts"] = [
            {"name": "xla-cache", "mountPath": tpu.compile_cache_dir}
        ]
        pod["volumes"] = [
            {
                "name": "xla-cache",
                "hostPath": {
                    "path": "/var/cache/tpumlops/xla",
                    "type": "DirectoryOrCreate",
                },
            }
        ]
    if tpu.snapshot.enabled:
        # Snapshot store survives the pod the same way the XLA cache
        # does — a wake-from-zero on the same node restores locally.
        container.setdefault("volumeMounts", []).append(
            {"name": "weight-snapshots", "mountPath": tpu.snapshot.dir}
        )
        pod.setdefault("volumes", []).append(
            {
                "name": "weight-snapshots",
                "hostPath": {
                    "path": "/var/cache/tpumlops/snapshots",
                    "type": "DirectoryOrCreate",
                },
            }
        )
    return {
        **pod,
        "nodeSelector": {
            "cloud.google.com/gke-tpu-accelerator": accelerator,
            "cloud.google.com/gke-tpu-topology": gke_topology,
        },
        "tolerations": [
            {
                "key": "google.com/tpu",
                "operator": "Exists",
                "effect": "NoSchedule",
            }
        ],
        "containers": [container],
    }


def _tpu_predictor(
    version: str,
    model_uri: str,
    traffic: int,
    config: OperatorConfig,
    deployment_name: str,
    namespace: str,
    replicas: int | None = None,
) -> dict[str, Any]:
    """First-party TPU predictor: our JAX server on a v5e node pool.

    Multi-host topologies (SURVEY §7 hard part 5) make one predictor =
    ``hosts`` pods run as an indexed StatefulSet behind a headless Service
    (see ``build_worker_unit_manifests`` — the reconciler applies those
    alongside this routing manifest): pod index = JAX process id, pod 0 is
    the coordinator *and* the only pod routed traffic reaches (followers
    run the lockstep loop in ``server/multihost.py``).
    """
    info = _topology_info(config)
    predictor: dict[str, Any] = {
        "graph": {
            "name": f"tpu-server-{version}",
            "implementation": "TRITON_SERVER",  # pre-packaged V2-protocol slot
            "type": "MODEL",
            "modelUri": model_uri,
            "children": [],
        },
        "name": f"v{version}",
        # data-parallel copies of the predictor — DP in SURVEY §2.3's
        # inventory (single-host only; multi-host units reject replicas>1
        # at config parse).  ``replicas`` is the autoscaler's live count
        # (None = spec.tpu.replicas, byte-for-byte the fixed topology).
        "replicas": (
            config.tpu.replicas if replicas is None else int(replicas)
        ),
        "traffic": traffic,
    }
    if info.hosts > 1:
        # Routing-only predictor: NO componentSpecs, or a Seldon controller
        # consuming this CR would materialize a second copy of the pods the
        # operator's StatefulSet already owns (and Deployment pods lack the
        # pod-index label the env fieldRef needs).  The pod spec lives in
        # build_worker_unit_manifests' StatefulSet template instead.
        predictor["tpuWorkerUnit"] = {
            "name": worker_unit_name(deployment_name, version),
            "hosts": info.hosts,
            "chipsPerHost": info.chips_per_host,
            "coordinatorPort": COORDINATOR_PORT,
            # the routed Service must select only pod index 0: followers
            # serve health but no inference frontend, and sending them
            # traffic would split the unit's metrics identity
            "serviceSelectorExtra": {
                "apps.kubernetes.io/pod-index": "0",
            },
        }
    else:
        predictor["componentSpecs"] = [
            {
                "spec": _tpu_pod_spec(
                    version, model_uri, config, deployment_name, namespace
                )
            }
        ]
    return predictor


def build_worker_unit_manifests(
    name: str,
    namespace: str,
    owner_uid: str,
    config: OperatorConfig,
    version: str,
    model_uri: str,
) -> list[dict[str, Any]]:
    """First-party materialization of one multi-host predictor unit.

    The reference outsources pod creation to Seldon's controller; a
    multi-host TPU slice is beyond what that controller models (N pods =
    one predictor), so for ``hosts > 1`` the *operator* owns the unit:

    - a headless Service giving every pod a stable DNS name (the
      coordinator address baked into the pod env resolves to pod-0);
    - a routed Service selecting pod index 0 only — the leader owns the
      HTTP frontend, so Istio/router traffic weights keep meaning
      "percent of requests to this unit" and metric identity stays keyed
      by one predictor name;
    - an indexed StatefulSet (``podManagementPolicy: Parallel`` — pods
      must start together because ``jax.distributed.initialize`` blocks
      until all N processes join; OrderedReady would deadlock pod-0's
      readiness against pods that don't exist yet).

    Returns ``[]`` for single-host topologies (Seldon-shaped componentSpecs
    cover those).
    """
    info = _topology_info(config)
    if info.hosts <= 1:
        return []
    unit = worker_unit_name(name, version)
    labels = {
        "app": unit,
        "tpumlops/deployment": name,
        "tpumlops/predictor": f"v{version}",
    }
    owner = owner_reference(name, owner_uid)
    pod_spec = _tpu_pod_spec(version, model_uri, config, name, namespace)
    headless = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": unit,
            "namespace": namespace,
            "labels": labels,
            "ownerReferences": owner,
        },
        "spec": {
            "clusterIP": "None",
            "selector": {"app": unit},
            # publish addresses before readiness so the coordinator DNS
            # name resolves while the process group is still forming
            "publishNotReadyAddresses": True,
            "ports": [{"name": "coordinator", "port": COORDINATOR_PORT}],
        },
    }
    routed = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{name}-v{version}",
            "namespace": namespace,
            "labels": labels,
            "ownerReferences": owner,
        },
        "spec": {
            "selector": {"app": unit, "apps.kubernetes.io/pod-index": "0"},
            "ports": [
                {"name": "http", "port": 9000, "targetPort": 9000},
                {"name": "metrics", "port": 6000, "targetPort": 6000},
            ],
        },
    }
    statefulset = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {
            "name": unit,
            "namespace": namespace,
            "labels": labels,
            "ownerReferences": owner,
        },
        "spec": {
            "serviceName": unit,
            "replicas": info.hosts,
            "podManagementPolicy": "Parallel",
            "selector": {"matchLabels": {"app": unit}},
            "template": {
                "metadata": {"labels": labels},
                "spec": pod_spec,
            },
        },
    }
    return [headless, routed, statefulset]


def build_warm_pool_manifests(
    name: str,
    namespace: str,
    owner_uid: str,
    config: OperatorConfig,
    version: str,
    model_uri: str,
) -> list[dict[str, Any]]:
    """Warm-pool Deployment for ``autoscaling.warmPoolSize`` replicas.

    Each pod runs the server in ``--warm-pool`` mode: booted, compile
    sweep run against the current version's snapshot geometry, holding
    NO weights — deliberately NotReady (no traffic routes there) until
    a ``POST /admin/attach``.  Even unattached, the pool keeps the
    node-local snapshot + XLA caches hot, so a wake-from-zero replica
    scheduled onto the same node restores instead of cold-loading.
    Returns ``[]`` when the pool size is 0 (byte-identity) or the
    backend is not ``tpu``.
    """
    size = config.autoscaling.warm_pool_size
    if size <= 0 or config.backend != "tpu":
        return []
    unit = f"{name}-warm-pool"
    labels = {
        "app": unit,
        "tpumlops/deployment": name,
        "tpumlops/role": "warm-pool",
    }
    pod_spec = _tpu_pod_spec(version, model_uri, config, name, namespace)
    pod_spec["containers"][0]["args"] += ["--warm-pool", "1"]
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": unit,
                "namespace": namespace,
                "labels": labels,
                "ownerReferences": owner_reference(name, owner_uid),
            },
            "spec": {
                "replicas": size,
                "selector": {"matchLabels": {"app": unit}},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": pod_spec,
                },
            },
        }
    ]


def fleet_pool_name(deployment_name: str, version: str, pool: str) -> str:
    """Name of one disaggregated pool's Deployment/Service."""
    return f"{deployment_name}-v{version}-{pool}"


def build_fleet_pool_manifests(
    name: str,
    namespace: str,
    owner_uid: str,
    config: OperatorConfig,
    version: str,
    model_uri: str,
    prefill_replicas: int | None = None,
    decode_replicas: int | None = None,
) -> list[dict[str, Any]]:
    """Disaggregated prefill/decode pools for one predictor version.

    Two Deployments (each pod a full server flagged with its
    ``--fleet-role``) plus a routed Service per pool — the router's
    backend table points at the Services, role-tagged, so the
    prefix-affinity ring covers the decode pool and the KV-export relay
    targets the prefill pool.  Replica counts default to ``spec.fleet``
    and are overridden by the per-pool autoscaler (``status.fleet``).
    Returns ``[]`` when disaggregation is off (byte-identity) or the
    backend is not ``tpu``.
    """
    fleet = config.fleet
    if not fleet.disaggregation or config.backend != "tpu":
        return []
    counts = {
        "prefill": (
            fleet.prefill_replicas
            if prefill_replicas is None
            else int(prefill_replicas)
        ),
        "decode": (
            fleet.decode_replicas
            if decode_replicas is None
            else int(decode_replicas)
        ),
    }
    owner = owner_reference(name, owner_uid)
    out: list[dict[str, Any]] = []
    for pool, replicas in counts.items():
        unit = fleet_pool_name(name, version, pool)
        labels = {
            "app": unit,
            "tpumlops/deployment": name,
            "tpumlops/predictor": f"v{version}",
            "tpumlops/fleet-role": pool,
        }
        pod_spec = _tpu_pod_spec(version, model_uri, config, name, namespace)
        args = pod_spec["containers"][0]["args"]
        # Pool replicas export their OWN metric identity
        # (predictor_name "v<ver>-prefill"/"-decode"): the per-pool
        # autoscaler reads each pool's saturation series separately,
        # and pool pods must not pollute the unified predictor's
        # summed signals.
        args[args.index("--predictor-name") + 1] = f"v{version}-{pool}"
        args += ["--fleet-role", pool]
        out.append(
            {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {
                    "name": unit,
                    "namespace": namespace,
                    "labels": labels,
                    "ownerReferences": owner,
                },
                "spec": {
                    "replicas": replicas,
                    "selector": {"matchLabels": {"app": unit}},
                    "template": {
                        "metadata": {"labels": labels},
                        "spec": pod_spec,
                    },
                },
            }
        )
        out.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {
                    "name": unit,
                    "namespace": namespace,
                    "labels": labels,
                    "ownerReferences": owner,
                },
                "spec": {
                    "selector": {"app": unit},
                    "ports": [
                        {"name": "http", "port": 9000, "targetPort": 9000},
                        {
                            "name": "metrics",
                            "port": 6000,
                            "targetPort": 6000,
                        },
                    ],
                },
            }
        )
    return out


def build_deployment(
    name: str,
    namespace: str,
    owner_uid: str,
    config: OperatorConfig,
    current_version: str,
    new_model_uri: str,
    traffic_current: int,
    previous_version: str | None = None,
    old_model_uri: str | None = None,
    traffic_prev: int = 0,
    replicas: int | None = None,
) -> dict[str, Any]:
    """Build the (Seldon-shaped) deployment manifest for a rollout state.

    Predictor order matches the reference: previous first, current second
    (``mlflow_operator.py:181-222``); at 100% only the current predictor
    remains (``:354-358``).

    ``replicas`` is the autoscaler-controlled count (``status.replicas``);
    it applies to EVERY predictor in the manifest — during a canary the
    topology is frozen, so old and new versions must serve at the same
    replica count or the promotion judge would compare a loaded predictor
    against an idle one.  None (autoscaling off) keeps the spec-declared
    topology byte-for-byte.
    """
    if previous_version is not None and old_model_uri is None:
        raise ValueError("old_model_uri required when previous_version is set")

    if config.backend == "tpu":
        make = lambda v, uri, t: _tpu_predictor(
            v, uri, t, config, name, namespace, replicas=replicas
        )
        protocol = "v2"
    else:
        make = lambda v, uri, t: _seldon_predictor(
            v, uri, t, config, replicas=replicas
        )
        protocol = "kfserving"  # reference :235

    predictors: list[dict[str, Any]] = []
    if previous_version is not None and traffic_prev > 0:
        predictors.append(make(previous_version, old_model_uri, traffic_prev))
    predictors.append(make(current_version, new_model_uri, traffic_current))

    # Rollout context as annotations: `kubectl get sdep -o yaml` then
    # explains the split without chasing the owning MlflowModel's status
    # (the spec.predictors weights say WHAT, these say WHICH rollout).
    annotations = {
        "tpumlops.dev/current-version": str(current_version),
        "tpumlops.dev/traffic-current": str(traffic_current),
    }
    if previous_version is not None and traffic_prev > 0:
        annotations["tpumlops.dev/previous-version"] = str(previous_version)
        annotations["tpumlops.dev/traffic-prev"] = str(traffic_prev)
    if replicas is not None:
        # Autoscaler context (absent = fixed topology, byte-for-byte):
        # `kubectl get sdep -o yaml` explains the replica count without
        # chasing the owning MlflowModel's status.
        annotations["tpumlops.dev/replicas"] = str(replicas)
    if config.backend == "tpu" and config.fleet.disaggregation:
        # Fleet routing contract (absent = byte-for-byte): whatever
        # fronts this predictor (the native router in local/router
        # mode, a mesh config elsewhere) reads the affinity/handoff
        # knobs and the pool Service names from HERE — the manifest is
        # the handoff point, exactly as traffic weights are.
        fleet = config.fleet
        annotations["tpumlops.dev/fleet-disaggregation"] = "true"
        annotations["tpumlops.dev/fleet-prefill-service"] = fleet_pool_name(
            name, current_version, "prefill"
        )
        annotations["tpumlops.dev/fleet-decode-service"] = fleet_pool_name(
            name, current_version, "decode"
        )
        if fleet.prefix_affinity.enabled:
            annotations["tpumlops.dev/fleet-affinity-tokens"] = str(
                fleet.prefix_affinity.tokens
            )
        if fleet.kv_transfer.enabled:
            annotations["tpumlops.dev/fleet-kv-retries"] = str(
                fleet.kv_transfer.retries
            )
    if config.backend == "tpu" and config.fleet.observability.journey_ring > 0:
        # Fleet trace plane (absent = byte-for-byte): RouterSync reads
        # this annotation and sizes the router's journey ring — valid
        # with or without disaggregation, same handoff contract as the
        # affinity/kv knobs above.
        annotations["tpumlops.dev/fleet-journey-ring"] = str(
            config.fleet.observability.journey_ring
        )
    if (
        config.backend == "tpu"
        and config.tpu.observability.timeseries_ring > 0
    ):
        # Router half of the anomaly observatory (absent = byte-for-
        # byte): RouterSync reads this annotation and sizes the router's
        # per-backend time-series ring to match the replicas' rings —
        # proxy-visible slowness (leg latency) lives only at the router.
        annotations["tpumlops.dev/fleet-timeseries-ring"] = str(
            config.tpu.observability.timeseries_ring
        )
    if config.backend == "tpu" and config.multiplex.enabled:
        # Multiplexing contract (absent = byte-for-byte): RouterSync
        # reads mux-models to arm model-aware routing, and the shared
        # pool's packer reads poolRef/weight — same manifest-as-handoff
        # pattern as the fleet knobs above.
        annotations["tpumlops.dev/mux-models"] = "1"
        annotations["tpumlops.dev/mux-pool"] = str(config.multiplex.pool_ref)
        annotations["tpumlops.dev/mux-weight"] = str(config.multiplex.weight)

    return {
        "apiVersion": SELDON_API_VERSION,
        "kind": "SeldonDeployment",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "annotations": annotations,
            "ownerReferences": owner_reference(name, owner_uid),
        },
        "spec": {
            "name": name,
            "protocol": protocol,
            "predictors": predictors,
        },
    }


def set_traffic(
    manifest: Mapping[str, Any], weights: Mapping[str, int]
) -> dict[str, Any]:
    """Return a copy of ``manifest`` with predictor traffic set from
    ``weights`` (predictor name -> percent); reference ``:319-327``."""
    import copy

    out = copy.deepcopy(dict(manifest))
    for predictor in out["spec"]["predictors"]:
        if predictor["name"] in weights:
            predictor["traffic"] = weights[predictor["name"]]
    return out
