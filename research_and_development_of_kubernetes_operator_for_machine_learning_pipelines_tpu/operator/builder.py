"""Deployment manifest construction.

Two backends:

- ``seldon`` — byte-compatible with the reference's SeldonDeployment shape
  (``mlflow_operator.py:193-238``): ``MLFLOW_SERVER`` graph nodes, protocol
  ``kfserving``, predictor names ``v<version>``, weighted ``traffic``.
- ``tpu``    — the north-star first-party data plane: each predictor is our
  JAX/XLA inference server (``server/``) pinned to a TPU node pool via
  nodeSelector/tolerations, with mesh shape and topology passed through the
  container environment.  The Seldon CR shape (predictor list + traffic
  weights + Istio split) is retained so the promotion loop and metric
  identity (``deployment_name``/``predictor_name``/``namespace``,
  ``mlflow_operator.py:367``) are unchanged.

Owner references (``:158-169``) make the cluster GC the deployment when the
``MlflowModel`` CR is deleted.
"""

from __future__ import annotations

import json
from typing import Any, Mapping

from ..utils.config import OperatorConfig, TpuSpec, TPU_TOPOLOGIES

SELDON_API_VERSION = "machinelearning.seldon.io/v1"
MLFLOWMODEL_API_VERSION = "mlflow.nizepart.com/v1alpha1"


def owner_reference(name: str, uid: str) -> list[dict[str, Any]]:
    """Reference ``mlflow_operator.py:162-169``."""
    return [
        {
            "apiVersion": MLFLOWMODEL_API_VERSION,
            "kind": "MlflowModel",
            "name": name,
            "uid": uid,
            "controller": True,
            "blockOwnerDeletion": True,
        }
    ]


def _seldon_predictor(
    version: str, model_uri: str, traffic: int, config: OperatorConfig
) -> dict[str, Any]:
    """Reference-parity predictor (``mlflow_operator.py:195-222``)."""
    return {
        "graph": {
            "name": f"classifier-{version}",
            "implementation": "MLFLOW_SERVER",
            "modelUri": model_uri,
            "envSecretRefName": config.minio_secret,
            "children": [],
        },
        "name": f"v{version}",
        "replicas": 1,
        "traffic": traffic,
    }


def _tpu_predictor(
    version: str,
    model_uri: str,
    traffic: int,
    config: OperatorConfig,
    deployment_name: str,
    namespace: str,
) -> dict[str, Any]:
    """First-party TPU predictor: our JAX server on a v5e node pool."""
    tpu: TpuSpec = config.tpu
    info = TPU_TOPOLOGIES.get(tpu.topology)
    if info is None:
        raise ValueError(
            f"unknown tpuTopology {tpu.topology!r}; known: {sorted(TPU_TOPOLOGIES)}"
        )
    accelerator, gke_topology, _chips = info
    container = {
        "name": f"tpu-server-{version}",
        "image": config.server_image,
        "args": [
            "--model-uri", model_uri,
            "--model-name", config.model_name,
            "--predictor-name", f"v{version}",
            "--deployment-name", deployment_name,
            "--namespace", namespace,
            "--mesh-shape", json.dumps(dict(tpu.mesh_shape)),
            "--dtype", tpu.dtype,
            "--max-batch-size", str(tpu.max_batch_size),
            "--max-batch-delay-ms", str(tpu.max_batch_delay_ms),
        ],
        "env": [
            {"name": "TPU_TOPOLOGY", "value": tpu.topology},
            {"name": "JAX_PLATFORMS", "value": "tpu"},
            {
                "name": "JAX_COMPILATION_CACHE_DIR",
                "value": tpu.compile_cache_dir or "",
            },
        ],
        "ports": [
            {"name": "http", "containerPort": 9000},
            {"name": "metrics", "containerPort": 6000},
        ],
        "resources": {
            "limits": {"google.com/tpu": str(tpu.num_devices)},
            "requests": {"google.com/tpu": str(tpu.num_devices)},
        },
        "readinessProbe": {
            "httpGet": {"path": "/v2/health/ready", "port": 9000},
            # TPU cold-start: first jit compile can take tens of seconds;
            # generous window so a canary isn't killed mid-compile
            # (SURVEY §7 hard part 3).
            "initialDelaySeconds": 10,
            "periodSeconds": 5,
            "failureThreshold": 60,
        },
    }
    if config.minio_secret:
        container["envFrom"] = [{"secretRef": {"name": config.minio_secret}}]
    return {
        "graph": {
            "name": f"tpu-server-{version}",
            "implementation": "TRITON_SERVER",  # pre-packaged V2-protocol slot
            "type": "MODEL",
            "modelUri": model_uri,
            "children": [],
        },
        "componentSpecs": [
            {
                "spec": {
                    "nodeSelector": {
                        "cloud.google.com/gke-tpu-accelerator": accelerator,
                        "cloud.google.com/gke-tpu-topology": gke_topology,
                    },
                    "tolerations": [
                        {
                            "key": "google.com/tpu",
                            "operator": "Exists",
                            "effect": "NoSchedule",
                        }
                    ],
                    "containers": [container],
                }
            }
        ],
        "name": f"v{version}",
        "replicas": tpu.replicas,
        "traffic": traffic,
    }


def build_deployment(
    name: str,
    namespace: str,
    owner_uid: str,
    config: OperatorConfig,
    current_version: str,
    new_model_uri: str,
    traffic_current: int,
    previous_version: str | None = None,
    old_model_uri: str | None = None,
    traffic_prev: int = 0,
) -> dict[str, Any]:
    """Build the (Seldon-shaped) deployment manifest for a rollout state.

    Predictor order matches the reference: previous first, current second
    (``mlflow_operator.py:181-222``); at 100% only the current predictor
    remains (``:354-358``).
    """
    if previous_version is not None and old_model_uri is None:
        raise ValueError("old_model_uri required when previous_version is set")

    if config.backend == "tpu":
        make = lambda v, uri, t: _tpu_predictor(v, uri, t, config, name, namespace)
        protocol = "v2"
    else:
        make = lambda v, uri, t: _seldon_predictor(v, uri, t, config)
        protocol = "kfserving"  # reference :235

    predictors: list[dict[str, Any]] = []
    if previous_version is not None and traffic_prev > 0:
        predictors.append(make(previous_version, old_model_uri, traffic_prev))
    predictors.append(make(current_version, new_model_uri, traffic_current))

    return {
        "apiVersion": SELDON_API_VERSION,
        "kind": "SeldonDeployment",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "ownerReferences": owner_reference(name, owner_uid),
        },
        "spec": {
            "name": name,
            "protocol": protocol,
            "predictors": predictors,
        },
    }


def set_traffic(
    manifest: Mapping[str, Any], weights: Mapping[str, int]
) -> dict[str, Any]:
    """Return a copy of ``manifest`` with predictor traffic set from
    ``weights`` (predictor name -> percent); reference ``:319-327``."""
    import copy

    out = copy.deepcopy(dict(manifest))
    for predictor in out["spec"]["predictors"]:
        if predictor["name"] in weights:
            predictor["traffic"] = weights[predictor["name"]]
    return out
