"""Offline SLO planner: throughput-optimal knob search over a replayed
journey trace (``spec.planner``).

InferLine's observation (PAPERS.md) is that the cheapest configuration
meeting a tight latency objective is found OFFLINE, against a recorded
trace, with an analytic cost model — not by live trial and error on the
fleet.  Every input this planner needs already exists as a spec'd
surface:

- the **trace**: the router journey ring's ``/router/debug/requests``
  export, parsed by ``utils/journey_trace.py`` (typed rejection of
  drifted exports);
- the **cost model**: the same analytic FLOPs / HBM-bytes ledger the
  device-telemetry layer reads MFU against
  (:class:`~..server.device_telemetry.LlamaCostModel`), joined with the
  per-chip rooflines (:class:`~..server.device_telemetry.DevicePeaks`);
- the **knob space**: everything PRs 7-17 turned into pure config —
  ``decodeSteps`` K, ``speculative``, ``prefillBatch`` /
  ``prefillTokenBudget``, ``quantize``, cache slots (``maxSlots``), and
  ``meshShape`` chips-per-replica vs replica count (the fleet pool
  size).

:func:`plan` replays the trace's arrivals through a deterministic
slot-level simulator for every grid point and emits the cheapest
(chip-seconds) configuration whose predicted interactive TTFT p99 meets
the objective — or raises the typed :class:`InfeasibleObjectiveError`
naming the best the knob space can do.  Determinism is a contract:
``make verify``'s ``plan-contract`` step re-plans the committed fixture
trace and diffs the committed plan JSON byte-for-byte, so cost-model
drift fails CI instead of silently re-shaping fleets.

Error bars (documented in docs/PLANNER.md): tick walls are
``max(flops, bytes)`` rooflines plus a fixed host-dispatch constant —
no kernel-level overlap modeling; speculative decode is credited an
assumed acceptance rate (:data:`SPEC_ASSUMED_ACCEPTANCE`); the
simulator models slots, not the admission queue's class interleaving.
The numbers are planning-grade (which knob region), not benchmark-grade
(exact milliseconds).
"""

from __future__ import annotations

import json
import math
from dataclasses import asdict, dataclass, field, replace
from pathlib import Path
from typing import Any, Mapping

from ..server.device_telemetry import DevicePeaks, LlamaCostModel
from ..utils.config import OperatorConfig, PlannerSpec, TPU_TOPOLOGIES
from ..utils.journey_trace import (
    JourneyTrace,
    TraceRequest,
    load_journey_trace,
)

PLAN_FORMAT_VERSION = 1

# Fixed per-dispatch host overhead (enqueue + callback glue) the fused
# multi-step path amortizes by K.  Order-of-magnitude constant, same
# spirit as DevicePeaks' "assumed" rooflines.
HOST_DISPATCH_S = 300e-6

# Credit speculative decode an assumed draft-acceptance rate: the trace
# records arrivals, not text, so the real rate is unknowable offline.
# 0.3 is conservative for chat workloads (bench.py measures the real
# curve); docs/PLANNER.md carries the caveat.
SPEC_ASSUMED_ACCEPTANCE = 0.3
SPEC_DRAFT_TOKENS = 4

# v5e rooflines (per chip), matching device_telemetry's assumed table.
_DEFAULT_PEAKS = DevicePeaks(
    kind="tpu-v5e(assumed)",
    flops_per_s=197e12,
    hbm_bytes_per_s=819e9,
    hbm_bytes=16 * 2**30,
    source="assumed",
)


class InfeasibleObjectiveError(ValueError):
    """No point in the knob space meets the stated objective.

    Carries the best the space can do (``best_ms`` at ``best_knobs``) so
    the caller can surface "tighten the objective or grow the slice"
    with numbers instead of a bare failure."""

    def __init__(self, objective_ms: float, best_ms: float,
                 best_knobs: Mapping[str, Any]):
        self.objective_ms = objective_ms
        self.best_ms = best_ms
        self.best_knobs = dict(best_knobs)
        super().__init__(
            f"no knob configuration meets ttftP99Ms <= {objective_ms:g}: "
            f"best predicted p99 is {best_ms:.1f} ms at {self.best_knobs} "
            "— loosen the objective or provide a larger topology"
        )


@dataclass(frozen=True)
class ModelProfile:
    """Model geometry the analytic cost model needs (7B-class defaults).

    ``spec.planner.model`` overrides any field; the live server derives
    the same numbers from the artifact in hand
    (``LlamaCostModel.for_model``) — the planner runs where no artifact
    is loadable, so the geometry is declared instead."""

    num_layers: int = 32
    hidden_size: int = 4096
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    intermediate_size: int = 11008
    vocab_size: int = 32000

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | None) -> "ModelProfile":
        spec = dict(spec or {})
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(
                f"spec.planner.model has unknown keys {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        return cls(**{k: int(v) for k, v in spec.items()})

    @property
    def matmul_params(self) -> int:
        """Weight-matrix element count (the 2-flops-per-param term)."""
        h = self.hidden_size
        attn = 2 * h * h + 2 * h * self.num_kv_heads * self.head_dim
        mlp = 3 * h * self.intermediate_size
        return self.num_layers * (attn + mlp) + h * self.vocab_size


@dataclass(frozen=True)
class KnobPoint:
    """One candidate configuration the search scores."""

    tp: int = 1            # chips per replica (meshShape tp axis)
    replicas: int = 1      # pool size (chips_total = tp * replicas)
    max_slots: int = 8     # continuous-batching cache slots
    quantize: str = "none"
    decode_steps: int = 1
    speculative: bool = False
    prefill_batch: int = 1
    prefill_token_budget: int = 0

    @property
    def chips(self) -> int:
        return self.tp * self.replicas

    def as_spec(self) -> dict:
        """CRD-spelled knob dict (the plan's ``knobs`` key)."""
        return {
            "meshShape": {"dp": 1, "tp": self.tp},
            "replicas": self.replicas,
            "maxSlots": self.max_slots,
            "quantize": self.quantize,
            "decodeSteps": self.decode_steps,
            "speculative": bool(self.speculative),
            "prefillBatch": self.prefill_batch,
            "prefillTokenBudget": self.prefill_token_budget,
        }


def _cost_model(profile: ModelProfile, knob: KnobPoint) -> LlamaCostModel:
    dtype_bytes = 1 if knob.quantize in ("int8", "int8kv") else 2
    kv_eb = (
        1 + 4.0 / profile.head_dim if knob.quantize == "int8kv" else 2.0
    )
    return LlamaCostModel(
        matmul_params=profile.matmul_params,
        weight_bytes=profile.matmul_params * dtype_bytes,
        num_layers=profile.num_layers,
        num_heads=profile.num_heads,
        num_kv_heads=profile.num_kv_heads,
        head_dim=profile.head_dim,
        kv_elem_bytes=kv_eb,
        tp=knob.tp,
        hidden_size=profile.hidden_size,
        vocab_size=profile.vocab_size,
        act_bytes=2,
    )


def _wall(flops: float, nbytes: float, coll: Mapping[str, float],
          peaks: DevicePeaks, dispatches: float = 1.0) -> float:
    """Roofline wall of one device dispatch: max(compute, HBM) plus the
    ICI collective terms, plus ``dispatches`` host-dispatch constants."""
    w = max(flops / peaks.flops_per_s, nbytes / peaks.hbm_bytes_per_s)
    for b in coll.values():
        w += b / peaks.ici_bytes_per_s
    return w + dispatches * HOST_DISPATCH_S


def _prefill_seconds(cm: LlamaCostModel, peaks: DevicePeaks,
                     tokens: int, knob: KnobPoint) -> float:
    """Wall to prefill one ``tokens``-long cold prompt.  ``prefillBatch``
    > 1 amortizes the weight stream across packed admissions — credited
    as the weight-bytes term divided by the batch (full packing, the
    bursty-load best case the knob exists for)."""
    flops, nbytes = cm.prefill(1, tokens)
    if knob.prefill_batch > 1:
        nbytes -= cm.weight_bytes * (1.0 - 1.0 / knob.prefill_batch)
    return _wall(flops, nbytes, cm.collective_bytes(1, tokens), peaks)


def _per_token_seconds(cm: LlamaCostModel, peaks: DevicePeaks,
                       window: float, knob: KnobPoint) -> float:
    """Steady-state seconds per generated token for one slot, at full
    occupancy (``max_slots`` rows share every tick — the conservative
    load assumption), with the fused-K dispatch amortization and the
    assumed speculative acceptance credit applied."""
    rows = knob.max_slots
    if knob.speculative:
        s = 1 + SPEC_DRAFT_TOKENS
        flops, nbytes = cm.decode(rows, int(window), s)
        wall = _wall(flops, nbytes, cm.collective_bytes(rows, s), peaks)
        tokens = 1.0 + SPEC_ASSUMED_ACCEPTANCE * SPEC_DRAFT_TOKENS
        return wall / tokens
    flops, nbytes = cm.decode(rows, int(window), 1)
    # decodeSteps K fuses K decode iterations under ONE host dispatch.
    k = max(1, knob.decode_steps)
    wall = _wall(k * flops, k * nbytes, cm.collective_bytes(rows, k),
                 peaks, dispatches=1.0)
    return wall / k


@dataclass(frozen=True)
class Prediction:
    """What the simulator says one knob point does to the trace."""

    ttft_p50_ms: float
    ttft_p99_ms: float
    makespan_s: float
    chip_seconds: float
    chips: int
    requests: int


def _percentile(sorted_vals: list, q: float) -> float:
    """Deterministic nearest-rank percentile (no interpolation drift)."""
    if not sorted_vals:
        return 0.0
    rank = max(0, math.ceil(q * len(sorted_vals)) - 1)
    return sorted_vals[min(rank, len(sorted_vals) - 1)]


def predict(trace: JourneyTrace, knob: KnobPoint,
            profile: ModelProfile | None = None,
            peaks: DevicePeaks | None = None) -> Prediction:
    """Replay the trace's arrivals through ``knob``'s analytic engine.

    Deterministic slot-level simulation: arrivals assign to the replica
    with the least outstanding work (tie: lowest index), then to that
    replica's earliest-free slot.  TTFT = queue wait + prefill wall;
    the decode tail holds the slot for ``max_new_tokens`` at the
    steady-state per-token cadence.  The objective reads the
    interactive class's TTFTs when the trace carries classes (the SLO
    preemption exists to protect), all requests otherwise."""
    profile = profile or ModelProfile()
    base = peaks or _DEFAULT_PEAKS
    per_replica = base.scaled(knob.tp)
    cm = _cost_model(profile, knob)

    # slot_free[r][s] = when slot s of replica r next frees.
    slot_free = [[0.0] * knob.max_slots for _ in range(knob.replicas)]
    replica_load = [0.0] * knob.replicas  # outstanding busy seconds
    ttfts: list[float] = []
    interactive_ttfts: list[float] = []
    finish_last = 0.0
    for req in trace.requests:
        window = req.prompt_tokens + req.max_new_tokens / 2.0
        prefill_s = _prefill_seconds(cm, per_replica, req.prompt_tokens,
                                     knob)
        decode_s = req.max_new_tokens * _per_token_seconds(
            cm, per_replica, window, knob
        )
        r = min(range(knob.replicas), key=lambda i: (replica_load[i], i))
        slots = slot_free[r]
        s = min(range(knob.max_slots), key=lambda i: (slots[i], i))
        start = max(req.arrival_s, slots[s])
        ttft = (start - req.arrival_s) + prefill_s
        finish = start + prefill_s + decode_s
        slots[s] = finish
        replica_load[r] += prefill_s + decode_s
        finish_last = max(finish_last, finish)
        ttfts.append(ttft)
        if req.slo_class == "interactive":
            interactive_ttfts.append(ttft)
    scored = sorted(interactive_ttfts or ttfts)
    makespan = finish_last
    return Prediction(
        ttft_p50_ms=_percentile(scored, 0.50) * 1e3,
        ttft_p99_ms=_percentile(scored, 0.99) * 1e3,
        makespan_s=makespan,
        chip_seconds=knob.chips * makespan,
        chips=knob.chips,
        requests=len(trace.requests),
    )


def default_grid(chips_available: int = 8) -> tuple[KnobPoint, ...]:
    """The deterministic search grid, bounded by the topology's chips.

    Ordered canonically (ascending knob tuples) so ties in the
    (chip-seconds, p99) objective always resolve the same way."""
    points = []
    for tp in (1, 4, 8):
        for replicas in (1, 2, 4):
            if tp * replicas > chips_available:
                continue
            for max_slots in (4, 8, 16):
                for quantize in ("none", "int8", "int8kv"):
                    for decode_steps in (1, 4):
                        for speculative in (False, True):
                            for prefill_batch in (1, 4):
                                points.append(KnobPoint(
                                    tp=tp,
                                    replicas=replicas,
                                    max_slots=max_slots,
                                    quantize=quantize,
                                    decode_steps=decode_steps,
                                    speculative=speculative,
                                    prefill_batch=prefill_batch,
                                    prefill_token_budget=(
                                        2048 if prefill_batch > 1 else 0
                                    ),
                                ))
    return tuple(points)


def _round_floats(obj):
    """3-decimal rounding everywhere: the committed plan JSON must be
    byte-for-byte reproducible across platforms' float printing."""
    if isinstance(obj, float):
        return round(obj, 3)
    if isinstance(obj, dict):
        return {k: _round_floats(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_round_floats(v) for v in obj]
    return obj


def plan(trace: JourneyTrace,
         objective: Mapping[str, float],
         profile: ModelProfile | None = None,
         peaks: DevicePeaks | None = None,
         grid: tuple[KnobPoint, ...] | None = None,
         chips_available: int = 8,
         seed: int = 0) -> dict:
    """Search the knob grid for the cheapest point meeting ``objective``.

    Returns the costed plan dict (``status.plan`` / ``scripts/plan.py``
    output).  Raises :class:`InfeasibleObjectiveError` (typed) when no
    grid point meets the objective, and ``ValueError`` for an objective
    key the planner does not know or an empty trace.  ``seed`` is
    recorded in the plan for provenance; the search itself is
    exhaustive and deterministic — same trace + same objective ==
    byte-for-byte the same plan."""
    unknown = set(objective) - {"ttftP99Ms"}
    if unknown:
        raise ValueError(
            f"unknown planner objective keys {sorted(unknown)}; "
            "known: ['ttftP99Ms']"
        )
    if "ttftP99Ms" not in objective:
        raise ValueError("planner objective requires ttftP99Ms")
    objective_ms = float(objective["ttftP99Ms"])
    if objective_ms <= 0:
        raise ValueError(
            f"planner objective ttftP99Ms must be > 0, got {objective_ms}"
        )
    if not trace.requests:
        raise ValueError("journey trace has no requests to replay")
    grid = grid or default_grid(chips_available)
    best = None           # (chip_seconds, p99, idx, knob, pred): feasible
    best_any = None       # same, ignoring feasibility (for the error)
    for idx, knob in enumerate(grid):
        pred = predict(trace, knob, profile=profile, peaks=peaks)
        key = (pred.chip_seconds, pred.ttft_p99_ms, idx)
        if best_any is None or pred.ttft_p99_ms < best_any[4].ttft_p99_ms:
            best_any = (*key, knob, pred)
        if pred.ttft_p99_ms <= objective_ms and (
            best is None or key < best[:3]
        ):
            best = (*key, knob, pred)
    if best is None:
        assert best_any is not None
        raise InfeasibleObjectiveError(
            objective_ms, best_any[4].ttft_p99_ms, best_any[3].as_spec()
        )
    _, _, _, knob, pred = best
    return _round_floats({
        "formatVersion": PLAN_FORMAT_VERSION,
        "seed": int(seed),
        "objective": {"ttftP99Ms": objective_ms},
        "knobs": knob.as_spec(),
        "predicted": {
            "ttftP50Ms": pred.ttft_p50_ms,
            "ttftP99Ms": pred.ttft_p99_ms,
            "makespanS": pred.makespan_s,
            "chipSeconds": pred.chip_seconds,
            "chips": pred.chips,
        },
        "trace": {
            "requests": pred.requests,
            "spanS": trace.span_s,
            "formatVersion": trace.format_version,
        },
        "searched": len(grid),
    })


def plan_for_config(config: OperatorConfig) -> dict | None:
    """The reconciler's entry: run :func:`plan` per ``spec.planner``.

    Returns None when the planner is disabled.  Trace loading, profile
    parsing, and the search all raise typed ValueErrors the reconciler
    surfaces on CR status."""
    spec: PlannerSpec = config.planner
    if not spec.enabled:
        return None
    source = spec.trace if spec.trace is not None else spec.trace_path
    trace = load_journey_trace(source)
    profile = ModelProfile.from_spec(spec.model)
    info = TPU_TOPOLOGIES.get(config.tpu.topology)
    chips = info.chips if info is not None else 8
    return plan(trace, spec.objective, profile=profile,
                chips_available=chips)


def apply_plan(config: OperatorConfig, plan_dict: Mapping[str, Any]
               ) -> OperatorConfig:
    """``applyMode: apply``: fold the plan's chosen knobs into the
    config the builder renders manifests from.  Returns a NEW config
    (frozen dataclasses throughout); suggest mode never calls this."""
    knobs = dict(plan_dict.get("knobs") or {})
    tpu = config.tpu
    spec_updates: dict = {}
    if "meshShape" in knobs:
        spec_updates["mesh_shape"] = dict(knobs["meshShape"])
    if "replicas" in knobs:
        spec_updates["replicas"] = int(knobs["replicas"])
    if "maxSlots" in knobs:
        spec_updates["max_slots"] = int(knobs["maxSlots"])
    if "quantize" in knobs:
        spec_updates["quantize"] = str(knobs["quantize"])
    if "decodeSteps" in knobs:
        spec_updates["decode_steps"] = int(knobs["decodeSteps"])
    if "prefillBatch" in knobs:
        spec_updates["prefill_batch"] = int(knobs["prefillBatch"])
    if "prefillTokenBudget" in knobs:
        spec_updates["prefill_token_budget"] = int(
            knobs["prefillTokenBudget"]
        )
    if "speculative" in knobs:
        spec_updates["speculative"] = replace(
            tpu.speculative, enabled=bool(knobs["speculative"])
        )
    return replace(config, tpu=replace(tpu, **spec_updates))


@dataclass(frozen=True)
class PlanRecord:
    """One planner decision for the rollout journal (``kind: "plan"``) —
    journaled beside gate/scale/SLO records when the computed plan
    changes, surfacing on ``status.history`` and ``/debug/rollouts``."""

    ts: float
    wall: float
    apply_mode: str
    objective: dict = field(default_factory=dict)
    knobs: dict = field(default_factory=dict)
    predicted: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "kind": "plan",
            "ts": self.ts,
            "wall": self.wall,
            "applyMode": self.apply_mode,
            "objective": dict(self.objective),
            "knobs": dict(self.knobs),
            "predicted": dict(self.predicted),
        }
