"""Fleet anomaly observatory: peer straggler detection + baseline drift.

The rings (``server/timeseries.py``, the router's ``--timeseries-ring``)
give every replica a short-horizon per-second history; this module is
the pure logic that turns a FLEET of those histories into verdicts:

- **Straggler** — one replica's window statistic (mean ITL p99, mean
  router leg wall, queue-depth slope, …) is a robust outlier against its
  same-pool peers.  Outliers are scored with the median/MAD modified
  z-score (Iglewicz & Hoaglin): ``z = 0.6745 · (x − median) / MAD``,
  falling back to the mean absolute deviation (scale 1.2533) when MAD
  collapses to zero (e.g. two identical healthy peers + one outlier).
  A series is only compared when **at least** ``spec.anomaly.minPeers``
  replicas report it — the MAD of a pair is degenerate, so small fleets
  produce NO verdict rather than a noisy one.
- **Drift** — a replica's current window has moved more than
  ``spec.anomaly.driftPct`` percent away from its own post-warmup /
  post-attach baseline (the ring's lifecycle marks anchor the baseline
  window), catching a slow degradation every peer shares — which peer
  comparison is structurally blind to.

:func:`detect` is a pure function of (windows, spec, baselines) — same
division of labor as ``autoscaler.decide`` and ``multiplexer.plan``; the
reconciler's ``_anomaly_step`` owns the I/O (ring snapshots in, journal
records + status out).  The window/baseline extraction helpers
(:func:`replica_series`, :func:`router_series`, :func:`baseline_of`)
keep detect() generic over NAMED series: server-side ITL and router-side
leg latency are just two series names, so proxy-visible slowness (a
``ChaosProxy inject_slow`` replica whose own server-side ITL looks
healthy) is caught by exactly the same code path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping, Sequence

from .rollout_recorder import _iso

# Modified z-score scale factors: 0.6745 ≈ Φ⁻¹(0.75) makes the MAD a
# consistent σ estimator for normal data; 1.2533 ≈ √(π/2) does the same
# for the mean absolute deviation (the MAD-zero fallback).
MAD_SCALE = 0.6745
MEANAD_SCALE = 1.253314

# Named series (replica_series emits them from a server ring snapshot).
# Kept as a tuple so the catalog in docs/OBSERVABILITY.md and the tests
# can pin the vocabulary.
SERVER_SERIES = (
    "itl_p50_ms",
    "itl_p99_ms",
    "mfu",
    "hbm_bw_util",
    "queue_depth",
    "queue_depth_slope",
    "active_slots",
    "shed",
    "poison",
)
ROUTER_SERIES = (
    "router_leg_p50_ms",
    "router_leg_p99_ms",
    "router_errors",
    "router_failovers",
)


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    if n % 2:
        return float(s[mid])
    return (s[mid - 1] + s[mid]) / 2.0


def robust_z(x: float, peers: Sequence[float]) -> "float | None":
    """Modified z-score of ``x`` against ``peers`` (x included).

    MAD-based; falls back to the mean absolute deviation when MAD is 0
    (a single outlier among otherwise-identical peers would otherwise
    be unscorable).  None when every deviation is zero — identical
    values have no outlier."""
    med = _median(peers)
    devs = [abs(v - med) for v in peers]
    mad = _median(devs)
    if mad > 0:
        return MAD_SCALE * (x - med) / mad
    mean_ad = sum(devs) / len(devs)
    if mean_ad > 0:
        return (x - med) / (MEANAD_SCALE * mean_ad)
    return None


def slope(samples: Sequence[float]) -> float:
    """Least-squares slope per sample step (the queue-growth signal:
    a replica whose queue RISES while its peers' hold flat is falling
    behind even if its absolute depth still looks ordinary)."""
    n = len(samples)
    if n < 2:
        return 0.0
    mean_x = (n - 1) / 2.0
    mean_y = sum(samples) / n
    num = sum((i - mean_x) * (y - mean_y) for i, y in enumerate(samples))
    den = sum((i - mean_x) ** 2 for i in range(n))
    return num / den if den else 0.0


@dataclass(frozen=True)
class AnomalyVerdict:
    """One replica flagged on one series."""

    replica: str
    kind: str  # "straggler" | "drift"
    series: str
    value: float  # the replica's window statistic
    direction: str  # "high" | "low" (relative to peers / baseline)
    z: "float | None" = None  # straggler: modified z-score
    peer_median: "float | None" = None  # straggler: the fleet's median
    peers: int = 0  # straggler: replicas compared (incl. this one)
    baseline: "float | None" = None  # drift: the anchored baseline
    drift_pct: "float | None" = None  # drift: observed deviation (%)

    @property
    def shape(self) -> tuple:
        """Dedupe key: WHICH replica is anomalous on WHICH series in
        WHICH direction — never the live statistics, which jitter every
        poll and would defeat the dedupe exactly when it matters (same
        contract as the PromotionHold rate limiter)."""
        return (self.replica, self.kind, self.series, self.direction)

    def as_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "replica": self.replica,
            "kind": self.kind,
            "series": self.series,
            "value": round(self.value, 4),
            "direction": self.direction,
        }
        if self.z is not None:
            out["z"] = round(self.z, 2)
        if self.peer_median is not None:
            out["peerMedian"] = round(self.peer_median, 4)
        if self.peers:
            out["peers"] = self.peers
        if self.baseline is not None:
            out["baseline"] = round(self.baseline, 4)
        if self.drift_pct is not None:
            out["driftPct"] = round(self.drift_pct, 1)
        return out


@dataclass(frozen=True)
class AnomalyRecord:
    """One verdict-set transition, journaled beside gate/scale/mux
    records (``kind: "anomaly"``)."""

    wall: float
    action: str  # "detected" | "cleared"
    verdicts: tuple = ()  # AnomalyVerdicts active after this transition
    replicas: int = 0  # fleet size the detector saw

    def as_dict(self) -> dict[str, Any]:
        return {
            "kind": "anomaly",
            "ts": self.wall,
            "time": _iso(self.wall),
            "action": self.action,
            "replicas": self.replicas,
            "verdicts": [v.as_dict() for v in self.verdicts],
        }


def detect(
    windows: Mapping[str, Mapping[str, Sequence[float]]],
    spec,
    baselines: "Mapping[str, Mapping[str, float]] | None" = None,
) -> tuple:
    """Pure detection pass over one fleet observation.

    ``windows`` maps replica → series name → window samples (what the
    extraction helpers below produce from ring snapshots).  ``spec`` is
    an ``AnomalySpec``; ``baselines`` maps replica → series → anchored
    baseline mean (drift is skipped for replicas/series without one, and
    entirely when ``spec.drift_pct`` is 0).

    Returns a deterministically-ordered tuple of verdicts: straggler
    verdicts first (by replica, series), then drift."""
    stats: dict[str, dict[str, float]] = {}
    for replica, series_map in windows.items():
        for series, samples in series_map.items():
            vals = [float(v) for v in samples if v is not None]
            if not vals:
                continue
            stats.setdefault(series, {})[replica] = sum(vals) / len(vals)

    verdicts: list[AnomalyVerdict] = []
    for series in sorted(stats):
        by_replica = stats[series]
        if len(by_replica) < spec.min_peers:
            continue  # hard no-verdict: a tiny peer set cannot vote
        peers = list(by_replica.values())
        med = _median(peers)
        for replica in sorted(by_replica):
            x = by_replica[replica]
            z = robust_z(x, peers)
            if z is None or abs(z) <= spec.mad_threshold:
                continue
            verdicts.append(
                AnomalyVerdict(
                    replica=replica,
                    kind="straggler",
                    series=series,
                    value=x,
                    direction="high" if x > med else "low",
                    z=z,
                    peer_median=med,
                    peers=len(peers),
                )
            )

    if spec.drift_pct > 0 and baselines:
        for replica in sorted(windows):
            base_map = baselines.get(replica) or {}
            for series in sorted(windows[replica]):
                base = base_map.get(series)
                cur = stats.get(series, {}).get(replica)
                if base is None or cur is None or base == 0:
                    continue
                pct = (cur - base) / abs(base) * 100.0
                if abs(pct) <= spec.drift_pct:
                    continue
                verdicts.append(
                    AnomalyVerdict(
                        replica=replica,
                        kind="drift",
                        series=series,
                        value=cur,
                        direction="high" if pct > 0 else "low",
                        baseline=base,
                        drift_pct=pct,
                    )
                )
    return tuple(verdicts)


# -- window / baseline extraction from ring snapshots -----------------------


def _window(samples: Sequence[Mapping], window_s: int) -> list:
    """Trailing ``window_s`` FINALIZED buckets (the open bucket is a
    partial second — including it would bias every rate downward)."""
    closed = [s for s in samples if not s.get("open")]
    return closed[-window_s:]


def replica_series(
    snapshot: Mapping, window_s: int
) -> dict[str, list]:
    """Named series from one server ``/debug/timeseries`` snapshot.

    Missing facets (no ITL this second, device telemetry off) are simply
    absent from that second's contribution — detect() works on what the
    fleet actually reports."""
    out: dict[str, list] = {}

    def push(series: str, value) -> None:
        if value is not None:
            out.setdefault(series, []).append(float(value))

    for s in _window(list(snapshot.get("samples") or ()), window_s):
        itl = s.get("itl") or {}
        if itl.get("n"):
            push("itl_p50_ms", itl.get("p50_ms"))
            push("itl_p99_ms", itl.get("p99_ms"))
        push("mfu", s.get("mfu"))
        push("hbm_bw_util", s.get("hbm_bw_util"))
        push("queue_depth", s.get("queue_depth"))
        push("active_slots", s.get("active_slots"))
        push("shed", s.get("shed"))
        push("poison", s.get("poison"))
    if "queue_depth" in out:
        out["queue_depth_slope"] = [slope(out["queue_depth"])]
    return out


def router_series(
    snapshot: Mapping, window_s: int
) -> dict[str, dict[str, list]]:
    """Per-backend named series from one ``/router/debug/timeseries``
    snapshot — keyed by backend (= replica/predictor) name, so they
    merge straight into the same fleet window map as the server series."""
    out: dict[str, dict[str, list]] = {}
    for name, ring in (snapshot.get("backends") or {}).items():
        series: dict[str, list] = {}
        for s in _window(list(ring.get("samples") or ()), window_s):
            if s.get("n"):
                series.setdefault("router_leg_p50_ms", []).append(
                    float(s.get("p50_ms") or 0.0)
                )
                series.setdefault("router_leg_p99_ms", []).append(
                    float(s.get("p99_ms") or 0.0)
                )
            series.setdefault("router_errors", []).append(
                float(s.get("errors") or 0)
            )
            series.setdefault("router_failovers", []).append(
                float(s.get("failovers") or 0)
            )
        if series:
            out[name] = series
    return out


def ring_sources_from(sources, timeout_s: float = 5.0):
    """Adapt a fleet trace-source list — or a zero-arg callable
    returning one (``[{"name", "base_url", "kind":
    "router"|"replica"}, ...]``, the ``--fleet-trace-sources`` shape) —
    into the reconciler's ``ring_sources`` seam: fetch every replica's
    ``/debug/timeseries`` and the router's ``/router/debug/timeseries``.
    A source with its ring disabled (404) or unreachable is simply
    absent from the observation; detect()'s min-peers gate handles the
    thinned fleet.  The ONLY I/O in this module — everything above
    stays pure."""
    import json as _json
    import urllib.request

    def _get(url: str):
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return _json.loads(resp.read().decode())

    def fetch() -> dict:
        specs = sources() if callable(sources) else sources
        out: dict = {"replicas": {}, "router": None}
        for spec in specs:
            base = str(spec.get("base_url") or "").rstrip("/")
            kind = spec.get("kind") or "replica"
            name = spec.get("name") or base
            try:
                if kind == "router":
                    out["router"] = _get(base + "/router/debug/timeseries")
                else:
                    out["replicas"][name] = _get(base + "/debug/timeseries")
            except Exception:
                continue
        return out

    return fetch


def baseline_of(snapshot: Mapping, baseline_s: int) -> dict[str, float]:
    """Anchored baseline from one server ring snapshot: the mean of each
    series over the ``baseline_s`` buckets FOLLOWING the newest
    lifecycle mark ("warmup" / "attach").  Empty when the ring carries
    no mark (nothing to anchor on) or no post-mark samples yet."""
    samples = [
        s for s in (snapshot.get("samples") or ()) if not s.get("open")
    ]
    mark_idx = None
    for i, s in enumerate(samples):
        if s.get("marks"):
            mark_idx = i
    if mark_idx is None:
        return {}
    window = samples[mark_idx : mark_idx + baseline_s]
    fake = {"samples": window}
    series = replica_series(fake, baseline_s)
    return {
        name: sum(vals) / len(vals)
        for name, vals in series.items()
        if vals and name != "queue_depth_slope"
    }
