"""The promotion gate: decide whether the canary earns more traffic.

Reference: ``should_promote_model`` (``mlflow_operator.py:419-460``).
Semantics preserved with default thresholds:

- any of {p95 latency, error rate, mean latency} being ``None`` on either
  model refuses promotion (``:430-434``) — both versions must have live
  traffic in the window;
- promote only if ALL of:
    new_p95 <= old_p95 * (1 + tol_p95)        (``:440``)
    new_err <= old_err * (1 + tol_err)        (``:447``)
    new_avg <= old_avg * (1 + tol_avg)        (``:454``)

Hardening beyond the reference (opt-in via ``GateThresholds``, see SURVEY
§3.5(4)):

- ``min_sample_count``: refuse until both predictors served >= N requests in
  the window, so a 2-request fluke can't drive a promotion;
- ``error_rate_floor``: absolute slack so a zero-error baseline doesn't
  deadlock the relative check on the canary's first error.

Observability: alongside the boolean and the prose reasons, the decision
carries a signed **margin** per check (budget − observed) so the rollout
journal, ``status.lastGate``, and ``tpumlops_operator_gate_margin`` can
say *how far* a canary is from promoting, not just that it isn't.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Mapping

from ..clients.base import ModelMetrics
from ..utils.config import GateThresholds

_log = logging.getLogger(__name__)


@dataclass(frozen=True)
class GateDecision:
    promote: bool
    reasons: tuple[str, ...] = ()
    # Which models had gating metrics missing (no traffic in the window):
    # any subset of {"new", "old"}.  Typed so consumers (warm-up targeting
    # in the reconciler) never parse the human-readable reason strings —
    # rewording a message must not change behavior.
    missing_on: frozenset[str] = frozenset()
    # Signed headroom per check, budget − observed (so >= 0 promotes and
    # exact boundary equality is margin 0.0): keys "latency_p95",
    # "error_rate", "latency_avg".  EMPTY — not zero — when the gate
    # refused before the budget comparisons ran (metrics missing or
    # below minSampleCount): an absent margin must never read as "right
    # at the boundary".  This is what the rollout journal, status
    # history, and tpumlops_operator_gate_margin{check} export instead
    # of leaving headroom derivable only from the prose reasons.
    margins: Mapping[str, float] = field(default_factory=dict)

    def __bool__(self) -> bool:
        return self.promote


def should_promote(
    new: ModelMetrics,
    old: ModelMetrics,
    thresholds: GateThresholds | None = None,
    logger: logging.Logger | logging.LoggerAdapter | None = None,
) -> GateDecision:
    """Return the gate decision with human-readable refusal reasons."""
    t = thresholds or GateThresholds()
    log = logger or _log
    reasons: list[str] = []

    # Availability check (reference :430-434): all three gating metrics must
    # be present on both models.  The reason names which model is missing
    # traffic so the reconciler can aim warm-up requests at that predictor.
    missing_on: set[str] = set()
    for who, m in (("new", new), ("old", old)):
        missing = [
            label
            for label, val in (
                ("latency_95th", m.latency_p95),
                ("error_rate", m.error_rate),
                ("latency_avg", m.latency_avg),
            )
            if val is None
        ]
        if missing:
            missing_on.add(who)
            reasons.append(
                f"metrics {', '.join(missing)} unavailable on {who} model "
                "(no traffic in window)"
            )
    if reasons:
        for r in reasons:
            log.warning(r)
        return GateDecision(False, tuple(reasons), frozenset(missing_on))

    # Hardening: minimum sample count before the gate may pass.
    if t.min_sample_count > 0:
        for who, m in (("new", new), ("old", old)):
            if m.request_count < t.min_sample_count:
                reasons.append(
                    f"{who} model has {m.request_count:.0f} samples "
                    f"< minSampleCount {t.min_sample_count}"
                )
        if reasons:
            for r in reasons:
                log.warning(r)
            return GateDecision(False, tuple(reasons))

    # Budgets per check; margin = budget − observed.  A negative margin
    # IS the refusal (margin < 0 ⇔ the reference's new > budget, so the
    # boundary stays inclusive: margin 0.0 promotes).
    err_budget = old.error_rate * (1 + t.error_rate)
    if t.error_rate_floor > 0:
        err_budget = max(err_budget, t.error_rate_floor)
    margins = {
        "latency_p95": old.latency_p95 * (1 + t.latency_p95) - new.latency_p95,
        "error_rate": err_budget - new.error_rate,
        "latency_avg": old.latency_avg * (1 + t.latency_avg) - new.latency_avg,
    }

    # p95 latency (reference :440-444)
    if margins["latency_p95"] < 0:
        reasons.append(
            f"p95 latency {new.latency_p95:.4f}s exceeds "
            f"{old.latency_p95:.4f}s * {1 + t.latency_p95:.2f}"
        )

    # error rate (reference :447-451), with optional absolute floor
    if margins["error_rate"] < 0:
        reasons.append(
            f"error rate {new.error_rate:.4f} exceeds budget {err_budget:.4f}"
        )

    # mean latency (reference :454-458)
    if margins["latency_avg"] < 0:
        reasons.append(
            f"mean latency {new.latency_avg:.4f}s exceeds "
            f"{old.latency_avg:.4f}s * {1 + t.latency_avg:.2f}"
        )

    if reasons:
        for r in reasons:
            log.warning(r)
        return GateDecision(False, tuple(reasons), margins=margins)
    log.info("promotion gate passed: canary within all thresholds")
    return GateDecision(True, margins=margins)
