"""SLO error-budget accounting: rolling attainment + burn rate per CR.

``spec.slo {ttftP99Ms, itlP99Ms, availabilityPct, windowMinutes}``
declares the serving objectives; this module turns the metrics the
operator ALREADY scrapes every reconcile step — TTFT/ITL p99 from the
engine series, availability from the router's gate histograms — into
the three numbers an on-call actually pages on:

- **attainment** — the fraction of in-window evaluation samples that
  met the target (each reconcile step contributes one sample per SLO;
  a sample whose signal was unobservable contributes nothing, never a
  fake pass/fail);
- **burn rate** — (1 − attainment) / (1 − objective).  1.0 means the
  error budget is being consumed exactly as fast as the objective
  allows; 2.0 means the budget will be gone in half the window;
- **error budget remaining** — max(0, 1 − burn rate) over the rolling
  window (1.0 = untouched, 0.0 = exhausted).

Exported as ``tpumlops_operator_slo_{attainment,error_budget_remaining,
burn_rate}{slo=...}`` (operator/telemetry.py) and journaled as
:class:`SloRecord` (``kind: "slo"``) into ``status.history`` /
``/debug/rollouts`` beside gate/scale/crashloop records whenever an
SLO's budget state changes — so "the canary gate refused WHILE the
availability budget was exhausted" reads straight out of the journal.

The sample windows live in operator memory (a restart restarts the
window — documented in docs/OBSERVABILITY.md; persisting per-step
samples in etcd-backed status would bloat every patch).  All pure
bookkeeping: the reconciler owns the I/O.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any

from .rollout_recorder import _iso

# Budget states (``SloRecord.state``): transitions between these are
# what gets journaled.
STATE_WITHIN = "within_budget"
STATE_EXHAUSTED = "budget_exhausted"


@dataclass(frozen=True)
class SloRecord:
    """One SLO budget-state transition, with the numbers behind it."""

    wall: float  # unix epoch seconds at evaluation time
    slo: str = ""  # ttft_p99 | itl_p99 | availability
    state: str = STATE_WITHIN
    prior_state: str | None = None  # None = first evaluation
    attainment: float | None = None
    burn_rate: float | None = None
    budget_remaining: float | None = None
    target: float | None = None  # ms for latency SLOs, pct for availability
    objective_pct: float = 99.0
    window_minutes: float = 60.0
    observed: float | None = None  # the newest raw signal reading
    samples: int = 0  # in-window samples behind the numbers

    def as_dict(self) -> dict[str, Any]:
        return {
            "kind": "slo",
            "ts": self.wall,
            "time": _iso(self.wall),
            "slo": self.slo,
            "state": self.state,
            "priorState": self.prior_state,
            "attainment": self.attainment,
            "burnRate": self.burn_rate,
            "budgetRemaining": self.budget_remaining,
            "target": self.target,
            "objectivePct": self.objective_pct,
            "windowMinutes": self.window_minutes,
            "observed": self.observed,
            "samples": self.samples,
        }


@dataclass(frozen=True)
class SloEval:
    """One SLO's rolling numbers after the current step's sample
    (telemetry feed via ``ReconcileOutcome.slo``)."""

    slo: str
    attainment: float | None  # None = no samples in window yet
    burn_rate: float | None
    budget_remaining: float | None
    samples: int = 0
    observed: float | None = None
    target: float | None = None

    @property
    def state(self) -> str | None:
        if self.burn_rate is None:
            return None  # unobservable: no state claim either way
        return STATE_EXHAUSTED if self.burn_rate >= 1.0 else STATE_WITHIN

    def as_dict(self) -> dict[str, Any]:
        return {
            "slo": self.slo,
            "attainment": self.attainment,
            "burn_rate": self.burn_rate,
            "budget_remaining": self.budget_remaining,
            "samples": self.samples,
            "observed": self.observed,
            "target": self.target,
        }


@dataclass
class SloSample:
    """One per-step observation of one SLO's SLI."""

    wall: float
    good: bool
    observed: float | None = None


class SloTracker:
    """Rolling per-SLO sample windows for one CR.

    Each reconcile step appends at most one sample per SLO (skipped
    entirely when the signal was unobservable — blindness must never
    read as attainment OR violation) and evaluates attainment over the
    samples still inside ``window_minutes``.
    """

    def __init__(self) -> None:
        self._windows: dict[str, deque] = {}

    def observe(
        self, slo: str, wall: float, good: bool,
        observed: float | None = None,
    ) -> None:
        self._windows.setdefault(slo, deque()).append(
            SloSample(wall=wall, good=bool(good), observed=observed)
        )

    def evaluate(
        self,
        slo: str,
        wall: float,
        window_s: float,
        objective_pct: float,
        target: float | None = None,
    ) -> SloEval:
        window = self._windows.setdefault(slo, deque())
        cutoff = wall - window_s
        while window and window[0].wall < cutoff:
            window.popleft()
        samples = len(window)
        if samples == 0:
            return SloEval(
                slo=slo, attainment=None, burn_rate=None,
                budget_remaining=None, samples=0, target=target,
            )
        good = sum(1 for s in window if s.good)
        attainment = good / samples
        allowed = 1.0 - objective_pct / 100.0  # > 0 (pct < 100 enforced)
        burn = (1.0 - attainment) / allowed
        observed = None
        for s in reversed(window):
            if s.observed is not None:
                observed = s.observed
                break
        return SloEval(
            slo=slo,
            attainment=attainment,
            burn_rate=burn,
            budget_remaining=max(0.0, 1.0 - burn),
            samples=samples,
            observed=observed,
            target=target,
        )

    def reset(self) -> None:
        self._windows.clear()


def collect_samples(slo_spec, model_metrics, engine_metrics) -> dict:
    """Map the scraped readings onto per-SLO SLI samples.

    Returns ``{slo_name: (good, observed)}`` with unobservable signals
    OMITTED (not recorded as either outcome):

    - ``ttft_p99`` / ``itl_p99`` — the engine p99 (seconds) vs the ms
      target;
    - ``availability`` — ``1 − error_rate`` from the gate-compatible
      router histograms; no traffic in the window (``error_rate`` None)
      is not an availability claim.
    """
    out: dict[str, tuple] = {}
    if slo_spec.ttft_p99_ms > 0 and engine_metrics is not None:
        p99_s = getattr(engine_metrics, "ttft_p99_s", None)
        if p99_s is not None:
            ms = p99_s * 1000.0
            out["ttft_p99"] = (ms <= slo_spec.ttft_p99_ms, ms)
    if slo_spec.itl_p99_ms > 0 and engine_metrics is not None:
        p99_s = getattr(engine_metrics, "itl_p99_s", None)
        if p99_s is not None:
            ms = p99_s * 1000.0
            out["itl_p99"] = (ms <= slo_spec.itl_p99_ms, ms)
    if model_metrics is not None and model_metrics.error_rate is not None:
        availability = (1.0 - model_metrics.error_rate) * 100.0
        out["availability"] = (
            availability >= slo_spec.availability_pct, availability,
        )
    return out


def target_of(slo_spec, name: str) -> float | None:
    return {
        "ttft_p99": slo_spec.ttft_p99_ms,
        "itl_p99": slo_spec.itl_p99_ms,
        "availability": slo_spec.availability_pct,
    }.get(name)
