"""Control plane: CRD-driven reconciler with metric-gated canary rollouts.

Rebuilds the reference's single-file operator (``mlflow_operator.py``) as a
level-triggered state machine:

- ``uri``        — artifact URI normalization (ref ``:18-24``)
- ``judge``      — the promotion gate decision (ref ``:419-460``)
- ``state``      — serializable promotion state (fixes SURVEY §3.5(2))
- ``builder``    — deployment manifest construction (ref ``:156-238``),
                   including the ``backend: tpu`` first-party data plane
- ``reconciler`` — the per-resource reconcile step (ref ``:26-361``,
                   without the infinite handler of §3.5(1))
- ``runtime``    — the watch/timer engine that drives reconcilers
"""

from .builder import build_deployment
from .judge import should_promote
from .reconciler import Reconciler, ReconcileOutcome
from .state import Phase, PromotionState
from .uri import artifact_uri, extract_relative_path

__all__ = [
    "artifact_uri",
    "extract_relative_path",
    "should_promote",
    "Phase",
    "PromotionState",
    "build_deployment",
    "Reconciler",
    "ReconcileOutcome",
]
