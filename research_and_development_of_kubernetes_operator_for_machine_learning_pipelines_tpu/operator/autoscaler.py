"""SLO-driven replica autoscaler: the topology half of the control plane.

The canary machinery adjusts *which version* gets traffic; until now the
operator never adjusted *how much capacity* serves it — every predictor
ran a fixed ``spec.tpu.replicas`` (default 1), so the engine-saturation
series the data plane exports (``tpumlops_engine_queue_depth``,
``tpumlops_admission_wait_ms``, ``tpumlops_ttft_seconds``) were observed
by nothing.  This module closes that loop, InferLine/λScale-style: per
``MlflowModel``, read the stable predictor's saturation signals, compute
a desired replica count against ``spec.autoscaling``, and apply it with
asymmetric hysteresis:

- **fast up** — once demand has persisted ``scaleUpStabilizationSeconds``
  (0 = immediately), jump straight to the desired count; queued users
  should not wait one cooldown per replica;
- **slow down** — step ONE replica at a time, and only after
  ``scaleDownCooldownSeconds`` since the last scale event in either
  direction, so a load dip never collapses capacity it will want back;
- **frozen during a canary** — the reconciler simply never evaluates the
  autoscaler while a rollout is in flight, so the promotion judge never
  compares versions across a topology change;
- **blind = hold** — missing metrics hold the current count; a
  Prometheus blackout must never read as "no load".

Everything here is a pure function of (spec, current state, observation,
wall time): the reconciler owns the I/O, status persistence (cooldown
and stabilization state round-trip through ``status.autoscaler`` so a
restarted operator keeps its pacing), and manifest application.  Every
decision that changes or withholds a change becomes a :class:`ScaleRecord`
in the PR-5 rollout journal (``status.history``, ``/debug/rollouts``,
``tpumlops_operator_autoscale_*``).

The data-plane half that makes scale-down safe — bounded admission with
429 shed and the lossless drain protocol — lives in ``server/app.py`` /
``server/generation.py``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Any, Mapping

from .rollout_recorder import _iso

# Hold reasons (``ScaleRecord.hold`` / the ``reason`` label on
# ``tpumlops_operator_autoscale_holds``): why a wanted scale did not run.
HOLD_METRICS_MISSING = "metrics_missing"
HOLD_STABILIZATION = "stabilization"
HOLD_COOLDOWN = "cooldown"


@dataclass(frozen=True)
class ScaleRecord:
    """One autoscaler decision, with everything it observed.

    Journaled alongside :class:`~.rollout_recorder.GateRecord` /
    :class:`~.rollout_recorder.TransitionRecord` (``kind: "scale"``), so
    a replica staircase is reconstructable from ``status.history`` or
    ``GET /debug/rollouts`` alone.  ``hold`` is ``None`` when the scale
    was applied; otherwise the typed reason it was withheld."""

    wall: float  # unix epoch seconds at evaluation time
    from_replicas: int = 0
    to_replicas: int = 0
    desired: int = 0  # the un-hysteresis'd target this evaluation wanted
    reason: str = ""
    hold: str | None = None
    version: str | None = None  # predictor version observed
    # Disaggregated pool this record sizes ("prefill"/"decode"); None =
    # the whole-predictor count (and the key is OMITTED from as_dict, so
    # pre-fleet journal records stay byte-for-byte).
    pool: str | None = None
    observed: Mapping[str, Any] = field(default_factory=dict)
    targets: Mapping[str, Any] = field(default_factory=dict)

    @property
    def applied(self) -> bool:
        return self.hold is None and self.to_replicas != self.from_replicas

    @property
    def direction(self) -> str:
        if self.hold is not None or self.to_replicas == self.from_replicas:
            return "hold"
        return "up" if self.to_replicas > self.from_replicas else "down"

    def as_dict(self) -> dict[str, Any]:
        out = {
            "kind": "scale",
            "ts": self.wall,
            "time": _iso(self.wall),
            "from": self.from_replicas,
            "to": self.to_replicas,
            "desired": self.desired,
            "direction": self.direction,
            "hold": self.hold,
            "reason": self.reason,
            "version": self.version,
            "observed": dict(self.observed),
            "targets": dict(self.targets),
        }
        if self.pool is not None:
            out["pool"] = self.pool
        return out


@dataclass(frozen=True)
class ScalerState:
    """Hysteresis state, round-tripped through ``status.autoscaler``.

    Wall-clock (unix epoch) timestamps on purpose: this state survives
    operator restarts via CR status, and the injected reconcile Clock is
    monotonic in production — a persisted monotonic reading would reset
    to ~0 on every restart and break cooldown arithmetic (the same
    lesson the rollout journal learned in the tracing PR)."""

    last_scale_wall: float = 0.0  # last applied scale, either direction
    above_since_wall: float | None = None  # demand > current since (or None)

    def to_status(self) -> dict[str, Any]:
        out: dict[str, Any] = {"lastScaleTime": self.last_scale_wall}
        if self.above_since_wall is not None:
            out["scaleUpPendingSince"] = self.above_since_wall
        return out

    @classmethod
    def from_status(cls, status: Mapping[str, Any] | None) -> "ScalerState":
        if not status:
            return cls()
        above = status.get("scaleUpPendingSince")
        return cls(
            last_scale_wall=float(status.get("lastScaleTime") or 0.0),
            above_since_wall=float(above) if above is not None else None,
        )


@dataclass(frozen=True)
class ScaleDecision:
    """What to run now, plus the state and journal record to persist."""

    replicas: int
    state: ScalerState
    record: ScaleRecord | None = None  # None = nothing worth journaling


def clamp_replicas(value: int, spec) -> int:
    return max(spec.min_replicas, min(spec.max_replicas, int(value)))


def desired_replicas(spec, current: int, observed) -> tuple[int, str]:
    """The un-hysteresis'd replica target for one observation.

    Backlog — engine queue depth plus router-parked requests — is the
    primary signal (``ceil(total / target-per-replica)``); a TTFT p95
    above budget adds one replica on top even when the queue looks fine
    — latency pressure without a backlog is what long prompts under
    packed prefill look like.  Parked requests count at full weight: a
    parked request is a user waiting on a CR with no capacity AT ALL.
    Returns ``(desired, reason)`` with the reason naming the binding
    signal.
    """
    wanted = spec.min_replicas
    reason = "idle"
    qd_target = spec.target_queue_depth_per_replica
    parked = getattr(observed, "parked", None)
    backlog_known = observed.queue_depth is not None or parked is not None
    backlog = (observed.queue_depth or 0.0) + (parked or 0.0)
    if qd_target > 0 and backlog_known:
        by_queue = math.ceil(backlog / qd_target)
        if parked and by_queue < 1:
            by_queue = 1  # a parked request needs at least one replica
        if by_queue > wanted:
            wanted = by_queue
            if parked:
                reason = (
                    f"queue depth {backlog:g} ({parked:g} parked at the "
                    f"router) / target {qd_target:g} per replica"
                )
            else:
                reason = (
                    f"queue depth {backlog:g} / target "
                    f"{qd_target:g} per replica"
                )
    ttft_target = spec.target_ttft_seconds
    if (
        ttft_target > 0
        and observed.ttft_p95_s is not None
        and observed.ttft_p95_s > ttft_target
        and current + 1 > wanted
    ):
        wanted = current + 1
        reason = (
            f"ttft p95 {observed.ttft_p95_s:.3f}s > target "
            f"{ttft_target:g}s"
        )
    return clamp_replicas(wanted, spec), reason


def decide(
    spec,
    current: int,
    state: ScalerState,
    observed,
    now_wall: float,
) -> ScaleDecision:
    """One autoscaler evaluation (pure; the reconciler applies it).

    ``spec`` is a :class:`~..utils.config.AutoscalingSpec`, ``observed``
    an :class:`~..clients.base.EngineMetrics` or ``None`` (source has no
    engine-metrics capability / query failed entirely).
    """

    def rec(to: int, desired: int, reason: str, hold: str | None):
        return ScaleRecord(
            wall=now_wall,
            from_replicas=current,
            to_replicas=to,
            desired=desired,
            reason=reason,
            hold=hold,
            observed=observed.as_dict() if observed is not None else {},
            targets={
                "queueDepthPerReplica": spec.target_queue_depth_per_replica,
                "ttftSeconds": spec.target_ttft_seconds,
                "minReplicas": spec.min_replicas,
                "maxReplicas": spec.max_replicas,
            },
        )

    parked = getattr(observed, "parked", None) if observed is not None else None
    blind = observed is None or (
        observed.queue_depth is None
        and observed.ttft_p95_s is None
        and parked is None
    )
    if blind:
        # Hold at current strength; also stop any pending scale-up clock
        # — stale demand must re-prove itself once metrics return.
        new_state = replace(state, above_since_wall=None)
        return ScaleDecision(
            replicas=current,
            state=new_state,
            record=rec(
                current, current,
                "engine metrics unavailable", HOLD_METRICS_MISSING,
            ),
        )

    desired, why = desired_replicas(spec, current, observed)

    # Wake from zero: a parked/queued request is a user ALREADY waiting,
    # so the stabilization window does not apply — every second of
    # hysteresis is a second added to their cold start.  Jump straight
    # to the demand.
    if current == 0 and desired > 0:
        return ScaleDecision(
            replicas=desired,
            state=ScalerState(last_scale_wall=now_wall, above_since_wall=None),
            record=rec(desired, desired, f"wake from zero: {why}", None),
        )

    # Scale-DOWN needs positive evidence of idleness.  With a queue
    # target configured, that evidence is the queue gauge itself — a
    # healthy TTFT cannot stand in for it (TTFT samples only admitted
    # requests; under shed the backlog pressure is exactly what TTFT
    # doesn't see).  A TTFT-only config needs a present TTFT reading —
    # and since the rate-window quantile is also None at zero traffic,
    # such a config holds its count through full idle (configure the
    # queue target to shrink).  A partially-answering source may still
    # justify GROWING; under-observing never shrinks the fleet.
    if desired < current:
        if spec.target_queue_depth_per_replica > 0:
            down_evidence = observed.queue_depth is not None
        else:
            down_evidence = observed.ttft_p95_s is not None
        if not down_evidence:
            return ScaleDecision(
                replicas=current,
                state=replace(state, above_since_wall=None),
                record=rec(
                    current, desired,
                    "idle-evidence signal unavailable; holding scale-down",
                    HOLD_METRICS_MISSING,
                ),
            )
        if current == 1 and desired == 0 and parked is None:
            # The LAST step to zero additionally needs the park signal
            # wired (router /router/parked observable): without it the
            # wake path could never see a waiting request and the CR
            # would be unreachable-forever, which is worse than one idle
            # replica.
            return ScaleDecision(
                replicas=current,
                state=replace(state, above_since_wall=None),
                record=rec(
                    current, desired,
                    "park signal unavailable; holding scale-to-zero "
                    "(the wake path needs router parked-request "
                    "visibility)",
                    HOLD_METRICS_MISSING,
                ),
            )

    if desired > current:
        since = (
            state.above_since_wall
            if state.above_since_wall is not None
            else now_wall
        )
        pending = replace(state, above_since_wall=since)
        if now_wall - since < spec.scale_up_stabilization_s:
            return ScaleDecision(
                replicas=current,
                state=pending,
                record=rec(current, desired, why, HOLD_STABILIZATION),
            )
        # Fast up: jump straight to the stabilized demand.
        return ScaleDecision(
            replicas=desired,
            state=ScalerState(
                last_scale_wall=now_wall, above_since_wall=None
            ),
            record=rec(desired, desired, why, None),
        )

    # Demand at or below current: any pending scale-up is off.
    state = replace(state, above_since_wall=None)
    if desired < current:
        since_last = now_wall - state.last_scale_wall
        if since_last < spec.scale_down_cooldown_s:
            return ScaleDecision(
                replicas=current,
                state=state,
                record=rec(current, desired, why, HOLD_COOLDOWN),
            )
        # Slow down: one replica per cooldown window, never straight to
        # the floor — the load that justified the fleet usually comes
        # back faster than a replica boots.
        to = current - 1
        return ScaleDecision(
            replicas=to,
            state=ScalerState(last_scale_wall=now_wall),
            record=rec(to, desired, why, None),
        )

    return ScaleDecision(replicas=current, state=state, record=None)


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode pools (spec.fleet) — each pool evaluated
# on ITS OWN saturation signal through the same decide() hysteresis:
#
#   prefill — admission wait p95 (queued prompts stalling before their
#             prefill begins is THE prefill-capacity signal; queue depth
#             conflates it with decode backlog);
#   decode  — the main autoscaling targets (queue depth / TTFT), which
#             at a decode pool measure token-streaming capacity.
#
# decide() reads only a duck-typed subset of AutoscalingSpec, so each
# pool gets a synthetic spec with its own band and targets — InferLine's
# "right-size each stage independently", without duplicating the
# cooldown/stabilization/blind-hold machinery.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class _PoolSpec:
    """The duck-typed subset of AutoscalingSpec that decide() reads."""

    min_replicas: int
    max_replicas: int
    target_queue_depth_per_replica: float
    target_ttft_seconds: float
    scale_up_stabilization_s: float
    scale_down_cooldown_s: float


@dataclass(frozen=True)
class FleetDecision:
    """Per-pool counts + states + journal records for one evaluation."""

    prefill: ScaleDecision
    decode: ScaleDecision

    def to_status(
        self, prior: Mapping[str, Any] | None = None
    ) -> dict[str, Any]:
        out = dict(prior or {})
        out["prefillReplicas"] = self.prefill.replicas
        out["decodeReplicas"] = self.decode.replicas
        out["prefillScaler"] = self.prefill.state.to_status()
        out["decodeScaler"] = self.decode.state.to_status()
        return out


def fleet_counts(fleet_spec, status: Mapping[str, Any] | None) -> tuple[int, int]:
    """Current (prefill, decode) pool counts: status.fleet when the
    autoscaler has taken control, else the spec counts."""
    status = status or {}
    prefill = status.get("prefillReplicas")
    decode = status.get("decodeReplicas")
    return (
        int(prefill) if prefill is not None else fleet_spec.prefill_replicas,
        int(decode) if decode is not None else fleet_spec.decode_replicas,
    )


def decide_fleet(
    auto,
    fleet_spec,
    status: Mapping[str, Any] | None,
    observed_prefill,
    observed_decode,
    now_wall: float,
) -> FleetDecision:
    """One per-pool evaluation (pure; the reconciler applies it).

    ``observed_prefill``/``observed_decode`` are per-pool
    :class:`~..clients.base.EngineMetrics` (or None = blind, which
    decide() holds on).  The prefill pool's admission-wait signal is
    mapped onto decide()'s TTFT slot — same shape (a p95 latency above a
    budget adds one replica), different series.
    """
    status = status or {}
    cur_prefill, cur_decode = fleet_counts(fleet_spec, status)

    wait_target_s = fleet_spec.prefill_target_admission_wait_ms / 1000.0
    prefill_spec = _PoolSpec(
        min_replicas=fleet_spec.prefill_min_replicas,
        max_replicas=fleet_spec.prefill_max_replicas,
        target_queue_depth_per_replica=0.0,
        target_ttft_seconds=wait_target_s,
        scale_up_stabilization_s=auto.scale_up_stabilization_s,
        scale_down_cooldown_s=auto.scale_down_cooldown_s,
    )
    decode_backlog = (
        observed_decode.queue_depth
        if observed_decode is not None and observed_decode.queue_depth
        else 0.0
    )
    if wait_target_s <= 0 or not auto.enabled:
        # Pool fixed at its current count: no signal, no record.
        dp = ScaleDecision(
            replicas=cur_prefill,
            state=ScalerState.from_status(status.get("prefillScaler")),
        )
    elif cur_prefill == 0 and decode_backlog > 0:
        # Wake from zero: a prefill pool at zero exports NO admission-
        # wait series, so its own signal can never wake it — the decode
        # pool's backlog is the fleet's "users are waiting" evidence
        # (cold prompts are falling back to unified prefill on decode
        # chips right now).  Same no-stabilization contract as the
        # predictor-level wake.
        dp = ScaleDecision(
            replicas=max(1, fleet_spec.prefill_min_replicas),
            state=ScalerState(last_scale_wall=now_wall),
            record=ScaleRecord(
                wall=now_wall,
                from_replicas=0,
                to_replicas=max(1, fleet_spec.prefill_min_replicas),
                desired=max(1, fleet_spec.prefill_min_replicas),
                reason=(
                    f"wake from zero: decode backlog {decode_backlog:g} "
                    "(cold prompts falling back to unified prefill)"
                ),
                observed=(
                    observed_decode.as_dict()
                    if observed_decode is not None
                    else {}
                ),
            ),
        )
    else:
        wait = (
            observed_prefill.admission_wait_p95_ms
            if observed_prefill is not None
            else None
        )
        from ..clients.base import EngineMetrics

        # parked=0.0 rides along whenever the wait series answers:
        # decide()'s last-step-to-zero guard demands park visibility,
        # and for a POOL the wake signal is the decode backlog above —
        # observable exactly when the wait series is (live pods).
        mapped = EngineMetrics(
            ttft_p95_s=(wait / 1000.0) if wait is not None else None,
            parked=0.0 if wait is not None else None,
        )
        dp = decide(
            prefill_spec,
            cur_prefill,
            ScalerState.from_status(status.get("prefillScaler")),
            mapped,
            now_wall,
        )
    decode_spec = _PoolSpec(
        min_replicas=fleet_spec.decode_min_replicas,
        max_replicas=fleet_spec.decode_max_replicas,
        target_queue_depth_per_replica=auto.target_queue_depth_per_replica,
        target_ttft_seconds=auto.target_ttft_seconds,
        scale_up_stabilization_s=auto.scale_up_stabilization_s,
        scale_down_cooldown_s=auto.scale_down_cooldown_s,
    )
    if not auto.enabled:
        dd = ScaleDecision(
            replicas=cur_decode,
            state=ScalerState.from_status(status.get("decodeScaler")),
        )
    else:
        dd = decide(
            decode_spec,
            cur_decode,
            ScalerState.from_status(status.get("decodeScaler")),
            observed_decode,
            now_wall,
        )

    def tag(decision: ScaleDecision, pool: str) -> ScaleDecision:
        if decision.record is None:
            return decision
        return ScaleDecision(
            replicas=decision.replicas,
            state=decision.state,
            record=replace(decision.record, pool=pool),
        )

    return FleetDecision(prefill=tag(dp, "prefill"), decode=tag(dd, "decode"))
