"""Artifact URI normalization.

The MLflow registry reports artifact sources like
``mlflow-artifacts:/1/<run>/artifacts/model``; predictors need them
re-rooted under the object store the cluster actually mounts (``s3://mlflow``
in the reference, configurable here — SURVEY §3.5(5)).

Reference behavior: ``extract_relative_path`` at ``mlflow_operator.py:18-24``
and the re-rooting at ``:125-135``.
"""

from __future__ import annotations

_MLFLOW_SCHEME = "mlflow-artifacts:/"


def extract_relative_path(source_uri: str) -> str:
    """Strip the ``mlflow-artifacts:/`` scheme (first occurrence only) and any
    leading slashes, yielding a bucket-relative path.

    Matches reference semantics exactly (``mlflow_operator.py:18-24``):
    non-mlflow-scheme URIs pass through with only the leading-slash strip.
    """
    if source_uri.startswith(_MLFLOW_SCHEME):
        relative = source_uri.replace(_MLFLOW_SCHEME, "", 1)
    else:
        relative = source_uri
    return relative.lstrip("/")


def artifact_uri(source_uri: str, artifact_root: str = "s3://mlflow") -> str:
    """Re-root an MLflow source URI under the cluster's artifact store.

    Reference: ``f"{base_uri}/{relative_path}"`` with ``base_uri`` hardcoded
    to ``s3://mlflow`` (``mlflow_operator.py:125-127``).  Already-rooted URIs
    (s3://, gs://, file://, /abs/path) whose root matches are passed through
    unchanged so the operator is idempotent over its own outputs.
    """
    root = artifact_root.rstrip("/")
    if source_uri.startswith(root + "/") or source_uri == root:
        return source_uri
    return f"{root}/{extract_relative_path(source_uri)}"
