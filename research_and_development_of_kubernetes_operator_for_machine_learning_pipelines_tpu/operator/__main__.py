"""``python -m <package>.operator`` — the operator process entrypoint
(what the operator Deployment manifest runs).

Wires the real REST clients (no cluster SDKs needed) into the runtime:
Kubernetes in-cluster auth, MLflow from ``MLFLOW_TRACKING_URI`` env (same
creds-secret convention as the reference,
``mlflow-operator-deployment.yaml:21-23``), and a per-URL-cached Prometheus
source honoring each CR's ``spec.prometheusUrl``.
"""

from __future__ import annotations

import argparse
import logging


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser("tpumlops-operator")
    ap.add_argument("--namespace", default="", help="watch one namespace (default all)")
    ap.add_argument(
        "--sync-interval",
        type=float,
        default=None,
        help="fallback resync poll (default 30s with the watch active — it "
        "only bounds staleness after a dropped watch event — or 5s under "
        "--no-watch, where the poll is the only reaction path)",
    )
    ap.add_argument(
        "--no-watch",
        action="store_true",
        help="disable the event-driven watch and rely on polling alone",
    )
    ap.add_argument(
        "--leader-elect",
        action="store_true",
        help="acquire a coordination.k8s.io Lease before reconciling, so "
        "replicas > 1 run active/standby instead of double-reconciling "
        "(the reference pins replicas: 1 and has no election)",
    )
    ap.add_argument(
        "--concurrent-reconciles",
        type=int,
        default=4,
        help="distinct CRs reconciled in parallel (one CR is never "
        "reconciled concurrently with itself); 1 = serial",
    )
    ap.add_argument(
        "--leader-elect-namespace",
        default="tpumlops-system",
        help="namespace of the election Lease",
    )
    ap.add_argument("--kube-url", default=None, help="API server URL (default in-cluster)")
    ap.add_argument("--log-level", default="INFO")
    ap.add_argument(
        "--metrics-port",
        type=int,
        default=8080,
        help="operator self-metrics /metrics (+ /debug/spans) listener; "
        "0 disables",
    )
    ap.add_argument(
        "--log-format",
        default="text",
        choices=["text", "json"],
        help="json: one JSON object per log line (machine-parseable)",
    )
    ap.add_argument(
        "--rollout-ring",
        type=int,
        default=0,
        help="per-CR rollout journal capacity (gate decisions + phase "
        "transitions, served at /debug/rollouts and "
        "/debug/rollouts/trace on the metrics listener); 0 disables — "
        "no recorder object is constructed at all",
    )
    ap.add_argument(
        "--fleet-trace-sources",
        default=None,
        help="wire GET /debug/fleet-trace on the metrics listener: inline "
        'JSON or a file path of [{"name", "base_url", "kind": '
        '"router"|"replica"}, ...] naming the fleet\'s trace endpoints '
        "(the native router runs in local/router mode today — an "
        "in-cluster router controller that would make these "
        "auto-discoverable from the routing manifest is ROADMAP item "
        "2's open end); unset = the endpoint 404s",
    )
    args = ap.parse_args(argv)

    from ..utils.logging import configure as configure_logging

    configure_logging(
        level=getattr(logging, args.log_level.upper()),
        json_format=args.log_format == "json",
    )

    from ..clients.dataplane import DataPlaneWarmup
    from ..clients.kube_rest import KubeRestClient
    from ..clients.mlflow_rest import MlflowRestClient
    from ..clients.prom_http import PrometheusSource
    from .leader import LeaderElector
    from .rollout_recorder import RolloutRecorder
    from .runtime import CrWatcher, DeploymentWatcher, OperatorRuntime
    from .telemetry import OperatorTelemetry

    if args.sync_interval is None:
        args.sync_interval = 5.0 if args.no_watch else 30.0

    kube = KubeRestClient(base_url=args.kube_url)
    registry = MlflowRestClient()
    telemetry = OperatorTelemetry()
    recorder = (
        RolloutRecorder(capacity=args.rollout_ring)
        if args.rollout_ring > 0
        else None
    )
    fleet_trace_sources = None
    if args.fleet_trace_sources:
        import json as _json
        import os as _os

        raw = args.fleet_trace_sources
        if _os.path.exists(raw):
            with open(raw) as f:
                raw = f.read()
        try:
            specs = _json.loads(raw)
        except _json.JSONDecodeError as e:
            raise SystemExit(
                f"--fleet-trace-sources is not valid JSON: {e}"
            ) from e
        if not isinstance(specs, list):
            raise SystemExit(
                "--fleet-trace-sources must be a JSON list of "
                '{"name", "base_url", "kind"} objects'
            )
        fleet_trace_sources = lambda: specs  # noqa: E731
    # The same source list drives the anomaly observatory: ring
    # snapshots for CRs with spec.anomaly, and /debug/fleet-overview.
    ring_sources = None
    if fleet_trace_sources is not None:
        from .anomaly import ring_sources_from

        ring_sources = ring_sources_from(fleet_trace_sources)
    if args.metrics_port:
        telemetry.serve(
            args.metrics_port,
            recorder=recorder,
            fleet_trace_sources=fleet_trace_sources,
        )

    sources: dict[str, PrometheusSource] = {}

    def metrics_factory(url: str) -> PrometheusSource:
        if url not in sources:
            sources[url] = PrometheusSource(url)
        return sources[url]

    import signal
    import threading

    class _Session:
        """One reconciling session: a fresh runtime + watchers.

        Fresh per leadership round on purpose: ``OperatorRuntime.stop``
        is terminal (its stop event is never cleared), and all durable
        state lives in CR status anyway — a regained leadership resumes
        exactly like an operator restart would.
        """

        def __init__(self):
            self.runtime = OperatorRuntime(
                kube=kube,
                registry=registry,
                metrics_factory=metrics_factory,
                warmup=DataPlaneWarmup(),
                namespace=args.namespace,
                sync_interval_s=args.sync_interval,
                telemetry=telemetry,
                recorder=recorder,
                max_concurrent_reconciles=args.concurrent_reconciles,
                ring_sources=ring_sources,
            )
            # Watchers start HERE, synchronously, so teardown can never
            # race a half-started serve thread into orphaning them.
            self.watchers = (
                []
                if args.no_watch
                else [
                    CrWatcher(self.runtime).start(),
                    DeploymentWatcher(self.runtime).start(),
                ]
            )
            self.thread: threading.Thread | None = None

        def serve_background(self):
            self.thread = threading.Thread(
                target=self.runtime.serve, daemon=True
            )
            self.thread.start()

        def teardown(self, drain_s: float = 0.0):
            # Leadership loss passes the election's takeover grace (one
            # renew interval): an in-flight reconcile finishing its patch
            # is fine inside the grace, a dual writer past it is not.
            self.runtime.stop(drain_s=drain_s)
            # Signal both before joining either: each stop() may wait out
            # a 15s blocked watch read, and those waits must overlap.
            for w in self.watchers:
                w._stop.set()
            for w in self.watchers:
                w.stop()
            if self.thread is not None:
                self.thread.join(timeout=30)

    if args.leader_elect:
        # Reconcile only while holding the Lease.  SIGTERM releases the
        # lease so the successor takes over immediately instead of
        # waiting out the lease duration (rolling-update gap).
        elector = LeaderElector(kube, namespace=args.leader_elect_namespace)
        session: list[_Session] = []

        def on_started():
            s = _Session()
            session[:] = [s]
            s.serve_background()

        def on_stopped():
            if session:
                session.pop().teardown(drain_s=elector.renew_interval_s)

        def _terminate(signum, frame):
            logging.getLogger(__name__).info("SIGTERM: releasing lease")
            elector.stop()

        signal.signal(signal.SIGTERM, _terminate)
        try:
            elector.run(on_started, on_stopped)
        finally:
            elector.stop()
            elector.release()
    else:
        s = _Session()
        signal.signal(
            signal.SIGTERM, lambda *_: s.runtime.stop()
        )
        try:
            s.runtime.serve()
        finally:
            s.teardown()


if __name__ == "__main__":
    main()
