"""Serializable canary-promotion state.

The reference keeps promotion progress in local variables of a blocking
loop (``traffic_current``/``traffic_prev``/``attempt`` at
``mlflow_operator.py:184-191,:296-352``); an operator restart mid-promotion
freezes the traffic split forever (SURVEY §3.5(2)).  The rebuild makes the
entire promotion a value: ``PromotionState`` round-trips through the CR
status subresource, so any operator instance can pick up a rollout exactly
where it stopped.

Status keys keep the reference's names where they exist
(``currentModelVersion`` / ``previousModelVersion`` / ``error``,
``crd.yaml:26-37``) and add the promotion-progress fields the reference
never persisted.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from enum import Enum
from typing import Any, Mapping


class Phase(str, Enum):
    """Rollout lifecycle.

    IDLE        — no version deployed yet (fresh CR).
    STABLE      — one version at 100% traffic, monitoring the alias.
    CANARY      — two predictors live, traffic shifting under the gate.
    FAILED      — gate failed max_attempts times and rollback is disabled:
                  weights frozen at last split (reference behavior,
                  ``mlflow_operator.py:342-349``).
    ROLLED_BACK — gate failed and rollback restored 100% to the old version
                  (the reference's TODO at ``:345``, implemented).
    ERROR       — alias missing: deployment torn down, error recorded
                  (``:64-93``); self-heals when the alias reappears.
    """

    IDLE = "Idle"
    STABLE = "Stable"
    CANARY = "Canary"
    FAILED = "Failed"
    ROLLED_BACK = "RolledBack"
    ERROR = "Error"


@dataclass(frozen=True)
class PromotionState:
    phase: Phase = Phase.IDLE
    current_version: str | None = None
    previous_version: str | None = None
    traffic_current: int = 0  # % of traffic on current_version
    traffic_prev: int = 0  # % of traffic on previous_version
    attempt: int = 0  # consecutive gate failures at this traffic level
    held_version: str | None = None  # version blocked after FAILED/ROLLED_BACK
    error: str | None = None
    # Rollout journal surfaced on status when spec.observability.historyLimit
    # > 0 (see operator/rollout_recorder.py for the record shapes).  Both
    # default empty AND are omitted from to_status() when empty, so an
    # unannotated CR's status stays byte-for-byte what it always was.
    # ``last_gate`` is the compact block of the most recent gate
    # evaluation; ``history`` a bounded tuple of full gate/phase records.
    last_gate: Any = None
    history: tuple = ()
    # Replica autoscaling (spec.autoscaling, operator/autoscaler.py).
    # ``replicas`` is the autoscaler-controlled predictor replica count
    # (None = autoscaling off, spec.tpu.replicas rules — and both keys
    # are omitted from to_status(), keeping an unannotated CR's status
    # byte-for-byte).  ``scaler`` is the hysteresis state dict
    # (ScalerState.to_status()): wall-clock cooldown/stabilization
    # anchors that must survive operator restarts.
    replicas: int | None = None
    scaler: Any = None
    # Scale-to-zero park context (spec.tpu.snapshot + autoscaling
    # minReplicas: 0): while the CR's Deployment is parked at zero
    # replicas, status.snapshot records WHERE the pre-baked weight
    # snapshot lives so the wake path (and a human reading kubectl -o
    # yaml) knows the restore source.  None (and omitted from status)
    # whenever the CR holds capacity.
    snapshot: Any = None
    # Disaggregated prefill/decode pools (spec.fleet.disaggregation):
    # the per-pool replica counts + hysteresis state the fleet
    # autoscaler controls, e.g. {"prefillReplicas": 1,
    # "decodeReplicas": 3, "prefillScaler": {...}, "decodeScaler":
    # {...}}.  None (and omitted from status) when disaggregation is
    # off — an unannotated CR's status stays byte-for-byte.
    fleet: Any = None
    # Multi-model multiplexing (spec.multiplex, operator/multiplexer.py):
    # this CR's view of its shared pool, e.g. {"pool": "shared-a",
    # "weight": 2.0, "attachedReplicas": [...], "parked": 3}.  None (and
    # omitted from status) when the CR is not multiplexed.
    multiplex: Any = None
    # Fleet anomaly observatory (spec.anomaly, operator/anomaly.py): the
    # active verdict list from the last detection pass, e.g.
    # [{"replica": "m-2", "kind": "straggler", "series":
    # "router_leg_p99_ms", ...}].  None (and omitted from status) when
    # anomaly detection is off — an unannotated CR stays byte-for-byte.
    anomalies: Any = None

    # -- transitions (pure; each returns a new state) -----------------------

    def with_(self, **kw: Any) -> "PromotionState":
        return dataclasses.replace(self, **kw)

    def alias_missing(self, alias: str) -> "PromotionState":
        """Reference ``:64-93``: error status, versions cleared.

        The rollout journal survives every fresh-state transition (here,
        ``new_version``, ``rolled_back``): it is this CR's audit trail,
        not a property of one rollout."""
        return PromotionState(
            phase=Phase.ERROR,
            error=f"Alias '{alias}' does not exist",
            last_gate=self.last_gate,
            history=self.history,
            replicas=self.replicas,
            scaler=self.scaler,
            snapshot=self.snapshot,
            fleet=self.fleet,
            multiplex=self.multiplex,
            anomalies=self.anomalies,
        )

    def new_version(self, version: str, initial_traffic: int) -> "PromotionState":
        """A different version now carries the alias (reference ``:97-122``).

        With no prior version the new one takes 100% immediately
        (``:188-191``); otherwise start a canary at ``initial_traffic``
        (reference hardcodes 10, ``:187``).

        The canary's baseline is the version *currently carrying the majority
        of traffic* — not blindly ``current_version`` as in the reference
        (``:101``).  Mid-canary or after a FAILED freeze, ``current_version``
        is an unproven canary at minority traffic; using it as the baseline
        would hand ~90% of traffic to a version that never earned it and
        drop the proven stable version entirely.
        """
        if self.current_version is None or self.phase in (Phase.IDLE, Phase.ERROR):
            return PromotionState(
                phase=Phase.STABLE,
                current_version=version,
                previous_version=None,
                traffic_current=100,
                traffic_prev=0,
                last_gate=self.last_gate,
                history=self.history,
                replicas=self.replicas,
                scaler=self.scaler,
                snapshot=self.snapshot,
            fleet=self.fleet,
            multiplex=self.multiplex,
            anomalies=self.anomalies,
            )
        if (
            self.previous_version is not None
            and self.traffic_prev >= self.traffic_current
        ):
            baseline = self.previous_version
        else:
            baseline = self.current_version
        if version == baseline:
            # Alias moved back to the proven version (e.g. reverting a bad
            # release): no canary needed, it is already trusted.
            return PromotionState(
                phase=Phase.STABLE,
                current_version=version,
                previous_version=None,
                traffic_current=100,
                traffic_prev=0,
                last_gate=self.last_gate,
                history=self.history,
                replicas=self.replicas,
                scaler=self.scaler,
                snapshot=self.snapshot,
            fleet=self.fleet,
            multiplex=self.multiplex,
            anomalies=self.anomalies,
            )
        return PromotionState(
            phase=Phase.CANARY,
            current_version=version,
            previous_version=baseline,
            traffic_current=initial_traffic,
            traffic_prev=100 - initial_traffic,
            attempt=0,
            last_gate=self.last_gate,
            history=self.history,
            # The scaled topology rides into (and through) the rollout
            # FROZEN: the autoscaler never evaluates mid-canary, so both
            # predictors serve at the same replica count and the judge
            # compares like with like.
            replicas=self.replicas,
            scaler=self.scaler,
            snapshot=self.snapshot,
            fleet=self.fleet,
            multiplex=self.multiplex,
            anomalies=self.anomalies,
        )

    def promoted_step(self, step: int) -> "PromotionState":
        """Gate passed: shift ``step`` % to the canary (reference ``:311-327``)."""
        new_cur = min(self.traffic_current + step, 100)
        new_prev = max(self.traffic_prev - step, 0)
        if new_cur >= 100:
            return self.with_(
                phase=Phase.STABLE,
                traffic_current=100,
                traffic_prev=0,
                previous_version=None,
                attempt=0,
            )
        return self.with_(traffic_current=new_cur, traffic_prev=new_prev, attempt=0)

    def gate_failed(self) -> "PromotionState":
        return self.with_(attempt=self.attempt + 1)

    def halt_failed(self) -> "PromotionState":
        """Max attempts exhausted, rollback disabled: freeze (ref ``:342-349``)."""
        return self.with_(phase=Phase.FAILED, held_version=self.current_version)

    def rolled_back(self) -> "PromotionState":
        """Max attempts exhausted, rollback enabled: old version back to 100%."""
        return PromotionState(
            phase=Phase.ROLLED_BACK,
            current_version=self.previous_version,
            previous_version=None,
            traffic_current=100,
            traffic_prev=0,
            held_version=self.current_version,
            last_gate=self.last_gate,
            history=self.history,
            replicas=self.replicas,
            scaler=self.scaler,
            snapshot=self.snapshot,
            fleet=self.fleet,
            multiplex=self.multiplex,
            anomalies=self.anomalies,
        )

    # -- serialization ------------------------------------------------------

    def conditions(
        self,
        prior: list[dict] | None = None,
        now_iso: str = "",
    ) -> list[dict[str, Any]]:
        """Standard K8s status Conditions derived from the phase.

        The reference exposes none; these make ``kubectl wait
        --for=condition=Available`` and dashboard tooling work:

        - ``Available``   — a version is serving traffic (Stable, mid-
          Canary, rolled back onto the old version, or halted at a
          frozen split — Failed still serves 100% of traffic);
        - ``Progressing`` — a canary rollout is in flight;
        - ``Degraded``    — promotion failed / spec or alias error /
          serving the rolled-back version.

        ``lastTransitionTime`` only moves when a condition's status
        flips (K8s convention), which is why the caller passes the prior
        conditions back in.
        """
        available = (
            self.phase
            in (Phase.STABLE, Phase.CANARY, Phase.ROLLED_BACK, Phase.FAILED)
            and self.current_version is not None
        )
        degraded_reason = {
            Phase.FAILED: ("PromotionFailed", "Canary halted at max attempts."),
            Phase.ERROR: ("Error", self.error or "reconcile error"),
            Phase.ROLLED_BACK: (
                "RolledBack",
                f"Serving previous version {self.current_version}; "
                f"version {self.held_version} held.",
            ),
        }.get(self.phase)
        desired = [
            (
                "Available",
                available,
                "Serving" if available else "NoServingVersion",
                f"Version {self.current_version} at "
                f"{self.traffic_current}% traffic."
                if available
                else "No model version is serving.",
            ),
            (
                "Progressing",
                self.phase == Phase.CANARY,
                "CanaryRollout" if self.phase == Phase.CANARY else "Idle",
                f"Canary at {self.traffic_current}% "
                f"(attempt {self.attempt})."
                if self.phase == Phase.CANARY
                else "No rollout in flight.",
            ),
            (
                "Degraded",
                degraded_reason is not None,
                degraded_reason[0] if degraded_reason else "Healthy",
                degraded_reason[1] if degraded_reason else "",
            ),
        ]
        prior_map = {c.get("type"): c for c in (prior or [])}
        out = []
        for ctype, truth, reason, message in desired:
            status = "True" if truth else "False"
            prev = prior_map.get(ctype)
            ltt = (
                prev.get("lastTransitionTime")
                if prev is not None and prev.get("status") == status
                else now_iso
            )
            out.append(
                {
                    "type": ctype,
                    "status": status,
                    "reason": reason,
                    "message": message,
                    "lastTransitionTime": ltt,
                }
            )
        return out

    def to_status(self) -> dict[str, Any]:
        status = {
            "phase": self.phase.value,
            "currentModelVersion": self.current_version,
            "previousModelVersion": self.previous_version,
            "trafficCurrent": self.traffic_current,
            "trafficPrev": self.traffic_prev,
            "attempt": self.attempt,
            "heldVersion": self.held_version,
            "error": self.error,
        }
        # Omitted — not null — when empty: historyLimit 0 (the default)
        # must keep status patches byte-identical to pre-journal behavior.
        if self.last_gate is not None:
            status["lastGate"] = self.last_gate
        if self.history:
            status["history"] = list(self.history)
        # Same contract for the autoscaler keys: absent unless autoscaling
        # has taken control of the replica count.
        if self.replicas is not None:
            status["replicas"] = self.replicas
        if self.scaler is not None:
            status["autoscaler"] = dict(self.scaler)
        if self.snapshot is not None:
            status["snapshot"] = dict(self.snapshot)
        if self.fleet is not None:
            status["fleet"] = dict(self.fleet)
        if self.multiplex is not None:
            status["multiplex"] = dict(self.multiplex)
        if self.anomalies is not None:
            status["anomalies"] = list(self.anomalies)
        return status

    @classmethod
    def from_status(cls, status: Mapping[str, Any] | None) -> "PromotionState":
        if not status:
            return cls()
        phase_raw = status.get("phase")
        current = status.get("currentModelVersion")
        try:
            if phase_raw is not None:
                Phase(phase_raw)
        except ValueError:
            # Unknown phase string (written by a newer/older operator):
            # fall through to reference-status adoption below.
            phase_raw = None
        if phase_raw is None:
            # Status written by the reference operator (versions only,
            # crd.yaml:26-37): infer a stable single-version deployment so
            # the rebuild can adopt in-place.
            phase = Phase.STABLE if current else Phase.IDLE
            return cls(
                phase=phase,
                current_version=current,
                previous_version=status.get("previousModelVersion"),
                traffic_current=100 if current else 0,
                error=status.get("error"),
            )
        return cls(
            phase=Phase(phase_raw),
            current_version=current,
            previous_version=status.get("previousModelVersion"),
            traffic_current=int(status.get("trafficCurrent") or 0),
            traffic_prev=int(status.get("trafficPrev") or 0),
            attempt=int(status.get("attempt") or 0),
            held_version=status.get("heldVersion"),
            error=status.get("error"),
            last_gate=status.get("lastGate"),
            history=tuple(status.get("history") or ()),
            replicas=(
                int(status["replicas"])
                if status.get("replicas") is not None
                else None
            ),
            scaler=status.get("autoscaler"),
            snapshot=status.get("snapshot"),
            fleet=status.get("fleet"),
            multiplex=status.get("multiplex"),
            anomalies=(
                list(status["anomalies"])
                if status.get("anomalies") is not None
                else None
            ),
        )
