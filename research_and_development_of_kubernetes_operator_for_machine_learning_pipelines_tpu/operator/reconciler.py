"""Level-triggered reconciler for ``MlflowModel`` resources.

The reference's handler is a ``while True`` loop that never returns
(``mlflow_operator.py:56``), blocks the whole event loop with synchronous
network calls, spawns a duplicate loop on every CR edit, and holds promotion
progress in local variables (SURVEY §3.5(1-3)).  The rebuild inverts that:
``Reconciler.reconcile`` is a *single step* — read the world, compute the
next state, apply it, persist it to status, and tell the runtime when to
call back.  Crash/restart at any point resumes from status.

One step performs at most one state transition, so each call is short and
the runtime can interleave many resources on one thread.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any

from ..clients.base import (
    AliasNotFound,
    ApiError,
    Conflict,
    Event,
    KubeClient,
    MetricsSource,
    ModelVersion,
    NotFound,
    ObjectRef,
    RegistryClient,
    RegistryError,
    MLFLOWMODEL,
    SELDONDEPLOYMENT,
)
from ..utils.clock import Clock, SystemClock
from ..utils.config import (
    OperatorConfig,
    TPU_HBM_GIB_PER_CHIP,
    TPU_TOPOLOGIES,
)
from ..utils.logging import model_logger
from .builder import build_deployment
from .judge import should_promote
from .rollout_recorder import CrashLoopRecord, GateRecord, TransitionRecord
from .state import Phase, PromotionState
from .uri import artifact_uri

# One structured JSON decision line per gate evaluation (the control
# plane's analogue of the server's ``tpumlops.request`` completion line):
# CR identity + decision + margins, machine-parseable in both log modes.
_gate_log = logging.getLogger("tpumlops.gate")


def _capacity_summary(config: OperatorConfig) -> "dict | None":
    """``status.capacity``: what the operator scheduled, in device terms
    — topology, chips, HBM — so the CR itself answers "how much hardware
    does this model hold" (the server's ledger answers how it is spent).
    None unless ``spec.tpu.observability.deviceTelemetry`` on a ``tpu``
    backend: the disabled status patch stays byte-for-byte."""
    if config.backend != "tpu" or not config.tpu.observability.device_telemetry:
        return None
    info = TPU_TOPOLOGIES.get(config.tpu.topology)
    if info is None:
        return None
    hbm_per_chip = TPU_HBM_GIB_PER_CHIP.get(info.accelerator)
    out = {
        "topology": config.tpu.topology,
        "chips": info.chips,
        "hosts": info.hosts,
        "meshShape": dict(config.tpu.mesh_shape),
        # The tp axis pulled out of the mesh for dashboards/selectors:
        # > 1 means one replica spans tensorParallel chips and the HBM
        # numbers below divide across them.
        "tensorParallel": int(dict(config.tpu.mesh_shape).get("tp", 1)),
        "quantize": config.tpu.quantize,
        "deviceTelemetry": True,
    }
    if hbm_per_chip is not None:
        out["hbmGiBPerChip"] = hbm_per_chip
        out["hbmGiBTotal"] = hbm_per_chip * info.chips
    return out


class _OpTimer:
    """Context manager accumulating wall seconds into ``sink[component]``."""

    __slots__ = ("_sink", "_component", "_t0")

    def __init__(self, sink: dict, component: str):
        self._sink = sink
        self._component = component

    def __enter__(self):
        self._t0 = time.perf_counter()

    def __exit__(self, *exc):
        self._sink[self._component] = self._sink.get(self._component, 0.0) + (
            time.perf_counter() - self._t0
        )
        return False


@dataclass
class ReconcileOutcome:
    state: PromotionState
    requeue_after: float  # seconds until the runtime should reconcile again
    events: list[Event] = field(default_factory=list)
    applied: bool = False  # whether a deployment manifest was written
    # Seconds per operation class within this step (status_patch,
    # manifest_apply, gate_read, registry) — the overhead breakdown the
    # time-to-100% bench and operator telemetry report (VERDICT r2 #10).
    timings: dict = field(default_factory=dict)
    # The step's GateRecord when this step evaluated the promotion gate
    # (None otherwise); OperatorTelemetry reads it for the
    # tpumlops_operator_gate_* series.
    gate: Any = None
    # The step's ScaleRecord when this step evaluated the autoscaler
    # (None otherwise — including every step with autoscaling disabled);
    # OperatorTelemetry reads it for tpumlops_operator_autoscale_*.
    scale: Any = None
    # {slo_name: SloEval} when spec.slo is configured (None otherwise);
    # OperatorTelemetry reads it for the tpumlops_operator_slo_* gauges.
    slo: Any = None
    # The step's MuxRecords when this CR is multiplexed (None otherwise);
    # OperatorTelemetry reads them for tpumlops_operator_mux_*.
    mux: Any = None
    # The step's AnomalyRecords when this step journaled a verdict-set
    # transition (None otherwise — including every step with
    # spec.anomaly absent); OperatorTelemetry reads them for
    # tpumlops_operator_anomaly_*.
    anomaly: Any = None


class Reconciler:
    """Reconciles one ``MlflowModel`` resource.

    All collaborators are injected protocols (SURVEY §4's fake seams):
    ``kube`` (API server), ``registry`` (MLflow), ``metrics`` (Prometheus),
    ``clock`` (pacing).
    """

    def __init__(
        self,
        name: str,
        namespace: str,
        kube: KubeClient,
        registry: RegistryClient,
        metrics: MetricsSource | None = None,
        clock: Clock | None = None,
        logger: logging.Logger | logging.LoggerAdapter | None = None,
        metrics_factory=None,  # Callable[[str], MetricsSource]; honors spec.prometheusUrl
        warmup=None,  # Callable[(deployment, predictor, namespace, n)]; synthetic traffic
        recorder=None,  # RolloutRecorder | None; per-CR gate/phase journal
        wall=None,  # Callable[[], float]; unix-epoch seconds (tests inject)
        mux_pools=None,  # Mapping[str, multiplexer.Multiplexer] | None
        ring_sources=None,  # Callable[[], dict] | None; fleet ring snapshots
    ):
        self.name = name
        self.namespace = namespace
        self.kube = kube
        self.registry = registry
        self.metrics = metrics
        self.metrics_factory = metrics_factory
        self.warmup = warmup
        self.clock = clock or SystemClock()
        self.log = logger or model_logger(name, namespace)
        if metrics is None and metrics_factory is None:
            raise ValueError("either metrics or metrics_factory is required")
        # (model, version) -> registry source URI.  An MLflow version's
        # source is immutable once registered, so resolve each version once
        # — the reference does the same (resolves at version-change time,
        # ``mlflow_operator.py:125-135``); without this every canary step
        # pays up to two registry round-trips re-resolving both versions.
        # The cache holds the raw source, NOT the final artifact URI:
        # spec.artifactRoot is mutable, so rooting must happen per call.
        # Freshness: alias resolutions overwrite the current version's
        # entry, and AliasNotFound clears the cache (a deleted/re-created
        # registered model restarts version numbering with new sources).
        self._source_cache: dict[tuple[str, str], str] = {}
        self._timings: dict[str, float] = {}
        self.recorder = recorder
        # Gate/phase records produced by the current step, flushed to the
        # recorder (with the step's full op-timer breakdown) in reconcile().
        self._pending_records: list = []
        # Stuck-canary event rate limiter: the (traffic, reasons) of the
        # last PromotionHold Warning actually emitted, and how many
        # identical refusals have been suppressed since.
        self._last_hold: tuple | None = None
        self._hold_suppressed = 0
        # Autoscaler wiring.  ``wall`` is unix-epoch time (NOT the
        # injected Clock, which is monotonic in production): cooldown /
        # stabilization anchors persist in CR status across operator
        # restarts, where a monotonic reading would reset to ~0.
        self._wall = wall or time.time
        # Journal rate limiter for autoscaler holds: the (hold, desired,
        # current) shape of the last hold record journaled — an
        # unchanged "cooldown" hold must not append one record per poll.
        self._last_scale_hold: tuple | None = None
        # The step's ScaleRecord (telemetry feed), set by _autoscale_step.
        self._scale_record = None
        # SLO error-budget accounting (operator/slo.py): rolling sample
        # windows live in operator memory (a restart restarts the
        # window), budget-state transitions journal beside gate/scale
        # records, and the latest evals feed tpumlops_operator_slo_*.
        self._slo_tracker = None
        self._slo_last_state: dict = {}
        self._slo_evals = None
        # The step's engine-metrics reading, stashed by _autoscale_step
        # so _slo_step reuses it instead of issuing a second identical
        # fetch (False = no fetch ran this step; None = fetched blind).
        self._step_engine_obs: object = False
        # Offline SLO planner (operator/planner.py): plans are pure
        # functions of (spec.planner, topology, trace), so each is
        # computed once and cached until the spec or trace file changes
        # — a reconcile poll must not re-run the grid search.
        self._plan_cache: dict = {}
        # Shared-pool multiplexers (operator/multiplexer.py), keyed by
        # spec.multiplex.poolRef and SHARED across every member CR's
        # reconciler — the runtime (or a test harness) owns the mapping.
        # None/missing pool = this CR surfaces status only; the pump,
        # journal drain, and mux events all no-op.
        self.mux_pools = mux_pools
        # Fleet anomaly observatory (spec.anomaly, operator/anomaly.py).
        # ``ring_sources`` is a zero-arg callable returning
        # ``{"replicas": {name: server-ring snapshot}, "router":
        # router-ring snapshot | None}`` — the reconciler never does its
        # own HTTP; the runtime (or a test) owns the fetching.  The
        # verdict-set shape of the last journaled transition dedupes the
        # journal/event stream exactly like the PromotionHold limiter;
        # None = unknown (rebuilt from status.anomalies on the first
        # step, so an operator restart doesn't re-announce a standing
        # verdict).
        self.ring_sources = ring_sources
        self._anomaly_last_shape: "frozenset | None" = None
        self._anomaly_records = None
        # Replicas currently under a straggler verdict — read by the
        # multiplexer pump (straggler = last-choice attach target).
        # None = unknown until the first step reads status back.
        self._stragglers: "frozenset | None" = None

    def _metrics_source(self, config: OperatorConfig) -> MetricsSource:
        """Fixed source (tests) or per-CR source from spec.prometheusUrl."""
        if self.metrics is not None:
            return self.metrics
        return self.metrics_factory(config.prometheus_url)

    # -- object refs --------------------------------------------------------

    @property
    def cr_ref(self) -> ObjectRef:
        return ObjectRef(namespace=self.namespace, name=self.name, **MLFLOWMODEL)

    @property
    def deployment_ref(self) -> ObjectRef:
        return ObjectRef(namespace=self.namespace, name=self.name, **SELDONDEPLOYMENT)

    # -- main entry ----------------------------------------------------------

    def _op_timer(self, component: str):
        """Accumulate wall time of one operation class into the step's
        timing breakdown (read back through ReconcileOutcome.timings)."""
        return _OpTimer(self._timings, component)

    def reconcile(self, obj: dict) -> ReconcileOutcome:
        """One reconcile step for the given CR object (spec+status+metadata)."""
        self._timings = {}
        self._pending_records = []
        self._scale_record = None
        self._mux_records = None
        self._anomaly_records = None
        self._step_engine_obs = False
        # Reset per step: an early-returning _slo_step (spec didn't
        # parse, nothing serving) must export NO evals, not re-export
        # the previous step's numbers as if live accounting ran.
        self._slo_evals = None
        # Per-CR log identity: metadata.generation on every line of this
        # step (the control-plane analogue of the server's request_id).
        if hasattr(self.log, "set_generation"):
            self.log.set_generation(
                (obj.get("metadata") or {}).get("generation")
            )
        outcome = self._reconcile_inner(obj)
        # Capacity-summary sync runs on EVERY path (ERROR-parked and
        # held CRs included — the journal keys have per-branch shedding,
        # capacity is cheaper to sync centrally): one patch when the
        # spec-derived summary differs from what status carries.
        self._sync_capacity_status(outcome.state)
        # Planner-output sync mirrors it: status.plan appears/refreshes/
        # clears with one patch when the computed plan differs from what
        # status carries; a disabled planner on a CR that never had the
        # key patches nothing (byte-for-byte).
        self._sync_plan_status(outcome.state)
        # Replica-churn audit runs centrally too (every path, ERROR-
        # parked CRs included): restart counts are observation, not
        # rollout logic, and must keep flowing while a canary is stuck.
        outcome.state = self._sync_restart_audit(outcome.state)
        # SLO accounting is observation too: it samples every step —
        # canary steps included (an SLO breach DURING a rollout is
        # exactly what the journal must be able to show).
        outcome.state = self._slo_step(outcome.state, outcome.events)
        outcome.slo = self._slo_evals
        # Anomaly detection is fleet observation on the same footing:
        # every path, stuck canaries included — a straggler mid-rollout
        # is precisely what the observatory exists to catch.
        outcome.state = self._anomaly_step(outcome.state, outcome.events)
        outcome.anomaly = self._anomaly_records
        outcome.timings = self._timings
        outcome.scale = self._scale_record
        outcome.mux = self._mux_records
        # Flush the step's journal records.  Gate records get the step's
        # COMPLETE op-timer breakdown here (the status.history copy was
        # written mid-step, before its own status_patch could be timed).
        for rec in self._pending_records:
            if isinstance(rec, GateRecord):
                rec = dataclasses.replace(rec, timings=dict(self._timings))
                outcome.gate = rec
            if self.recorder is not None:
                self.recorder.record(self.namespace, self.name, rec)
        return outcome

    def _reconcile_inner(self, obj: dict) -> ReconcileOutcome:
        # Prior conditions feed lastTransitionTime stability (state.py).
        self._prior_conditions = (obj.get("status") or {}).get("conditions")
        prior_status = obj.get("status") or {}
        self._had_journal_keys = bool(
            prior_status.get("lastGate") or prior_status.get("history")
        )
        # Same explicit-null contract for the autoscaler keys: a CR whose
        # autoscaling was just disabled needs one patch clearing them.
        self._had_scaler_keys = (
            prior_status.get("replicas") is not None
            or prior_status.get("autoscaler") is not None
        )
        # Scale-to-zero park context: same explicit-null contract — a CR
        # waking from zero needs one patch clearing status.snapshot.
        self._had_snapshot_key = prior_status.get("snapshot") is not None
        # Disaggregated-fleet pool counts: same explicit-null contract.
        self._had_fleet_key = prior_status.get("fleet") is not None
        # Multiplexed-pool view: same explicit-null contract.
        self._had_multiplex_key = prior_status.get("multiplex") is not None
        # Anomaly verdicts: same explicit-null contract; the straggler
        # set and journal-dedupe shape also rebuild from status here so
        # an operator restart neither re-announces a standing verdict
        # nor forgets which replicas the multiplexer should avoid.
        self._had_anomalies_key = prior_status.get("anomalies") is not None
        if self._stragglers is None:
            prior_anoms = prior_status.get("anomalies") or ()
            self._stragglers = frozenset(
                a.get("replica")
                for a in prior_anoms
                if isinstance(a, dict) and a.get("kind") == "straggler"
            )
            if self._anomaly_last_shape is None:
                self._anomaly_last_shape = frozenset(
                    (
                        a.get("replica"),
                        a.get("kind"),
                        a.get("series"),
                        a.get("direction"),
                    )
                    for a in prior_anoms
                    if isinstance(a, dict)
                )
        # Device-telemetry capacity summary: recomputed from spec each
        # step (no state round-trip needed); the explicit-null contract
        # mirrors the journal/scaler keys so disabling clears it once.
        self._had_capacity_key = prior_status.get("capacity") is not None
        self._prior_capacity = prior_status.get("capacity")
        self._capacity_status = None
        # Unknown until the spec parses: a config-error step must leave
        # status.capacity untouched (neither refreshed nor nulled) — the
        # summary still reflects the last VALID spec, and a transient
        # typo in an unrelated field must not wipe it.
        self._capacity_known = False
        # Replica-churn audit (PR 13): container restart counts across
        # this CR's pods surface as ``status.restarts`` when the rollout
        # journal is enabled.  Same explicit-null contract; same
        # config-error caution (an unparseable spec leaves the key
        # untouched).
        self._had_restarts_key = prior_status.get("restarts") is not None
        self._prior_restarts = prior_status.get("restarts")
        self._restarts_status = None
        self._restarts_known = False
        self._audit_config = None
        # Offline planner output (status.plan): same explicit-null
        # contract as capacity, and the same config-error caution — an
        # unparseable spec leaves the key untouched.
        self._had_plan_key = prior_status.get("plan") is not None
        self._prior_plan = prior_status.get("plan")
        self._plan_status = None
        self._plan_known = False
        state = PromotionState.from_status(obj.get("status"))
        events: list[Event] = []
        try:
            config = OperatorConfig.from_spec(obj.get("spec") or {})
        except ValueError as e:
            return self._on_config_error(state, str(e), events)
        # Offline SLO planner: compute/refresh the costed plan before
        # the capacity summary so applyMode: apply's knob substitution
        # is what capacity (and every manifest below) describes.  A
        # planner failure — unreadable/drifted trace, infeasible
        # objective — is a spec problem and surfaces exactly like one.
        try:
            config, state = self._planner_step(config, state)
        except ValueError as e:
            return self._on_config_error(state, f"planner: {e}", events)
        self._capacity_status = _capacity_summary(config)
        self._capacity_known = True
        self._audit_config = config

        # 1. Resolve alias -> version (reference :57-62).
        try:
            with self._op_timer("registry"):
                mv = self.registry.get_version_by_alias(
                    config.model_name, config.model_alias
                )
        except AliasNotFound:
            # A vanished alias often means the registered model was deleted;
            # if it is re-created, version numbers restart at 1 with new
            # sources — cached sources for the old incarnation would serve
            # stale artifacts, so drop them.
            self._source_cache.clear()
            return self._on_alias_missing(obj, config, state, events)
        except RegistryError as e:
            # Transport error: unlike the reference (which tears the
            # deployment down on *any* registry exception, :61-93), keep the
            # last-known-good data plane and retry.
            self.log.warning(f"registry unreachable, keeping current state: {e}")
            return ReconcileOutcome(state, config.monitoring_interval_s, events)
        # Upsert the freshly resolved source unconditionally: if the
        # registered model was deleted and re-created between reconciles
        # (version numbers restart with new sources), the alias resolution
        # in hand is the truth and any cached entry for this version is
        # stale.
        self._source_cache[(config.model_name, mv.version)] = mv.source

        # 2. Blocked version (post-rollback hold): don't redeploy a version
        #    that just failed its SLOs until the alias moves on.
        if (
            state.held_version is not None
            and mv.version == state.held_version
            and state.phase in (Phase.FAILED, Phase.ROLLED_BACK)
        ):
            self._ensure_deployment(obj, config, state)
            state = self._shed_disabled_journal(config, state)
            state = self._autoscale_step(obj, config, state, events)
            state = self._fleet_step(obj, config, state, events)
            state = self._multiplex_step(obj, config, state, events)
            return ReconcileOutcome(state, config.monitoring_interval_s, events)

        # 3. New version detected (reference :97-149).
        if mv.version != state.current_version:
            return self._on_new_version(obj, config, state, mv, events)

        # 4. Canary in progress: one gate evaluation (reference :296-352).
        if state.phase == Phase.CANARY:
            return self._on_canary_step(obj, config, state, events)

        # 5. Steady state: self-heal the deployment if it vanished, keep
        #    monitoring the alias, and size the topology to the load.
        #    The autoscaler runs ONLY here (and on the held-version
        #    branch above) — never mid-CANARY, so the promotion judge
        #    never compares versions across a topology change.
        if state.phase in (Phase.STABLE, Phase.FAILED, Phase.ROLLED_BACK):
            self._ensure_deployment(obj, config, state)
            state = self._shed_disabled_journal(config, state)
            state = self._autoscale_step(obj, config, state, events)
            state = self._fleet_step(obj, config, state, events)
            state = self._multiplex_step(obj, config, state, events)
        return ReconcileOutcome(state, config.monitoring_interval_s, events)

    def _planner_step(
        self, config: OperatorConfig, state: PromotionState
    ) -> "tuple[OperatorConfig, PromotionState]":
        """Offline SLO planner (operator/planner.py): compute the costed
        plan behind ``spec.planner``, journal a ``PlanRecord`` when it
        changes, and — under ``applyMode: apply`` — return the config
        with the chosen knobs substituted so everything downstream
        (capacity summary, manifests) describes the planned fleet.
        ``suggest`` (the default) changes NOTHING but ``status.plan``."""
        if not config.planner.enabled:
            self._plan_status = None
            self._plan_known = True
            return config, state
        from . import planner as planner_mod

        # Cache key: the planner inputs.  tracePath contributes its
        # mtime so replacing the export file on disk re-plans without a
        # spec edit.
        key_src: dict = {
            "planner": dataclasses.asdict(config.planner),
            "topology": config.tpu.topology,
        }
        if config.planner.trace_path:
            try:
                key_src["traceMtime"] = os.stat(
                    config.planner.trace_path
                ).st_mtime_ns
            except OSError:
                pass  # load_journey_trace will raise the typed error
        key = json.dumps(key_src, sort_keys=True, default=str)
        plan_dict = self._plan_cache.get(key)
        if plan_dict is None:
            with self._op_timer("planner"):
                plan_dict = planner_mod.plan_for_config(config)
            self._plan_cache.clear()  # one live plan per CR
            self._plan_cache[key] = plan_dict
        self._plan_status = plan_dict
        self._plan_known = True
        if plan_dict != getattr(self, "_prior_plan", None):
            rec = planner_mod.PlanRecord(
                ts=self.clock.now(),
                wall=time.time(),
                apply_mode=config.planner.apply_mode,
                objective=dict(plan_dict.get("objective", {})),
                knobs=dict(plan_dict.get("knobs", {})),
                predicted=dict(plan_dict.get("predicted", {})),
            )
            state = self._journal(config, state, rec)
        if config.planner.apply_mode == "apply":
            config = planner_mod.apply_plan(config, plan_dict)
        return config, state

    def _sync_plan_status(self, state: PromotionState) -> None:
        """Quiescent-CR plan sync, mirroring the capacity sync: one
        patch when the computed plan differs from what status carries
        (including the clearing null when the planner was disabled)."""
        if not getattr(self, "_plan_known", False):
            return  # config never parsed this step: leave status alone
        plan_dict = self._plan_status
        prior = getattr(self, "_prior_plan", None)
        if plan_dict == prior:
            return
        if plan_dict is None and not getattr(self, "_had_plan_key", False):
            return
        self._patch_status(state)

    def _sync_capacity_status(self, state: PromotionState) -> None:
        """Quiescent-CR capacity sync: transitions carry the key on their
        own patches, but a STABLE CR whose deviceTelemetry was just
        toggled (or whose topology spec changed) would otherwise never
        see status.capacity appear/refresh/clear — one patch, then
        steady state is patch-free again."""
        if not getattr(self, "_capacity_known", False):
            return  # config never parsed this step: leave status alone
        cap = self._capacity_status
        prior = getattr(self, "_prior_capacity", None)
        if cap == prior:
            return
        if cap is None and not getattr(self, "_had_capacity_key", False):
            return
        self._patch_status(state)

    # -- replica-churn audit (restart counts -> status.restarts) -------------

    @property
    def pods_ref(self) -> ObjectRef:
        return ObjectRef(
            namespace=self.namespace, name="", group="", version="v1",
            plural="pods",
        )

    def _collect_restarts(self) -> dict | None:
        """Summed container restart counts for this CR's pods (matched by
        the builder's ``tpumlops/deployment`` label), as the
        ``status.restarts`` block: ``{"total": N, "pods": {name: n}}``
        with zero-restart pods omitted (steady state stays compact and a
        fresh fleet reads ``{"total": 0, "pods": {}}``).  None = the pod
        listing failed (RBAC, API hiccup) — leave status untouched
        rather than publishing a fake zero."""
        try:
            pods = self.kube.list(self.pods_ref)
        except Exception as e:  # NotFound / ApiError / transport
            self.log.warning(f"pod listing for restart audit failed: {e}")
            return None
        total = 0
        per_pod: dict[str, int] = {}
        reasons: list[str] = []
        for pod in pods:
            meta = pod.get("metadata") or {}
            if (meta.get("labels") or {}).get(
                "tpumlops/deployment"
            ) != self.name:
                continue
            n = 0
            for cs in (pod.get("status") or {}).get(
                "containerStatuses"
            ) or []:
                n += int(cs.get("restartCount") or 0)
                term = (cs.get("lastState") or {}).get("terminated") or {}
                if term.get("reason"):
                    reasons.append(str(term["reason"]))
            if n > 0:
                per_pod[meta.get("name", "")] = n
            total += n
        return {
            "total": total,
            "pods": dict(sorted(per_pod.items())),
            **({"lastReason": reasons[-1]} if reasons else {}),
        }

    def _sync_restart_audit(self, state: PromotionState) -> PromotionState:
        """Surface replica churn next to the gate decisions.

        Gated on ``spec.observability.historyLimit`` (the journal knob):
        at the default 0 no pods are listed and every status patch is
        byte-for-byte what it was.  When the summed restart count GROWS,
        a ``ReplicaCrashLoop`` Warning fires (deduped: an unchanged
        total never re-fires, across operator restarts too — the prior
        total is read back from status) and a ``crashloop`` record joins
        ``status.history``."""
        config = self._audit_config
        if config is None:
            # The spec didn't parse this step: like the capacity summary,
            # neither refresh nor clear — the block reflects the last
            # VALID spec, and wiping it would reset the crash-loop dedupe
            # baseline (a re-fired ReplicaCrashLoop for churn already
            # announced once the typo is fixed).
            return state
        if config.observability.history_limit <= 0:
            if getattr(self, "_had_restarts_key", False):
                # Journal disabled with the key lingering: one explicit-
                # null patch clears it, then steady state is patch-free.
                self._restarts_known = True
                self._restarts_status = None
                self._patch_status(state)
            return state
        with self._op_timer("restart_audit"):
            rs = self._collect_restarts()
        if rs is None:
            return state  # listing failed: neither refresh nor null
        self._restarts_known = True
        self._restarts_status = rs
        prior = self._prior_restarts if isinstance(
            self._prior_restarts, dict
        ) else None
        prior_total = int((prior or {}).get("total") or 0)
        if rs["total"] > prior_total:
            prior_pods = (prior or {}).get("pods") or {}
            grown = tuple(
                (pod, n)
                for pod, n in rs["pods"].items()
                if n > int(prior_pods.get(pod) or 0)
            )
            ev = Event(
                "Warning",
                "ReplicaCrashLoop",
                f"Replica restarts {prior_total} -> {rs['total']} "
                + ", ".join(f"{pod} x{n}" for pod, n in grown)
                + (
                    f" (last: {rs['lastReason']})"
                    if rs.get("lastReason")
                    else ""
                ),
            )
            self.kube.emit_event(self.cr_ref, ev)
            rec = CrashLoopRecord(
                wall=self._wall(),
                total=int(rs["total"]),
                prior_total=prior_total,
                pods=grown,
                reason=str(rs.get("lastReason") or ""),
            )
            state = self._journal(config, state, rec)
            self._patch_status(state)
        elif rs != prior:
            # Count shrank (pod replaced) or details shifted: refresh the
            # block quietly — churn DOWN is not an alert.
            self._patch_status(state)
        return state

    def _engine_fetch(self, fetch, predictor: str, window_s, slo_tails: bool):
        """engine_metrics with the ``slo_tails`` hint, falling back to
        the 4-argument shape for duck-typed sources that predate it."""
        try:
            return fetch(
                self.name, predictor, self.namespace, window_s,
                slo_tails=slo_tails,
            )
        except TypeError:
            return fetch(self.name, predictor, self.namespace, window_s)

    def _slo_step(
        self, state: PromotionState, events: list[Event]
    ) -> PromotionState:
        """One SLO accounting pass (``spec.slo``; operator/slo.py).

        Samples the metrics already scraped for this CR — TTFT/ITL p99
        from the engine series, availability from the gate histograms —
        into rolling per-SLO windows, computes attainment / burn rate /
        budget remaining, and journals an ``SloRecord`` (plus a
        ``SloBudgetExhausted`` Warning) whenever an SLO's budget state
        changes.  Absent ``spec.slo`` (the default): no tracker object,
        no reads, no status writes — byte-for-byte."""
        config = self._audit_config
        if config is None:
            return state  # spec didn't parse: leave everything alone
        if not config.slo.enabled:
            if self._slo_tracker is not None:
                # spec.slo removed: drop the window and state so a
                # re-enable starts a fresh budget, not a stale one.
                self._slo_tracker = None
                self._slo_last_state = {}
            self._slo_evals = None
            return state
        if state.current_version is None:
            return state  # nothing serving yet: nothing to attain
        from . import slo as _slo

        if self._slo_tracker is None:
            self._slo_tracker = _slo.SloTracker()
        spec = config.slo
        source = self._metrics_source(config)
        predictor = f"v{state.current_version}"
        model = engine = None
        with self._op_timer("slo_read"):
            try:
                model = source.model_metrics(
                    self.name, predictor, self.namespace,
                    config.canary.metrics_window_s,
                )
            except Exception as e:
                self.log.warning(f"slo model metrics read failed: {e}")
            if self._step_engine_obs is not False:
                # The autoscale pass already read this predictor's
                # engine metrics this step (tails included, since
                # spec.slo is on): reuse instead of a second fetch.
                engine = self._step_engine_obs
            else:
                fetch = getattr(source, "engine_metrics", None)
                if fetch is not None:
                    try:
                        engine = self._engine_fetch(
                            fetch, predictor,
                            config.canary.metrics_window_s,
                            slo_tails=True,
                        )
                    except Exception as e:
                        self.log.warning(
                            f"slo engine metrics read failed: {e}"
                        )
        wall = self._wall()
        samples = _slo.collect_samples(spec, model, engine)
        window_s = spec.window_minutes * 60.0
        evals: dict = {}
        recs: list = []
        for name in spec.slo_names:
            if name in samples:
                good, observed = samples[name]
                self._slo_tracker.observe(name, wall, good, observed)
            ev = self._slo_tracker.evaluate(
                name, wall, window_s, spec.availability_pct,
                _slo.target_of(spec, name),
            )
            evals[name] = ev
            st = ev.state
            if st is not None and st != self._slo_last_state.get(name):
                recs.append(
                    _slo.SloRecord(
                        wall=wall,
                        slo=name,
                        state=st,
                        prior_state=self._slo_last_state.get(name),
                        attainment=ev.attainment,
                        burn_rate=ev.burn_rate,
                        budget_remaining=ev.budget_remaining,
                        target=ev.target,
                        objective_pct=spec.availability_pct,
                        window_minutes=spec.window_minutes,
                        observed=ev.observed,
                        samples=ev.samples,
                    )
                )
                self._slo_last_state[name] = st
        self._slo_evals = evals
        if recs:
            for rec in recs:
                if rec.state == _slo.STATE_EXHAUSTED:
                    ev = Event(
                        "Warning",
                        "SloBudgetExhausted",
                        f"SLO {rec.slo} error budget exhausted: "
                        f"attainment {rec.attainment:.4f} vs objective "
                        f"{rec.objective_pct}% over "
                        f"{rec.window_minutes:g}m (burn rate "
                        f"{rec.burn_rate:.2f}).",
                    )
                    events.append(ev)
                    self.kube.emit_event(self.cr_ref, ev)
                    self.log.warning(ev.message)
            state = self._journal(config, state, *recs)
            self._patch_status(state)
        return state

    def _anomaly_step(
        self, state: PromotionState, events: list[Event]
    ) -> PromotionState:
        """One fleet anomaly-detection pass (``spec.anomaly``;
        operator/anomaly.py).

        Pulls ring snapshots through the injected ``ring_sources``
        callable, builds the per-replica named-series windows (server
        ITL/MFU/queue PLUS the router's per-backend leg latency — the
        only vantage that sees proxy-injected slowness), and runs the
        pure ``detect()``.  A verdict-set SHAPE transition — which
        replicas/series/directions, never the jittering statistics —
        journals one ``AnomalyRecord``, emits one ``AnomalyDetected``
        Warning, and refreshes ``status.anomalies``; an unchanged
        standing verdict is silent.  Absent ``spec.anomaly`` (the
        default): no fetches, no status writes — byte-for-byte."""
        config = self._audit_config
        if config is None:
            return state  # spec didn't parse: leave everything alone
        spec = config.anomaly
        if not spec.enabled:
            self._anomaly_last_shape = frozenset()
            self._stragglers = frozenset()
            if state.anomalies is not None:
                # spec.anomaly removed with the key lingering: one
                # explicit-null patch clears it, then patch-free again.
                state = state.with_(anomalies=None)
                self._patch_status(state)
            return state
        if self.ring_sources is None:
            return state  # observatory not wired into this runtime
        from . import anomaly as _anomaly

        with self._op_timer("anomaly"):
            try:
                obs = self.ring_sources() or {}
            except Exception as e:
                self.log.warning(f"anomaly ring fetch failed: {e}")
                return state
            windows: dict = {}
            baselines: dict = {}
            for replica, snap in sorted(
                (obs.get("replicas") or {}).items()
            ):
                series = _anomaly.replica_series(snap, spec.window_s)
                if series:
                    windows[replica] = series
                base = _anomaly.baseline_of(snap, spec.baseline_s)
                if base:
                    baselines[replica] = base
            router_snap = obs.get("router")
            if router_snap:
                for replica, series in _anomaly.router_series(
                    router_snap, spec.window_s
                ).items():
                    windows.setdefault(replica, {}).update(series)
            verdicts = _anomaly.detect(windows, spec, baselines)
        shape = frozenset(v.shape for v in verdicts)
        self._stragglers = frozenset(
            v.replica for v in verdicts if v.kind == "straggler"
        )
        prev = self._anomaly_last_shape
        if prev is None:
            prev = frozenset()
        if shape == prev:
            return state  # standing verdict (or standing quiet): silent
        self._anomaly_last_shape = shape
        rec = _anomaly.AnomalyRecord(
            wall=self._wall(),
            action="detected" if verdicts else "cleared",
            verdicts=verdicts,
            replicas=len(windows),
        )
        self._anomaly_records = [rec]
        state = self._journal(config, state, rec)
        # status.anomalies carries the verdicts stamped at this
        # transition (live numbers would force a patch per poll).
        state = state.with_(anomalies=[v.as_dict() for v in verdicts])
        self._patch_status(state)
        if verdicts:
            ev = Event(
                "Warning",
                "AnomalyDetected",
                f"Fleet anomaly across {len(windows)} replicas: "
                + "; ".join(
                    f"{v.replica} {v.kind} on {v.series} "
                    f"({v.direction})"
                    for v in verdicts
                ),
            )
            events.append(ev)
            self.kube.emit_event(self.cr_ref, ev)
            self.log.warning(ev.message)
        else:
            self.log.info("fleet anomaly verdicts cleared")
        return state

    def _shed_disabled_journal(
        self, config: OperatorConfig, state: PromotionState
    ) -> PromotionState:
        """historyLimit back at 0 on a quiescent CR: the journal-writing
        paths won't run again until the next rollout, so clear the stale
        status.lastGate/history here (one extra patch, then steady state
        is patch-free again)."""
        if config.observability.history_limit > 0 or (
            state.last_gate is None and not state.history
        ):
            return state
        state = state.with_(last_gate=None, history=())
        self._patch_status(state)
        return state

    # -- replica autoscaling (operator/autoscaler.py) ------------------------

    def _autoscale_step(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        events: list[Event],
    ) -> PromotionState:
        """One autoscaler evaluation on a steady-state (non-canary) CR.

        Reads the current version's engine-saturation signals, computes
        the desired replica count with asymmetric hysteresis (pure logic
        in ``operator/autoscaler.py``), applies topology changes through
        the normal manifest path, and journals every decision as a
        ``ScaleRecord`` beside the gate/phase records.
        """
        from . import autoscaler as _scaling

        auto = config.autoscaling
        if not auto.enabled:
            if state.replicas is None and state.scaler is None:
                return state
            # Autoscaling switched off: hand the topology back to
            # spec.tpu.replicas and clear the status keys (explicit
            # nulls via _had_scaler_keys).
            state = state.with_(replicas=None, scaler=None)
            self._apply_for_state(obj, config, state)
            self._patch_status(state)
            self.log.info(
                "autoscaling disabled; replicas back to spec topology"
            )
            return state
        if state.current_version is None:
            return state

        current = state.replicas
        if current is None:
            # First evaluation after enabling: adopt the spec topology,
            # clamped into the autoscaler's band.
            current = _scaling.clamp_replicas(config.tpu.replicas, auto)
        observed = None
        source = self._metrics_source(config)
        fetch = getattr(source, "engine_metrics", None)
        if fetch is not None:
            try:
                with self._op_timer("scale_read"):
                    # slo_tails rides along when spec.slo is on, so the
                    # SLO step can reuse THIS reading instead of a
                    # second identical fetch.
                    observed = self._engine_fetch(
                        fetch,
                        f"v{state.current_version}",
                        config.canary.metrics_window_s,
                        slo_tails=config.slo.enabled,
                    )
            except Exception as e:
                # Blind = hold (decide() treats None as metrics-missing);
                # a Prometheus blip must never read as "no load".
                self.log.warning(f"engine metrics read failed: {e}")
                observed = None
            self._step_engine_obs = observed

        decision = _scaling.decide(
            auto,
            current,
            _scaling.ScalerState.from_status(state.scaler),
            observed,
            self._wall(),
        )
        record = decision.record
        if record is not None:
            record = dataclasses.replace(
                record, version=state.current_version
            )
        self._scale_record = record

        first_take = state.replicas is None
        changed = decision.replicas != current
        new_state = state.with_(
            replicas=decision.replicas, scaler=decision.state.to_status()
        )
        # Park context: while the Deployment is at zero, status.snapshot
        # records the restore source the wake path will use.
        if decision.replicas == 0:
            snap = self._snapshot_status(config, state)
            if snap is not None and new_state.snapshot != snap:
                new_state = new_state.with_(snapshot=snap)
        elif new_state.snapshot is not None:
            new_state = new_state.with_(snapshot=None)

        if changed or first_take:
            self._last_scale_hold = None
            applied_rec = record if changed else None
            if first_take and config.tpu.replicas != decision.replicas:
                # Enabling autoscaling CHANGED the running topology (the
                # spec count was clamped into the band, or the demand
                # moved it immediately): journal the real from-count and
                # arm the cooldown — an unrecorded multi-replica jump
                # would be invisible in status.history and a follow-up
                # step-down could fire with no scale event on record.
                base = record if record is not None else _scaling.ScaleRecord(
                    wall=self._wall(),
                    desired=decision.replicas,
                    reason="spec topology adopted into the autoscaling band",
                )
                applied_rec = dataclasses.replace(
                    base,
                    from_replicas=config.tpu.replicas,
                    to_replicas=decision.replicas,
                    hold=None,
                    version=state.current_version,
                )
                self._scale_record = applied_rec
                new_state = new_state.with_(
                    scaler=dataclasses.replace(
                        decision.state, last_scale_wall=self._wall()
                    ).to_status()
                )
            self._apply_for_state(obj, config, new_state)
            new_state = self._journal(config, new_state, applied_rec)
            self._patch_status(new_state)
            if applied_rec is not None and applied_rec.applied:
                if applied_rec.to_replicas == 0:
                    reason = "ScaledToZero"
                elif applied_rec.from_replicas == 0:
                    reason = "WokenFromZero"
                elif applied_rec.to_replicas > applied_rec.from_replicas:
                    reason = "ScaledUp"
                else:
                    reason = "ScaledDown"
                ev = Event(
                    "Normal",
                    reason,
                    f"Scaled replicas {applied_rec.from_replicas} -> "
                    f"{applied_rec.to_replicas} ({applied_rec.reason}).",
                )
                events.append(ev)
                self.kube.emit_event(self.cr_ref, ev)
                self.log.info(ev.message)
            return new_state

        # No topology change.  Journal a hold only when its shape is new
        # (an unchanged "cooldown" hold must not append one record per
        # poll), and patch only when something durable moved (the
        # stabilization clock arming/landing, or the journal growing).
        hold_rec = None
        if record is not None and record.hold is not None:
            hold_key = (record.hold, record.desired, current)
            if hold_key != self._last_scale_hold:
                self._last_scale_hold = hold_key
                hold_rec = record
        new_state = self._journal(config, new_state, hold_rec)
        if new_state != state:
            self._patch_status(new_state)
        return new_state

    def _fleet_step(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        events: list[Event],
    ) -> PromotionState:
        """One per-pool fleet autoscaler evaluation (disaggregated CRs,
        steady state only — frozen during canary like the whole-predictor
        autoscaler).

        The prefill pool sizes on its own admission-wait signal, the
        decode pool on the main autoscaling targets; every APPLIED
        change journals a pool-tagged ``ScaleRecord`` and re-applies the
        pool Deployments through the worker-unit sync."""
        from . import autoscaler as _scaling

        fleet = config.fleet
        if not fleet.disaggregation:
            if state.fleet is not None:
                # Disaggregation switched off: clear the status key and
                # re-apply so the worker-unit sync GCs the pool
                # Deployments/Services this CR no longer wants.
                state = state.with_(fleet=None)
                self._apply_for_state(obj, config, state)
                self._patch_status(state)
            return state
        if not config.autoscaling.enabled or state.current_version is None:
            if (
                state.fleet is not None
                and state.current_version is not None
            ):
                # Autoscaling switched off mid-flight: hand the pool
                # counts back to spec.fleet and clear the status key —
                # a stale status.fleet would silently pin the pools at
                # the autoscaler's last counts through later spec edits.
                state = state.with_(fleet=None)
                self._apply_for_state(obj, config, state)
                self._patch_status(state)
            return state
        source = self._metrics_source(config)
        fetch = getattr(source, "engine_metrics", None)
        obs_prefill = obs_decode = None
        if fetch is not None:
            try:
                with self._op_timer("scale_read"):
                    obs_prefill = fetch(
                        self.name,
                        f"v{state.current_version}-prefill",
                        self.namespace,
                        config.canary.metrics_window_s,
                    )
                    obs_decode = fetch(
                        self.name,
                        f"v{state.current_version}-decode",
                        self.namespace,
                        config.canary.metrics_window_s,
                    )
            except Exception as e:
                # Blind = hold, same contract as the predictor scaler.
                self.log.warning(f"fleet engine metrics read failed: {e}")
        decision = _scaling.decide_fleet(
            config.autoscaling, fleet, state.fleet,
            obs_prefill, obs_decode, self._wall(),
        )
        cur_prefill, cur_decode = _scaling.fleet_counts(fleet, state.fleet)
        changed = (
            decision.prefill.replicas != cur_prefill
            or decision.decode.replicas != cur_decode
        )
        new_state = state.with_(fleet=decision.to_status(state.fleet))
        applied = [
            dataclasses.replace(d.record, version=state.current_version)
            for d in (decision.prefill, decision.decode)
            if d.record is not None and d.record.applied
        ]
        if changed:
            self._apply_for_state(obj, config, new_state)
            new_state = self._journal(config, new_state, *applied)
            self._patch_status(new_state)
            for rec in applied:
                ev = Event(
                    "Normal",
                    "FleetScaled",
                    f"Scaled {rec.pool} pool {rec.from_replicas} -> "
                    f"{rec.to_replicas} ({rec.reason}).",
                )
                events.append(ev)
                self.kube.emit_event(self.cr_ref, ev)
                self.log.info(ev.message)
        elif new_state != state:
            # Stabilization/cooldown clocks moved (or the key is new):
            # persist them without journaling per-poll hold records.
            self._patch_status(new_state)
        return new_state

    def _multiplex_step(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        events: list[Event],
    ) -> PromotionState:
        """One multiplexer pass for a pool-member CR (steady state only,
        like the autoscaler — a mid-canary CR must not be swapped out
        from under the judge).

        Registers this CR with its shared-pool coordinator
        (operator/multiplexer.py), pumps one observe→plan→execute pass
        (rate-limited inside the coordinator so N members don't N-fold
        the convergence rate — attaches go through the existing
        warm-pool admin endpoint), journals the resulting MuxRecords
        into THIS CR's status.history, and publishes status.multiplex.
        Disabled = the key clears once, then byte-for-byte."""
        mux = config.multiplex
        if not mux.enabled:
            if state.multiplex is not None:
                state = state.with_(multiplex=None)
                self._patch_status(state)
            return state
        status: dict = {"pool": mux.pool_ref, "weight": mux.weight}
        coord = (self.mux_pools or {}).get(mux.pool_ref)
        recs = []
        if coord is not None:
            uri = None
            if state.current_version is not None:
                try:
                    # The ATTACHABLE artifact uri (what the pool restores
                    # from), not the raw registry source.
                    uri = self._resolve_uri(config, state.current_version)
                except Exception as e:  # registry blip: keep the last
                    self.log.warning(f"mux uri resolution failed: {e}")
            if uri:
                coord.register(self.name, uri=uri, weight=mux.weight)
            # Straggler verdicts steer placement: a flagged replica is
            # the LAST choice as an attach target.  Empty set (verdicts
            # off or all clear) leaves every decision byte-identical.
            set_stragglers = getattr(coord, "set_stragglers", None)
            if set_stragglers is not None:
                set_stragglers(self._stragglers or frozenset())
            with self._op_timer("mux_pump"):
                coord.pump()
            recs = coord.take_records(self.name)
            status.update(coord.model_status(self.name))
        self._mux_records = recs
        new_state = state.with_(multiplex=status)
        new_state = self._journal(config, new_state, *recs)
        if new_state != state:
            self._patch_status(new_state)
        for rec in recs:
            if rec.action in ("attach", "replace"):
                ev = Event(
                    "Normal",
                    "MuxAttached",
                    f"Multiplexer {rec.action}ed {rec.model} onto "
                    f"{rec.replica} in pool {rec.pool} "
                    f"(score {rec.score:g}, {rec.parked} parked).",
                )
                events.append(ev)
                self.kube.emit_event(self.cr_ref, ev)
                self.log.info(ev.message)
            elif rec.action == "error":
                ev = Event(
                    "Warning",
                    "MuxAttachFailed",
                    f"Multiplexer could not attach {rec.model}: "
                    f"{rec.reason}.",
                )
                events.append(ev)
                self.kube.emit_event(self.cr_ref, ev)
                self.log.warning(ev.message)
        return new_state

    def _snapshot_status(self, config: OperatorConfig, state) -> "dict | None":
        """``status.snapshot`` for a CR parked at zero: the deterministic
        snapshot location (``server/snapshot.py`` keys it by model URI;
        quantize/mesh invalidation lives in the manifest's content hash)
        so the wake path — and a human — can find the restore source
        without the data plane running."""
        if not config.tpu.snapshot.enabled or state.current_version is None:
            return None
        out: dict = {
            "enabled": True,
            "dir": config.tpu.snapshot.dir,
            "quantize": config.tpu.quantize,
        }
        try:
            uri = self._resolve_uri(config, state.current_version)
            from ..server.snapshot import snapshot_path_for

            out["modelUri"] = uri
            out["uri"] = str(snapshot_path_for(config.tpu.snapshot.dir, uri))
        except Exception as e:  # registry blip: park context still lands
            self.log.warning(f"snapshot URI resolution failed: {e}")
        return out

    # -- handlers ------------------------------------------------------------

    def _on_config_error(
        self, state: PromotionState, message: str, events: list[Event]
    ) -> ReconcileOutcome:
        """Invalid spec: surface it on the CR instead of only in operator logs.

        The data plane is deliberately left as-is — a spec typo must not tear
        down a serving model.  Status error + a Warning event are written only
        when the message changes, so backoff retries don't spam the stream.
        """
        err = f"invalid spec: {message}"
        new_state = state.with_(error=err)
        if state.error != err:
            self._patch_status(new_state)
            ev = Event("Warning", "InvalidSpec", err)
            events.append(ev)
            self.kube.emit_event(self.cr_ref, ev)
            self.log.error(err)
        return ReconcileOutcome(new_state, 300.0, events)

    def _on_alias_missing(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        events: list[Event],
    ) -> ReconcileOutcome:
        """Reference :64-93: error status, tear down, Warning event."""
        new_state = state.alias_missing(config.model_alias)
        changed = state != new_state
        # Strip stale journal keys if historyLimit went back to 0 — an
        # ERROR-parked CR never reaches the other shedding sites.
        new_state = self._journal(config, new_state)
        if changed:
            self._patch_status(new_state)
            self._delete_deployment()
            ev = Event(
                "Warning",
                "AliasNotFound",
                f"Alias '{config.model_alias}' does not exist.",
            )
            events.append(ev)
            self.kube.emit_event(self.cr_ref, ev)
            self.log.error(f"Alias '{config.model_alias}' does not exist.")
        elif state != new_state:
            # Journal-only cleanup: patch, but don't re-announce the
            # missing alias.
            self._patch_status(new_state)
        return ReconcileOutcome(new_state, config.monitoring_interval_s, events)

    # -- rollout journal -----------------------------------------------------

    def _journal(self, config: OperatorConfig, state: PromotionState, *records):
        """Queue journal records for the recorder flush and — when
        ``spec.observability.historyLimit`` > 0 — fold them into the
        state's status journal.  Returns the state to persist."""
        recs = [r for r in records if r is not None]
        self._pending_records.extend(recs)
        limit = config.observability.history_limit
        if limit <= 0:
            # Journal disabled: strip keys left over from when it was
            # enabled so the upcoming patch clears them.
            if state.last_gate is not None or state.history:
                return state.with_(last_gate=None, history=())
            return state
        if not recs:
            return state
        history = (state.history + tuple(r.as_dict() for r in recs))[-limit:]
        kw: dict = {"history": tuple(history)}
        for r in reversed(recs):
            if isinstance(r, GateRecord):
                kw["last_gate"] = r.compact()
                break
        return state.with_(**kw)

    def _gate_record(
        self,
        config: OperatorConfig,
        state: PromotionState,
        decision,
        new_m,
        old_m,
        traffic_after: int,
        attempt: int,
    ) -> GateRecord:
        """Everything the judge saw and decided, as one journal record.
        The timings snapshot here is what has accrued so far this step
        (registry + gate_read + any manifest apply); the recorder copy
        is re-stamped with the complete breakdown at step end."""
        return GateRecord(
            ts=self.clock.now(),
            wall=time.time(),
            new_version=state.current_version,
            old_version=state.previous_version,
            traffic_before=state.traffic_current,
            traffic_after=traffic_after,
            attempt=attempt,
            promote=bool(decision.promote),
            reasons=tuple(decision.reasons),
            missing_on=tuple(sorted(decision.missing_on)),
            margins=dict(decision.margins),
            new_metrics=new_m.as_dict(),
            old_metrics=old_m.as_dict(),
            thresholds=dataclasses.asdict(config.thresholds),
            timings=dict(self._timings),
            suppressed_events=self._hold_suppressed,
        )

    def _transition(
        self,
        from_phase: Phase,
        to_phase: Phase,
        reason: str,
        new_version: str | None,
        old_version: str | None,
        traffic: int,
    ) -> TransitionRecord:
        return TransitionRecord(
            ts=self.clock.now(),
            wall=time.time(),
            from_phase=from_phase.value,
            to_phase=to_phase.value,
            reason=reason,
            new_version=new_version,
            old_version=old_version,
            traffic=traffic,
        )

    def _log_decision(self, config: OperatorConfig, rec: GateRecord) -> None:
        payload = {
            "event": "gate_decision",
            "namespace": self.namespace,
            "name": self.name,
            "model": config.model_name,
            "newVersion": rec.new_version,
            "oldVersion": rec.old_version,
            "result": rec.result,
            "refusal": rec.refusal,
            "attempt": rec.attempt,
            "trafficBefore": rec.traffic_before,
            "trafficAfter": rec.traffic_after,
            "margins": dict(rec.margins),
            "reasons": list(rec.reasons),
            "suppressedEvents": rec.suppressed_events,
        }
        _gate_log.info(
            "%s",
            json.dumps(payload, default=str),
            extra={"cr_namespace": self.namespace, "cr_name": self.name},
        )

    def _reset_hold_dedupe(self) -> None:
        self._last_hold = None
        self._hold_suppressed = 0

    # -- handlers (continued) ------------------------------------------------

    def _on_new_version(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        mv: ModelVersion,
        events: list[Event],
    ) -> ReconcileOutcome:
        new_state = state.new_version(mv.version, config.canary.initial_traffic)
        self._reset_hold_dedupe()
        self._last_scale_hold = None  # frozen rollout: fresh dedupe after
        # Apply + persist BEFORE emitting: if the apply fails persistently,
        # status is unchanged and the next reconcile retries this branch —
        # emitting first would duplicate the event on every retry.
        applied = self._apply_for_state(obj, config, new_state, source_of_current=mv)
        new_state = self._journal(
            config,
            new_state,
            self._transition(
                state.phase,
                new_state.phase,
                "NewModelVersionDetected",
                mv.version,
                new_state.previous_version,
                new_state.traffic_current,
            ),
        )
        self._patch_status(new_state)
        ev = Event(
            "Normal",
            "NewModelVersionDetected",
            f"New model version {mv.version} detected.",
        )
        events.append(ev)
        self.kube.emit_event(self.cr_ref, ev)
        self.log.info(f"New model version detected: {mv.version}")

        # Fresh STABLE deploy (no canary): the autoscaler takes the
        # topology under control immediately, so a minReplicas floor
        # above spec.tpu.replicas applies on first deploy rather than
        # one monitoring interval later.
        if new_state.phase == Phase.STABLE:
            new_state = self._autoscale_step(obj, config, new_state, events)
            new_state = self._fleet_step(obj, config, new_state, events)
            new_state = self._multiplex_step(obj, config, new_state, events)

        # Canary: go straight to the first gate check (the reference enters
        # its metrics loop immediately after the initial apply, :296-310).
        requeue = 0.0 if new_state.phase == Phase.CANARY else config.monitoring_interval_s
        return ReconcileOutcome(new_state, requeue, events, applied=applied)

    def _on_canary_step(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        events: list[Event],
    ) -> ReconcileOutcome:
        canary = config.canary
        source = self._metrics_source(config)
        with self._op_timer("gate_read"):
            new_m = source.model_metrics(
                self.name,
                f"v{state.current_version}",
                self.namespace,
                canary.metrics_window_s,
            )
            old_m = source.model_metrics(
                self.name,
                f"v{state.previous_version}",
                self.namespace,
                canary.metrics_window_s,
            )
        self.log.info(
            f"Metrics for new model (version {state.current_version}): {new_m.as_dict()}"
        )
        self.log.info(
            f"Metrics for old model (version {state.previous_version}): {old_m.as_dict()}"
        )

        decision = should_promote(new_m, old_m, config.thresholds, self.log)
        attempt_no = state.attempt + 1  # 1-based: this evaluation's number
        if decision:
            self._reset_hold_dedupe()
            new_state = state.promoted_step(canary.step)
            rec = self._gate_record(
                config, state, decision, new_m, old_m,
                new_state.traffic_current, attempt_no,
            )
            applied = self._apply_for_state(obj, config, new_state)
            records = [rec]
            if new_state.phase == Phase.STABLE:
                records.append(
                    self._transition(
                        Phase.CANARY, Phase.STABLE, "PromotionComplete",
                        new_state.current_version, state.previous_version, 100,
                    )
                )
            new_state = self._journal(config, new_state, *records)
            self._patch_status(new_state)
            self._log_decision(config, rec)
            if new_state.phase == Phase.STABLE:
                ev = Event(
                    "Normal",
                    "PromotionComplete",
                    "New model now receives 100% traffic. "
                    "Previous model has been removed.",
                )
                requeue = config.monitoring_interval_s
            else:
                ev = Event(
                    "Normal",
                    "TrafficIncrease",
                    f"Increased traffic to new model to {new_state.traffic_current}%",
                )
                requeue = canary.step_interval_s
            events.append(ev)
            self.kube.emit_event(self.cr_ref, ev)
            self.log.info(ev.message)
            return ReconcileOutcome(new_state, requeue, events, applied=applied)

        # Gate refused.  If the refusal is missing metrics (no traffic in the
        # window — SURVEY §3.5(4) zero-traffic deadlock), send best-effort
        # synthetic warm-up traffic to the canary before the next attempt.
        # This runs on gate attempts, NOT at deploy time: right after the
        # manifest apply the canary pod/service does not exist yet, so a
        # deploy-time burst would always fail and never be retried.
        if canary.warmup_requests > 0 and self.warmup is not None:
            # The gate needs BOTH predictors' metrics; warm whichever one the
            # judge reported as missing traffic (usually the 10% canary, but a
            # drained stable predictor deadlocks the gate just the same).
            targets = []
            if "new" in decision.missing_on:
                targets.append(f"v{state.current_version}")
            if "old" in decision.missing_on:
                targets.append(f"v{state.previous_version}")
            for predictor in targets:
                try:
                    self.warmup(
                        self.name,
                        predictor,
                        self.namespace,
                        canary.warmup_requests,
                        model=config.model_name,
                    )
                    self.log.info(
                        f"sent {canary.warmup_requests} warm-up requests to "
                        f"{predictor} (gate metrics unavailable)"
                    )
                except Exception as e:
                    self.log.warning(f"warm-up traffic failed: {e}")

        new_state = state.gate_failed()
        if new_state.attempt < canary.max_attempts:
            # Stuck-canary event rate limiting: an unchanged refusal at
            # the same traffic level emits ONE Warning event, not one
            # per poll — the suppressed count rides the journal.  The
            # key is the refusal SHAPE (which checks fail / which model
            # is traffic-less), never the reason strings: those embed
            # live metric readings that jitter every poll, which would
            # defeat the dedupe exactly when it matters.
            hold_key = (
                state.traffic_current,
                tuple(sorted(decision.missing_on)),
                bool(decision.margins),  # min_sample vs threshold class
                tuple(
                    sorted(
                        k for k, v in decision.margins.items() if v < 0
                    )
                ),
            )
            if hold_key != self._last_hold:
                self._last_hold = hold_key
                self._hold_suppressed = 0
                hold_ev = Event(
                    "Warning",
                    "PromotionHold",
                    f"Gate refused promotion at {state.traffic_current}% "
                    f"(attempt {new_state.attempt}/{canary.max_attempts}): "
                    + "; ".join(decision.reasons),
                )
                events.append(hold_ev)
                self.kube.emit_event(self.cr_ref, hold_ev)
            else:
                self._hold_suppressed += 1
            rec = self._gate_record(
                config, state, decision, new_m, old_m,
                state.traffic_current, attempt_no,
            )
            new_state = self._journal(config, new_state, rec)
            self._patch_status(new_state)
            self._log_decision(config, rec)
            self.log.info(
                f"Attempt {new_state.attempt}/{canary.max_attempts}: metrics do not "
                f"meet conditions, retrying after {canary.attempt_delay_s} seconds."
            )
            return ReconcileOutcome(new_state, canary.attempt_delay_s, events)

        # Max attempts exhausted (reference :341-349).
        rec = self._gate_record(
            config, state, decision, new_m, old_m,
            state.traffic_current, attempt_no,
        )
        self._reset_hold_dedupe()
        fail_ev = Event(
            "Warning",
            "PromotionFailed",
            f"Metrics did not meet conditions after {canary.max_attempts} attempts, "
            "stopping promotion.",
        )
        events.append(fail_ev)
        self.kube.emit_event(self.cr_ref, fail_ev)
        self.log.warning(fail_ev.message)

        if canary.rollback_on_failure:
            # The rollback the reference left as a TODO (:345).
            new_state = new_state.rolled_back()
            applied = self._apply_for_state(obj, config, new_state)
            new_state = self._journal(
                config,
                new_state,
                rec,
                self._transition(
                    Phase.CANARY, Phase.ROLLED_BACK, "RollbackComplete",
                    new_state.held_version, new_state.current_version, 100,
                ),
            )
            self._patch_status(new_state)
            self._log_decision(config, rec)
            rb_ev = Event(
                "Normal",
                "RollbackComplete",
                f"Rolled back to version {new_state.current_version}; "
                f"version {new_state.held_version} is held until the alias moves.",
            )
            events.append(rb_ev)
            self.kube.emit_event(self.cr_ref, rb_ev)
            self.log.warning(rb_ev.message)
            return ReconcileOutcome(
                new_state, config.monitoring_interval_s, events, applied=applied
            )

        new_state = new_state.halt_failed()
        new_state = self._journal(
            config,
            new_state,
            rec,
            self._transition(
                Phase.CANARY, Phase.FAILED, "PromotionFailed",
                new_state.current_version, new_state.previous_version,
                new_state.traffic_current,
            ),
        )
        self._patch_status(new_state)
        self._log_decision(config, rec)
        return ReconcileOutcome(new_state, config.monitoring_interval_s, events)

    # -- deployment application ---------------------------------------------

    def _resolve_uri(self, config: OperatorConfig, version: str) -> str:
        key = (config.model_name, version)
        source = self._source_cache.get(key)
        if source is None:
            source = self.registry.get_version(config.model_name, version).source
            self._source_cache[key] = source
        return artifact_uri(source, config.artifact_root)

    def _manifest_for_state(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        source_of_current: ModelVersion | None = None,
    ) -> dict:
        if source_of_current is not None and source_of_current.version == state.current_version:
            new_uri = artifact_uri(source_of_current.source, config.artifact_root)
            self._source_cache[
                (config.model_name, state.current_version)
            ] = source_of_current.source
        else:
            new_uri = self._resolve_uri(config, state.current_version)
        old_uri = None
        if state.previous_version is not None and state.traffic_prev > 0:
            old_uri = self._resolve_uri(config, state.previous_version)
        owner_uid = (obj.get("metadata") or {}).get("uid", f"uid-{self.name}")
        return build_deployment(
            name=self.name,
            namespace=self.namespace,
            owner_uid=owner_uid,
            config=config,
            current_version=state.current_version,
            new_model_uri=new_uri,
            traffic_current=state.traffic_current,
            previous_version=state.previous_version if state.traffic_prev > 0 else None,
            old_model_uri=old_uri,
            traffic_prev=state.traffic_prev,
            # Autoscaler-controlled count (None = spec topology).  Applies
            # to every predictor: mid-canary the topology is frozen, so
            # both versions serve at the same replica count.
            replicas=state.replicas,
        )

    def _apply_for_state(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        source_of_current: ModelVersion | None = None,
    ) -> bool:
        if state.current_version is None:
            return False
        manifest = self._manifest_for_state(obj, config, state, source_of_current)
        self._apply_deployment(manifest)
        if config.backend == "tpu":
            self._sync_worker_units(obj, config, state, source_of_current)
        return True

    def _apply_deployment(self, manifest: dict) -> None:
        self._apply_object(self.deployment_ref, manifest)

    def _apply_object(self, ref: ObjectRef, manifest: dict, max_retries: int = 3) -> None:
        """Create-or-replace with optimistic-concurrency retry.

        Reference ``apply_seldon_deployment`` (``mlflow_operator.py:244-282``)
        does get -> inject resourceVersion -> replace, creating on 404 — but a
        409 from a concurrent writer kills the handler.  Here Conflict causes
        a re-get and retry.
        """
        with self._op_timer("manifest_apply"):
            self._apply_object_inner(ref, manifest, max_retries)

    def _apply_object_inner(
        self, ref: ObjectRef, manifest: dict, max_retries: int = 3
    ) -> None:
        for attempt in range(max_retries):
            try:
                existing = self.kube.get(ref)
            except NotFound:
                try:
                    self.kube.create(ref, manifest)
                    self.log.info(f"Created {ref.plural}/{ref.name}.")
                    return
                except Conflict:
                    continue  # lost a create race; re-get and replace
            else:
                body = dict(manifest)
                meta = dict(body.get("metadata") or {})
                rv = (existing.get("metadata") or {}).get("resourceVersion")
                if rv:
                    meta["resourceVersion"] = rv
                body["metadata"] = meta
                try:
                    self.kube.replace(ref, body)
                    return
                except Conflict:
                    if attempt == max_retries - 1:
                        raise
                    continue
        raise ApiError(409, f"could not apply {ref.plural}/{ref.name} after retries")

    # -- multi-host worker units (SURVEY §7 hard part 5) ---------------------

    _UNIT_KIND_REFS = {
        "StatefulSet": {"group": "apps", "version": "v1", "plural": "statefulsets"},
        "Service": {"group": "", "version": "v1", "plural": "services"},
        # Warm-pool replicas (autoscaling.warmPoolSize): weightless,
        # compile-swept servers awaiting /admin/attach.
        "Deployment": {"group": "apps", "version": "v1", "plural": "deployments"},
    }

    def _sync_worker_units(
        self,
        obj: dict,
        config: OperatorConfig,
        state: PromotionState,
        source_of_current: ModelVersion | None = None,
        only_if_missing: bool = False,
    ) -> None:
        """Level-triggered: apply the worker units the current state needs,
        delete any this CR owns that it no longer needs (e.g. the old
        version's unit after the 100% step drops the predictor).

        The reference outsources all pod materialization to Seldon's
        controller; a multi-host slice (one predictor = N pods) is beyond
        that model, so for ``backend: tpu`` the operator owns these
        first-party.  Single-host topologies produce no units; the sync
        then only garbage-collects leftovers (e.g. after a topology edit).
        """
        from .builder import (
            build_fleet_pool_manifests,
            build_warm_pool_manifests,
            build_worker_unit_manifests,
        )

        owner_uid = (obj.get("metadata") or {}).get("uid", f"uid-{self.name}")
        desired: list[dict] = []
        if state.current_version is not None:
            if (
                source_of_current is not None
                and source_of_current.version == state.current_version
            ):
                uri = artifact_uri(source_of_current.source, config.artifact_root)
            else:
                uri = self._resolve_uri(config, state.current_version)
            desired += build_worker_unit_manifests(
                self.name, self.namespace, owner_uid, config,
                state.current_version, uri,
            )
            # Warm pool rides the current version (its snapshot geometry
            # is the prewarm source); [] when warmPoolSize is 0.
            desired += build_warm_pool_manifests(
                self.name, self.namespace, owner_uid, config,
                state.current_version, uri,
            )
            # Disaggregated prefill/decode pools ([] when off): counts
            # come from status.fleet when the per-pool autoscaler has
            # taken control, else spec.fleet.
            if config.fleet.disaggregation:
                from . import autoscaler as _scaling

                n_prefill, n_decode = _scaling.fleet_counts(
                    config.fleet, state.fleet
                )
                desired += build_fleet_pool_manifests(
                    self.name, self.namespace, owner_uid, config,
                    state.current_version, uri,
                    prefill_replicas=n_prefill,
                    decode_replicas=n_decode,
                )
        if state.previous_version is not None and state.traffic_prev > 0:
            prev_uri = self._resolve_uri(config, state.previous_version)
            desired += build_worker_unit_manifests(
                self.name, self.namespace, owner_uid, config,
                state.previous_version, prev_uri,
            )
            if config.fleet.disaggregation:
                # The outgoing version's pools at SPEC counts: the fleet
                # autoscaler is frozen during a canary, same contract as
                # the whole-predictor count.
                desired += build_fleet_pool_manifests(
                    self.name, self.namespace, owner_uid, config,
                    state.previous_version, prev_uri,
                )

        desired_names: dict[str, set[str]] = {
            kind: set() for kind in self._UNIT_KIND_REFS
        }
        for manifest in desired:
            kind = manifest["kind"]
            name = manifest["metadata"]["name"]
            desired_names[kind].add(name)
            ref = self._unit_ref(kind, name)
            if only_if_missing:
                # steady-state self-heal: recreate what's gone without
                # rewriting (and rv-bumping) healthy objects every cycle
                try:
                    self.kube.get(ref)
                    continue
                except NotFound:
                    self.log.warning(
                        f"worker-unit {kind} {name} missing; recreating (self-heal)."
                    )
            self._apply_object(ref, manifest)
        self._gc_worker_units(keep=desired_names)

    def _unit_ref(self, kind: str, name: str) -> ObjectRef:
        return ObjectRef(
            namespace=self.namespace, name=name, **self._UNIT_KIND_REFS[kind]
        )

    def _gc_worker_units(self, keep: dict[str, set[str]] | None = None) -> None:
        keep = keep or {}
        for kind in self._UNIT_KIND_REFS:
            try:
                existing = self.kube.list(self._unit_ref(kind, ""))
            except ApiError as e:
                self.log.warning(f"worker-unit GC list of {kind} failed: {e}")
                continue
            for found in existing:
                meta = found.get("metadata") or {}
                labels = meta.get("labels") or {}
                if labels.get("tpumlops/deployment") != self.name:
                    continue  # not ours
                name = meta.get("name", "")
                if name in keep.get(kind, set()):
                    continue
                try:
                    self.kube.delete(self._unit_ref(kind, name))
                    self.log.info(f"Deleted stale worker-unit {kind} {name}.")
                except NotFound:
                    pass

    def _ensure_deployment(
        self, obj: dict, config: OperatorConfig, state: PromotionState
    ) -> None:
        """Self-heal: recreate the deployment if it was deleted out-of-band.

        The reference cannot do this — it only writes on version change — so
        a deleted SeldonDeployment stays gone until the next alias move.
        """
        if state.current_version is None:
            return
        try:
            self.kube.get(self.deployment_ref)
        except NotFound:
            self.log.warning("SeldonDeployment missing; recreating (self-heal).")
            self._apply_for_state(obj, config, state)
            return
        if config.backend == "tpu":
            from .builder import _topology_info

            # the units are separate objects; heal them independently of
            # the (still-present) routing manifest.  Single-host topologies
            # have no units — skip the registry round-trips.
            if _topology_info(config).hosts > 1:
                self._sync_worker_units(obj, config, state, only_if_missing=True)

    def _delete_deployment(self) -> None:
        """Reference ``delete_seldon_deployment`` (:462-477): 404 tolerated.

        Also tears down any first-party worker units (in-cluster the
        ownerReferences GC covers them too; explicit delete keeps fakes and
        non-GC stores equivalent)."""
        try:
            self.kube.delete(self.deployment_ref)
            self.log.info(f"SeldonDeployment '{self.name}' deleted.")
        except NotFound:
            pass
        self._gc_worker_units()

    def _patch_status(self, state: PromotionState) -> None:
        import datetime

        # Wall clock, NOT self.clock: the injected Clock is monotonic in
        # production (SystemClock = time.monotonic), and a
        # lastTransitionTime of "1970-01-03T…" is garbage to kubectl and
        # anything sorting conditions.  Transition stability still comes
        # from the prior-conditions comparison, so FakeClock tests are
        # unaffected.
        now_iso = datetime.datetime.fromtimestamp(
            time.time(), datetime.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%SZ")
        status = state.to_status()
        # Journal keys are omitted when empty (byte-for-byte default), so
        # a CR whose historyLimit went back to 0 needs explicit nulls once
        # to clear what the merge-patch would otherwise leave behind.
        if getattr(self, "_had_journal_keys", False):
            status.setdefault("lastGate", None)
            status.setdefault("history", None)
        if getattr(self, "_had_scaler_keys", False):
            status.setdefault("replicas", None)
            status.setdefault("autoscaler", None)
        if getattr(self, "_had_snapshot_key", False):
            status.setdefault("snapshot", None)
        if getattr(self, "_had_fleet_key", False):
            status.setdefault("fleet", None)
        if getattr(self, "_had_multiplex_key", False):
            status.setdefault("multiplex", None)
        if getattr(self, "_had_anomalies_key", False):
            status.setdefault("anomalies", None)
        if getattr(self, "_capacity_known", False):
            cap = self._capacity_status
            if cap is not None:
                status["capacity"] = cap
            elif getattr(self, "_had_capacity_key", False):
                status.setdefault("capacity", None)
            # Any patch carries the current summary (or its explicit
            # null), so the end-of-step sync knows nothing is left to do.
            self._prior_capacity = cap
        if getattr(self, "_restarts_known", False):
            rs = self._restarts_status
            if rs is not None:
                status["restarts"] = rs
            elif getattr(self, "_had_restarts_key", False):
                status.setdefault("restarts", None)
            self._prior_restarts = rs
        if getattr(self, "_plan_known", False):
            plan_dict = self._plan_status
            if plan_dict is not None:
                status["plan"] = plan_dict
            elif getattr(self, "_had_plan_key", False):
                status.setdefault("plan", None)
            self._prior_plan = plan_dict
        status["conditions"] = state.conditions(
            getattr(self, "_prior_conditions", None), now_iso
        )
        # Later patches in the same reconcile see the fresh conditions.
        self._prior_conditions = status["conditions"]
        try:
            with self._op_timer("status_patch"):
                self.kube.patch_status(self.cr_ref, status)
        except NotFound:
            # CR deleted mid-step; runtime will stop this reconciler.
            self.log.info("CR gone; skipping status patch.")
