"""Operator runtime: watches ``MlflowModel`` CRs and drives reconcilers.

Replaces kopf's role in the reference (``@kopf.on.create``/``on.update``,
``mlflow_operator.py:26-27``) with an explicit scheduler:

- one ``Reconciler`` per CR, created/removed as CRs appear/disappear;
- each reconcile step returns ``requeue_after``; the runtime maintains a
  per-resource due time instead of per-handler sleep loops — so N edits to a
  CR never spawn N competing monitors (fixes SURVEY §3.5(1));
- CR deletion stops the reconciler and deletes its data plane (the reference
  has no delete handler and leans entirely on ownerReferences GC;
  we do both — GC in-cluster via ownerReferences, explicit delete here so
  fakes and non-GC stores behave identically);
- reconcile errors back off exponentially instead of killing the handler
  (the reference's unhandled exceptions end monitoring forever, §5).

Deterministic by construction: with a ``FakeClock`` the test advances time
and calls ``run_until_idle``; with the ``SystemClock`` ``serve`` runs a real
loop.  If kopf *is* installed, ``kopf_adapter`` (separate module) bridges
events into this same runtime.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from ..clients.base import (
    KubeClient,
    MetricsSource,
    NotFound,
    ObjectRef,
    RegistryClient,
    MLFLOWMODEL,
    SELDONDEPLOYMENT,
)
from ..utils.clock import Clock, FakeClock, SystemClock
from .reconciler import Reconciler

_log = logging.getLogger(__name__)

_MAX_BACKOFF_S = 300.0


@dataclass
class _Entry:
    reconciler: Reconciler
    due_at: float
    failures: int = 0


class OperatorRuntime:
    def __init__(
        self,
        kube: KubeClient,
        registry: RegistryClient,
        metrics: MetricsSource | None = None,
        clock: Clock | None = None,
        namespace: str = "",
        sync_interval_s: float = 5.0,
        metrics_factory=None,
        warmup=None,
        telemetry=None,
    ):
        if metrics is None and metrics_factory is None:
            raise ValueError(
                "OperatorRuntime needs metrics or metrics_factory — failing "
                "here, not on first CR, so misconfiguration dies at startup"
            )
        self.kube = kube
        self.registry = registry
        self.metrics = metrics
        self.metrics_factory = metrics_factory
        self.warmup = warmup
        self.telemetry = telemetry  # OperatorTelemetry | None (SURVEY §5)
        self.clock = clock or SystemClock()
        self.namespace = namespace
        self.sync_interval_s = sync_interval_s
        self._entries: dict[tuple[str, str], _Entry] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()

    # -- discovery -----------------------------------------------------------

    def _list_ref(self) -> ObjectRef:
        return ObjectRef(namespace=self.namespace, name="", **MLFLOWMODEL)

    def sync(self) -> None:
        """Reconcile the set of reconcilers with the set of CRs."""
        with self._lock:
            seen: set[tuple[str, str]] = set()
            for obj in self.kube.list(self._list_ref()):
                meta = obj.get("metadata") or {}
                key = (meta.get("namespace", "default"), meta.get("name", ""))
                seen.add(key)
                if key not in self._entries:
                    ns, name = key
                    _log.info("tracking MlflowModel %s/%s", ns, name)
                    self._entries[key] = _Entry(
                        reconciler=Reconciler(
                            name=name,
                            namespace=ns,
                            kube=self.kube,
                            registry=self.registry,
                            metrics=self.metrics,
                            clock=self.clock,
                            metrics_factory=self.metrics_factory,
                            warmup=self.warmup,
                        ),
                        due_at=self.clock.now(),  # reconcile promptly
                    )
            for key in list(self._entries):
                if key not in seen:
                    ns, name = key
                    _log.info("MlflowModel %s/%s deleted; tearing down", ns, name)
                    entry = self._entries.pop(key)
                    try:
                        entry.reconciler._delete_deployment()
                    except Exception:
                        _log.exception("teardown of %s/%s failed", ns, name)
                    if self.telemetry is not None:
                        self.telemetry.forget(ns, name)

    # -- stepping ------------------------------------------------------------

    def step(self) -> float | None:
        """Run every due reconciler once.

        Returns seconds until the next entry is due (None if no entries).
        Never raises: API-server outages (during discovery or reconcile)
        back off instead of killing the runtime — the reference's unhandled
        exceptions silently end monitoring forever (SURVEY §5).
        """
        try:
            self.sync()
        except Exception:
            _log.exception("CR discovery failed; retrying next step")
        now = self.clock.now()
        with self._lock:
            due = [(k, e) for k, e in self._entries.items() if e.due_at <= now]
        for key, entry in due:
            ns, name = key
            t0 = time.perf_counter()
            try:
                obj = self.kube.get(
                    ObjectRef(namespace=ns, name=name, **MLFLOWMODEL)
                )
                outcome = entry.reconciler.reconcile(dict(obj))
                entry.failures = 0
                entry.due_at = self.clock.now() + max(0.0, outcome.requeue_after)
                if self.telemetry is not None:
                    self.telemetry.record_outcome(
                        ns, name, outcome, time.perf_counter() - t0
                    )
            except NotFound:
                continue  # sync() on the next step removes it
            except Exception:
                entry.failures += 1
                backoff = min(_MAX_BACKOFF_S, 2.0 ** entry.failures)
                entry.due_at = self.clock.now() + backoff
                if self.telemetry is not None:
                    self.telemetry.record_failure(
                        ns, name, time.perf_counter() - t0
                    )
                _log.exception(
                    "reconcile of %s/%s failed (attempt %d), backing off %.0fs",
                    ns,
                    name,
                    entry.failures,
                    backoff,
                )
        with self._lock:
            if self.telemetry is not None:
                self.telemetry.set_resource_count(len(self._entries))
            if not self._entries:
                return None
            return max(0.0, min(e.due_at for e in self._entries.values()) - self.clock.now())

    # -- loops ---------------------------------------------------------------

    def run_until_idle(self, max_wall: float = 3600.0, max_steps: int = 10_000) -> None:
        """Test loop for ``FakeClock``: step, then jump the clock to the next
        due time, until nothing is due within ``max_wall`` fake-seconds."""
        if not isinstance(self.clock, FakeClock):
            raise TypeError("run_until_idle requires a FakeClock")
        deadline = self.clock.now() + max_wall
        for _ in range(max_steps):
            delay = self.step()
            if delay is None:
                return
            if delay > 0:
                if self.clock.now() + delay > deadline:
                    return
                self.clock.advance(delay)
        raise RuntimeError("run_until_idle did not settle (livelock?)")

    def run_for(self, fake_seconds: float, max_steps: int = 10_000) -> None:
        """Advance a ``FakeClock`` by ``fake_seconds``, stepping as entries
        come due."""
        if not isinstance(self.clock, FakeClock):
            raise TypeError("run_for requires a FakeClock")
        deadline = self.clock.now() + fake_seconds
        for _ in range(max_steps):
            delay = self.step()
            remaining = deadline - self.clock.now()
            if remaining <= 0:
                return
            if delay is None:
                self.clock.advance(remaining)
                return
            self.clock.advance(min(delay, remaining) if delay > 0 else 0)
            if delay == 0:
                continue
        raise RuntimeError("run_for did not settle (livelock?)")

    def serve(self) -> None:
        """Real-time loop (SystemClock)."""
        _log.info("operator runtime serving (namespace=%r)", self.namespace or "*")
        while not self._stop.is_set():
            try:
                delay = self.step()
            except Exception:  # belt and braces: serve() must never die
                _log.exception("runtime step failed")
                delay = self.sync_interval_s
            sleep_for = self.sync_interval_s if delay is None else min(delay, self.sync_interval_s)
            self._stop.wait(max(0.05, sleep_for))

    def stop(self) -> None:
        self._stop.set()
