"""Operator runtime: watches ``MlflowModel`` CRs and drives reconcilers.

Replaces kopf's role in the reference (``@kopf.on.create``/``on.update``,
``mlflow_operator.py:26-27``) with an explicit scheduler:

- one ``Reconciler`` per CR, created/removed as CRs appear/disappear;
- each reconcile step returns ``requeue_after``; the runtime maintains a
  per-resource due time instead of per-handler sleep loops — so N edits to a
  CR never spawn N competing monitors (fixes SURVEY §3.5(1));
- CR deletion stops the reconciler and deletes its data plane (the reference
  has no delete handler and leans entirely on ownerReferences GC;
  we do both — GC in-cluster via ownerReferences, explicit delete here so
  fakes and non-GC stores behave identically);
- reconcile errors back off exponentially instead of killing the handler
  (the reference's unhandled exceptions end monitoring forever, §5).

Deterministic by construction: with a ``FakeClock`` the test advances time
and calls ``run_until_idle``; with the ``SystemClock`` ``serve`` runs a real
loop.

Event-driven reaction (the reference's kopf watch registration,
``mlflow_operator.py:26-27``): :class:`CrWatcher` consumes the API server's
watch stream (``KubeClient.watch``) and pokes the runtime — a CR add, edit,
or delete reconciles immediately instead of waiting out the resync poll.
The poll in ``sync()`` stays as the level-triggered fallback, so a dropped
watch event can delay a reconcile but never lose it.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from ..clients.base import (
    KubeClient,
    MetricsSource,
    NotFound,
    ObjectRef,
    RegistryClient,
    MLFLOWMODEL,
    SELDONDEPLOYMENT,
)
from ..utils.clock import Clock, FakeClock, SystemClock
from .reconciler import Reconciler

_log = logging.getLogger(__name__)

_MAX_BACKOFF_S = 300.0


@dataclass
class _Entry:
    reconciler: Reconciler
    due_at: float
    failures: int = 0
    # metadata.generation of the object at its last reconcile.  The API
    # server bumps generation on spec changes only — never on status
    # patches — which is what lets notify() tell a user edit (reconcile
    # now) from the reconciler's own status writes (don't touch pacing).
    generation: int | None = None
    # Bumped by notify(): a reconcile completing after a notify must not
    # overwrite the notify's due-now with its computed requeue.
    epoch: int = 0


class OperatorRuntime:
    def __init__(
        self,
        kube: KubeClient,
        registry: RegistryClient,
        metrics: MetricsSource | None = None,
        clock: Clock | None = None,
        namespace: str = "",
        sync_interval_s: float = 5.0,
        metrics_factory=None,
        warmup=None,
        telemetry=None,
        recorder=None,
        max_concurrent_reconciles: int = 1,
        mux_pools=None,
        ring_sources=None,
    ):
        if metrics is None and metrics_factory is None:
            raise ValueError(
                "OperatorRuntime needs metrics or metrics_factory — failing "
                "here, not on first CR, so misconfiguration dies at startup"
            )
        self.kube = kube
        self.registry = registry
        self.metrics = metrics
        self.metrics_factory = metrics_factory
        self.warmup = warmup
        self.telemetry = telemetry  # OperatorTelemetry | None (SURVEY §5)
        self.recorder = recorder  # RolloutRecorder | None (gate journal)
        # Mapping[poolRef, Multiplexer] — the shared warm-pool
        # coordinators CRs with spec.multiplex bind to.  Runtime-owned
        # (one coordinator outlives any single CR), reconciler-driven.
        self.mux_pools = mux_pools
        # Zero-arg callable returning fleet ring snapshots
        # ({"replicas": {name: snapshot}, "router": snapshot|None}) for
        # the anomaly observatory; None = spec.anomaly CRs detect
        # nothing (the seam is runtime wiring, not per-CR config).
        self.ring_sources = ring_sources
        self.clock = clock or SystemClock()
        self.namespace = namespace
        self.sync_interval_s = sync_interval_s
        self._entries: dict[tuple[str, str], _Entry] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        # Set by notify() (watch events) to cut a serve() sleep short.
        self._wake = threading.Event()
        # Reconciles of DISTINCT CRs may run concurrently (kopf runs
        # handlers concurrently; controller-runtime calls this knob
        # MaxConcurrentReconciles): without it one CR with a slow metrics
        # source stalls every other rollout.  Entries are never reconciled
        # concurrently with themselves — step() partitions by entry.
        self.max_concurrent_reconciles = max(1, int(max_concurrent_reconciles))
        self._pool = None
        # Keys currently being reconciled on the pool: step() neither
        # re-submits them (a CR is never reconciled concurrently with
        # itself) nor counts their stale due_at toward the next-due delay
        # (which would spin the serve loop hot for the whole reconcile).
        self._in_flight: set[tuple[str, str]] = set()
        # Bumped by notify(): a reconcile that finishes AFTER a watch
        # event must not clobber the event's due-now with its requeue.
        self._epoch = 0
        if self.max_concurrent_reconciles > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.max_concurrent_reconciles,
                thread_name_prefix="reconcile",
            )

    # -- discovery -----------------------------------------------------------

    def _list_ref(self) -> ObjectRef:
        return ObjectRef(namespace=self.namespace, name="", **MLFLOWMODEL)

    def sync(self) -> None:
        """Reconcile the set of reconcilers with the set of CRs."""
        with self._lock:
            seen: set[tuple[str, str]] = set()
            for obj in self.kube.list(self._list_ref()):
                meta = obj.get("metadata") or {}
                key = (meta.get("namespace", "default"), meta.get("name", ""))
                seen.add(key)
                if key not in self._entries:
                    ns, name = key
                    _log.info("tracking MlflowModel %s/%s", ns, name)
                    self._entries[key] = _Entry(
                        reconciler=Reconciler(
                            name=name,
                            namespace=ns,
                            kube=self.kube,
                            registry=self.registry,
                            metrics=self.metrics,
                            clock=self.clock,
                            metrics_factory=self.metrics_factory,
                            warmup=self.warmup,
                            recorder=self.recorder,
                            mux_pools=self.mux_pools,
                            ring_sources=self.ring_sources,
                        ),
                        due_at=self.clock.now(),  # reconcile promptly
                    )
            for key in list(self._entries):
                if key not in seen:
                    ns, name = key
                    _log.info("MlflowModel %s/%s deleted; tearing down", ns, name)
                    entry = self._entries.pop(key)
                    try:
                        entry.reconciler._delete_deployment()
                    except Exception:
                        _log.exception("teardown of %s/%s failed", ns, name)
                    if self.telemetry is not None:
                        self.telemetry.forget(ns, name)
                    if self.recorder is not None:
                        self.recorder.forget(ns, name)

    def notify(
        self,
        namespace: str,
        name: str,
        obj: dict | None = None,
        event_type: str = "MODIFIED",
    ) -> None:
        """React to a watch event: maybe mark the CR due now, wake serve.

        The canary's pacing (step intervals, gate retry delays) lives in
        ``requeue_after`` — so a MODIFIED event may only pull the due time
        forward when the *spec* changed.  The API server bumps
        ``metadata.generation`` on spec changes and never on status
        patches; without this check the reconciler's own status writes
        would echo back through the watch and each canary step would
        immediately schedule the next, promoting 0→100% in milliseconds
        with every gate interval skipped.

        Unknown keys (a just-created CR, or a deletion) need no per-entry
        action: ``step()`` always runs ``sync()`` first, which picks up
        adds and tears down deletes — waking is enough.
        """
        with self._lock:
            entry = self._entries.get((namespace, name))
            if entry is not None:
                # ADDED must take the same path: a reconnecting watch with
                # no cursor replays synthetic ADDED for every live object,
                # and those must not reset pacing either.
                if event_type in ("ADDED", "MODIFIED") and obj is not None:
                    gen = (obj.get("metadata") or {}).get("generation")
                    if gen is not None and gen == entry.generation:
                        return  # status echo / watch replay; pacing stands
                entry.due_at = self.clock.now()
                entry.epoch += 1
        self._wake.set()

    # -- stepping ------------------------------------------------------------

    def _set_due(self, entry: _Entry, epoch: int, due_at: float) -> None:
        """Write the post-reconcile due time unless a notify() landed
        mid-reconcile — its due-now wins over our computed requeue."""
        with self._lock:
            if entry.epoch == epoch:
                entry.due_at = due_at

    def _reconcile_one(self, key: tuple[str, str], entry: _Entry) -> None:
        ns, name = key
        t0 = time.perf_counter()
        with self._lock:
            epoch = entry.epoch
        try:
            obj = self.kube.get(
                ObjectRef(namespace=ns, name=name, **MLFLOWMODEL)
            )
            entry.generation = (obj.get("metadata") or {}).get("generation")
            outcome = entry.reconciler.reconcile(dict(obj))
            entry.failures = 0
            self._set_due(
                entry, epoch, self.clock.now() + max(0.0, outcome.requeue_after)
            )
            if self.telemetry is not None:
                self.telemetry.record_outcome(
                    ns, name, outcome, time.perf_counter() - t0
                )
        except NotFound:
            pass  # sync() on the next step removes it
        except Exception:
            entry.failures += 1
            backoff = min(_MAX_BACKOFF_S, 2.0 ** entry.failures)
            self._set_due(entry, epoch, self.clock.now() + backoff)
            if self.telemetry is not None:
                self.telemetry.record_failure(ns, name, time.perf_counter() - t0)
            _log.exception(
                "reconcile of %s/%s failed (attempt %d), backing off %.0fs",
                ns,
                name,
                entry.failures,
                backoff,
            )

    def step(self) -> float | None:
        """Run every due reconciler once.

        Returns seconds until the next entry is due (None if no entries).
        Never raises: API-server outages (during discovery or reconcile)
        back off instead of killing the runtime — the reference's unhandled
        exceptions silently end monitoring forever (SURVEY §5).
        """
        try:
            self.sync()
        except Exception:
            _log.exception("CR discovery failed; retrying next step")
        now = self.clock.now()
        with self._lock:
            due = [
                (k, e)
                for k, e in self._entries.items()
                if e.due_at <= now and k not in self._in_flight
            ]
        if self._pool is not None:
            # Fire-and-continue, NO barrier: one slow CR must not gate
            # anyone else's next round (controller-runtime semantics).
            # Completion wakes serve() to recompute the next due time.
            for key, entry in due:
                with self._lock:
                    self._in_flight.add(key)
                try:
                    fut = self._pool.submit(self._reconcile_one, key, entry)
                except RuntimeError:  # pool shut down mid-step (stop())
                    with self._lock:
                        self._in_flight.discard(key)
                    break
                fut.add_done_callback(
                    lambda _f, key=key: self._reconcile_done(key)
                )
        else:
            for key, entry in due:
                self._reconcile_one(key, entry)
        with self._lock:
            if self.telemetry is not None:
                self.telemetry.set_resource_count(len(self._entries))
            # In-flight entries' due_at is stale (past); counting them
            # would spin the serve loop for the whole reconcile.
            pending = [
                e.due_at
                for k, e in self._entries.items()
                if k not in self._in_flight
            ]
            if not pending:
                return None
            return max(0.0, min(pending) - self.clock.now())

    def _reconcile_done(self, key: tuple[str, str]) -> None:
        with self._lock:
            self._in_flight.discard(key)
        self._wake.set()

    # -- loops ---------------------------------------------------------------

    def run_until_idle(self, max_wall: float = 3600.0, max_steps: int = 10_000) -> None:
        """Test loop for ``FakeClock``: step, then jump the clock to the next
        due time, until nothing is due within ``max_wall`` fake-seconds."""
        if not isinstance(self.clock, FakeClock):
            raise TypeError("run_until_idle requires a FakeClock")
        deadline = self.clock.now() + max_wall
        for _ in range(max_steps):
            delay = self.step()
            if delay is None:
                return
            if delay > 0:
                if self.clock.now() + delay > deadline:
                    return
                self.clock.advance(delay)
        raise RuntimeError("run_until_idle did not settle (livelock?)")

    def run_for(self, fake_seconds: float, max_steps: int = 10_000) -> None:
        """Advance a ``FakeClock`` by ``fake_seconds``, stepping as entries
        come due."""
        if not isinstance(self.clock, FakeClock):
            raise TypeError("run_for requires a FakeClock")
        deadline = self.clock.now() + fake_seconds
        for _ in range(max_steps):
            delay = self.step()
            remaining = deadline - self.clock.now()
            if remaining <= 0:
                return
            if delay is None:
                self.clock.advance(remaining)
                return
            self.clock.advance(min(delay, remaining) if delay > 0 else 0)
            if delay == 0:
                continue
        raise RuntimeError("run_for did not settle (livelock?)")

    def serve(self) -> None:
        """Real-time loop (SystemClock)."""
        _log.info("operator runtime serving (namespace=%r)", self.namespace or "*")
        while not self._stop.is_set():
            try:
                delay = self.step()
            except Exception:  # belt and braces: serve() must never die
                _log.exception("runtime step failed")
                delay = self.sync_interval_s
            sleep_for = self.sync_interval_s if delay is None else min(delay, self.sync_interval_s)
            # Sleep until the next due time OR a watch notification —
            # whichever comes first.  stop() also sets _wake so shutdown
            # never waits out a sleep.
            if self._wake.wait(max(0.05, sleep_for)):
                self._wake.clear()

    def stop(self, drain_s: float = 0.0) -> None:
        """Stop the serve loop; optionally drain in-flight reconciles.

        ``drain_s > 0`` bounds a wait for reconciles already running on
        the pool.  On leadership loss this matters: shutdown(wait=False)
        only cancels *pending* work, and a slow in-flight reconcile that
        keeps patching status past the takeover window briefly
        reintroduces the dual-writer the Lease exists to prevent.  The
        wait is bounded (not ``shutdown(wait=True)``) so a hung metrics
        source cannot wedge teardown past the successor's takeover.
        """
        self._stop.set()
        self._wake.set()
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            if drain_s <= 0:
                # No drain requested — and stop() may be running inside a
                # signal handler on the serve thread itself, where taking
                # self._lock (held by step()) would self-deadlock.
                return
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._in_flight:
                        return
                time.sleep(0.05)
            with self._lock:
                leftover = set(self._in_flight)
            if leftover:
                _log.warning(
                    "stop: %d reconcile(s) still running after %.1fs drain "
                    "(%s) — a new leader may observe overlapping writes",
                    len(leftover), drain_s, sorted(leftover),
                )


class CrWatcher:
    """Event-driven bridge: API-server watch stream → ``runtime.notify``.

    The push half of the informer pattern (the reference gets this from
    kopf's watch registration, ``mlflow_operator.py:26-27``).  Lifecycle:

    - list once for a resourceVersion cursor, then stream events from it;
    - track the cursor through object and BOOKMARK events so a reconnect
      resumes where it left off instead of replaying history;
    - on 410 Gone (cursor fell out of etcd history) re-list for a fresh
      cursor — the standard re-list contract;
    - on transport errors reconnect with capped exponential backoff;
    - every delivered event just pokes the runtime: reconcile state lives
      in ``OperatorRuntime``/``Reconciler``; the watcher carries no state
      worth preserving, so a crashed watcher degrades to poll-only, it
      never wedges the operator.
    """

    def __init__(
        self,
        runtime: OperatorRuntime,
        timeout_s: int = 300,
        max_backoff_s: float = 30.0,
    ):
        kube = runtime.kube
        if not hasattr(kube, "watch"):
            raise TypeError(
                f"{type(kube).__name__} has no watch(); CrWatcher needs a "
                "watch-capable KubeClient (KubeRestClient or FakeKube)"
            )
        self.runtime = runtime
        self.timeout_s = timeout_s
        self.max_backoff_s = max_backoff_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "CrWatcher":
        self._thread = threading.Thread(
            target=self.run, daemon=True, name=type(self).__name__
        )
        self._thread.start()
        return self

    def _ref(self):
        """The collection this watcher streams (MlflowModels)."""
        return self.runtime._list_ref()

    def _handle(self, ev) -> None:
        meta = ev.object.get("metadata") or {}
        self.runtime.notify(
            meta.get("namespace", "default"),
            meta.get("name", ""),
            obj=dict(ev.object),
            event_type=ev.type,
        )

    def run(self) -> None:
        from ..clients.base import WatchExpired

        ref = self._ref()
        rv: str | None = None
        failures = 0
        while not self._stop.is_set():
            try:
                if rv is None:
                    if hasattr(self.runtime.kube, "list_with_version"):
                        _, rv = self.runtime.kube.list_with_version(ref)
                    else:
                        rv = ""
                    # The snapshot may differ from the runtime's view
                    # (adds/deletes during the gap): force a resync pass.
                    self.runtime._wake.set()
                for ev in self.runtime.kube.watch(
                    ref, resource_version=rv or None,
                    timeout_s=self.timeout_s, stop=self._stop,
                ):
                    failures = 0
                    meta = ev.object.get("metadata") or {}
                    if meta.get("resourceVersion"):
                        rv = meta["resourceVersion"]
                    if ev.type == "BOOKMARK":
                        continue
                    self._handle(ev)
                # Server closed the stream (watch timeout): reconnect from
                # the current cursor without re-listing.
            except WatchExpired:
                _log.info("watch cursor expired; re-listing")
                rv = None
            except Exception:
                failures += 1
                backoff = min(self.max_backoff_s, 2.0 ** min(failures, 16))
                _log.exception("watch failed; reconnecting in %.0fs", backoff)
                rv = None
                self._stop.wait(backoff)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # A real watch blocked in a read only observes stop after the
            # client's 15s read timeout — join must outlast it.
            self._thread.join(timeout=20)


class DeploymentWatcher(CrWatcher):
    """Watch SeldonDeployments and heal out-of-band deletions immediately.

    Only DELETED events react: the operator's own applies echo back as
    ADDED/MODIFIED and must not reset reconcile pacing, and any foreign
    edit is overwritten by the next apply anyway.  A deleted deployment
    whose (namespace, name) matches a tracked CR pulls that CR due NOW,
    so ``Reconciler._ensure_deployment`` recreates it in milliseconds
    instead of after the resync poll.
    """

    def _ref(self):
        return ObjectRef(
            namespace=self.runtime.namespace, name="", **SELDONDEPLOYMENT
        )

    def _handle(self, ev) -> None:
        if ev.type != "DELETED":
            return
        meta = ev.object.get("metadata") or {}
        self.runtime.notify(
            meta.get("namespace", "default"),
            meta.get("name", ""),
            obj=None,
            event_type="DELETED",
        )
