"""First-party operator metrics.

The reference *consumes* Prometheus but exports nothing about itself
(SURVEY §5: "the operator exports no metrics of its own") — so an operator
stuck in backoff, a promotion frozen mid-split, or a reconcile-latency
regression is invisible until someone reads pod logs.  This module gives
the control plane the same observability its data plane already has:

- ``tpumlops_operator_reconcile_total{namespace,name,result}`` — steps by
  outcome (``ok``/``error``);
- ``tpumlops_operator_reconcile_seconds`` — step latency histogram
  (the promotion-loop step timing SURVEY §5 calls for);
- ``tpumlops_operator_phase{...,phase}`` — one-hot rollout phase per CR;
- ``tpumlops_operator_traffic_percent`` — live canary split per CR
  (time-to-100% — the north-star metric — is directly readable from this
  series' history);
- ``tpumlops_operator_promotions_total{...,outcome}`` — completed /
  failed / rolled-back rollouts (from the same events the reference posts
  to Kubernetes, ``mlflow_operator.py:344,:361``);
- ``tpumlops_operator_resources`` — CRs currently managed.

Wired into ``OperatorRuntime`` (zero-cost when not configured) and served
by ``python -m <package>.operator --metrics-port``.
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from .state import Phase

_STEP_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0)

# Event reasons that terminate a rollout, mapped to a promotion outcome.
_TERMINAL_REASONS = {
    "PromotionComplete": "completed",
    "PromotionFailed": "failed",
    "RolledBack": "rolled_back",
}


class OperatorTelemetry:
    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        ident = ["namespace", "name"]
        self.reconciles = Counter(
            "tpumlops_operator_reconcile_total",
            "Reconcile steps by result",
            ident + ["result"],
            registry=self.registry,
        )
        self.reconcile_seconds = Histogram(
            "tpumlops_operator_reconcile_seconds",
            "Wall time of one reconcile step",
            ident,
            buckets=_STEP_BUCKETS,
            registry=self.registry,
        )
        # Where each step's time went (status patch vs manifest apply vs
        # gate read vs registry): the per-component split behind the
        # time-to-100% overhead line (VERDICT r2 #10) — a drift in
        # operator overhead becomes attributable instead of a mystery.
        self.step_component_seconds = Histogram(
            "tpumlops_operator_step_component_seconds",
            "Reconcile-step wall time per operation class",
            ident + ["component"],
            buckets=_STEP_BUCKETS,
            registry=self.registry,
        )
        self.phase = Gauge(
            "tpumlops_operator_phase",
            "Rollout phase (one-hot per CR)",
            ident + ["phase"],
            registry=self.registry,
        )
        self.traffic = Gauge(
            "tpumlops_operator_traffic_percent",
            "Traffic on the current (new) version",
            ident,
            registry=self.registry,
        )
        self.promotions = Counter(
            "tpumlops_operator_promotions_total",
            "Finished rollouts by outcome",
            ident + ["outcome"],
            registry=self.registry,
        )
        self.events = Counter(
            "tpumlops_operator_events_total",
            "Kubernetes events posted, by reason",
            ident + ["reason"],
            registry=self.registry,
        )
        self.resources = Gauge(
            "tpumlops_operator_resources",
            "MlflowModel resources currently managed",
            registry=self.registry,
        )
        # Every labeled series this object has minted, keyed by CR, so
        # forget() can prune with the public remove() API only (no reaching
        # into prometheus_client internals).
        self._series: dict[tuple[str, str], set] = {}

    def _child(self, metric, namespace: str, name: str, *extra: str):
        values = (namespace, name, *extra)
        self._series.setdefault((namespace, name), set()).add((metric, values))
        return metric.labels(*values)

    # -- recording (called by OperatorRuntime) -------------------------------

    def record_outcome(self, namespace: str, name: str, outcome, seconds: float):
        """Record a successful reconcile step and its resulting state."""
        self._child(self.reconciles, namespace, name, "ok").inc()
        self._child(self.reconcile_seconds, namespace, name).observe(seconds)
        for component, secs in (getattr(outcome, "timings", None) or {}).items():
            self._child(
                self.step_component_seconds, namespace, name, component
            ).observe(secs)
        state = outcome.state
        for phase in Phase:
            self._child(self.phase, namespace, name, phase.value).set(
                1.0 if state.phase == phase else 0.0
            )
        self._child(self.traffic, namespace, name).set(state.traffic_current)
        for event in outcome.events:
            self._child(self.events, namespace, name, event.reason).inc()
            outcome_label = _TERMINAL_REASONS.get(event.reason)
            if outcome_label:
                self._child(
                    self.promotions, namespace, name, outcome_label
                ).inc()

    def record_failure(self, namespace: str, name: str, seconds: float):
        self._child(self.reconciles, namespace, name, "error").inc()
        self._child(self.reconcile_seconds, namespace, name).observe(seconds)

    def set_resource_count(self, n: int):
        self.resources.set(n)

    def forget(self, namespace: str, name: str):
        """Drop a deleted CR's labeled series so /metrics stops exporting a
        phantom model (a stale phase=Canary gauge would fire "canary stuck"
        alerts forever)."""
        for metric, values in self._series.pop((namespace, name), ()):
            try:
                metric.remove(*values)
            except KeyError:
                pass

    def exposition(self) -> bytes:
        return generate_latest(self.registry)

    def serve(self, port: int, addr: str = "0.0.0.0"):
        """Expose /metrics AND /debug/spans on a daemon-thread listener.

        /debug/spans serves the ``utils/tracing.py`` GLOBAL_TRACER stats
        (reconcile-step span timings) as JSON — the same payload shape
        the data-plane server exposes, so one tool reads both planes."""
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ..utils.tracing import GLOBAL_TRACER

        telemetry = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib handler contract)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = telemetry.exposition()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/debug/spans":
                    body = json.dumps(
                        {"spans": GLOBAL_TRACER.as_dict()}
                    ).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log events
                pass

        httpd = ThreadingHTTPServer((addr, port), _Handler)
        httpd.daemon_threads = True
        threading.Thread(
            target=httpd.serve_forever, daemon=True, name="operator-metrics"
        ).start()
        return httpd
