"""First-party operator metrics.

The reference *consumes* Prometheus but exports nothing about itself
(SURVEY §5: "the operator exports no metrics of its own") — so an operator
stuck in backoff, a promotion frozen mid-split, or a reconcile-latency
regression is invisible until someone reads pod logs.  This module gives
the control plane the same observability its data plane already has:

- ``tpumlops_operator_reconcile_total{namespace,name,result}`` — steps by
  outcome (``ok``/``error``);
- ``tpumlops_operator_reconcile_seconds`` — step latency histogram
  (the promotion-loop step timing SURVEY §5 calls for);
- ``tpumlops_operator_phase{...,phase}`` — one-hot rollout phase per CR;
- ``tpumlops_operator_traffic_percent`` — live canary split per CR
  (time-to-100% — the north-star metric — is directly readable from this
  series' history);
- ``tpumlops_operator_promotions_total{...,outcome}`` — completed /
  failed / rolled-back rollouts (from the same events the reference posts
  to Kubernetes, ``mlflow_operator.py:344,:361``);
- ``tpumlops_operator_resources`` — CRs currently managed;
- ``tpumlops_operator_gate_margin{check}`` — signed headroom (budget −
  observed) of the last gate evaluation per check: how far the canary
  is from promoting, not just that it isn't;
- ``tpumlops_operator_gate_evaluations_total{result}`` — gate decisions
  by class (``promote`` / ``threshold`` / ``missing_metrics`` /
  ``min_sample``);
- ``tpumlops_operator_gate_attempt`` — this evaluation's attempt number
  at the current traffic level (resets on each promote step);
- ``tpumlops_operator_rollout_duration_seconds`` — NEW_VERSION→terminal
  wall time per rollout (the north-star time-to-100% as a histogram).

Wired into ``OperatorRuntime`` (zero-cost when not configured) and served
by ``python -m <package>.operator --metrics-port``.
"""

from __future__ import annotations

import time

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from .rollout_recorder import GATE_CHECKS
from .state import Phase

_STEP_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0)
# A rollout spans canary step intervals, not reconcile steps: seconds to
# hours.
_ROLLOUT_BUCKETS = (1.0, 5.0, 15.0, 60.0, 120.0, 300.0, 600.0, 1800.0,
                    3600.0, 7200.0)

# Event reasons that terminate a rollout, mapped to a promotion outcome.
# Ordered by precedence: a rolled-back step emits PromotionFailed AND
# RollbackComplete in the same outcome and must count once, as
# rolled_back — not once per reason (pre-journal versions keyed this on
# a "RolledBack" reason nothing ever emitted, so rolled_back rollouts
# were miscounted as failed).
_TERMINAL_REASONS = (
    ("RollbackComplete", "rolled_back"),
    ("PromotionComplete", "completed"),
    ("PromotionFailed", "failed"),
)


def _fetch_json(url: str, timeout: float = 5.0):
    import json as _json
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return _json.loads(resp.read().decode())


def fleet_overview(sources, models) -> dict:
    """One aggregated fleet view: every source's ring snapshot (server
    rings for replicas, the leg-latency ring + per-backend circuit
    state for the router) plus the control plane's per-model verdicts
    and mux assignments.

    ``sources`` is the fleet-trace source list
    (``[{"name", "base_url", "kind": "router"|"replica"}, ...]``).
    Unlike ``/debug/fleet-trace`` — where a missing component makes the
    merged trace silently wrong, so a fetch error is a 502 — a dark
    replica IS the story here: it stays listed with an ``error`` field
    instead of taking the whole overview down.  A 404 from a ring
    endpoint (ring disabled) lists the source with ``timeseries: null``
    and no error."""
    import urllib.error

    srcs: dict = {}
    for spec in sources:
        base = str(spec.get("base_url") or "").rstrip("/")
        name = spec.get("name") or base
        kind = spec.get("kind") or "replica"
        entry: dict = {"kind": kind, "base_url": base, "timeseries": None}
        ts_path = (
            "/router/debug/timeseries"
            if kind == "router"
            else "/debug/timeseries"
        )
        try:
            entry["timeseries"] = _fetch_json(base + ts_path)
        except urllib.error.HTTPError as e:
            if e.code != 404:  # 404 = ring off, a legitimate state
                entry["error"] = f"HTTP {e.code}"
        except Exception as e:
            entry["error"] = str(e)
        if kind == "router" and "error" not in entry:
            try:
                fl = _fetch_json(base + "/router/fleet")
                circuits = {}
                for b in fl.get("backends") or []:
                    c = {
                        "healthy": b.get("healthy"),
                        "circuitOpened": b.get("circuit_opened"),
                    }
                    if b.get("model"):
                        c["model"] = b["model"]
                    circuits[b.get("name")] = c
                entry["circuits"] = circuits
            except Exception as e:
                entry["error"] = str(e)
        srcs[name] = entry
    return {"sources": srcs, "models": models}


class OperatorTelemetry:
    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        ident = ["namespace", "name"]
        self.reconciles = Counter(
            "tpumlops_operator_reconcile_total",
            "Reconcile steps by result",
            ident + ["result"],
            registry=self.registry,
        )
        self.reconcile_seconds = Histogram(
            "tpumlops_operator_reconcile_seconds",
            "Wall time of one reconcile step",
            ident,
            buckets=_STEP_BUCKETS,
            registry=self.registry,
        )
        # Where each step's time went (status patch vs manifest apply vs
        # gate read vs registry): the per-component split behind the
        # time-to-100% overhead line (VERDICT r2 #10) — a drift in
        # operator overhead becomes attributable instead of a mystery.
        self.step_component_seconds = Histogram(
            "tpumlops_operator_step_component_seconds",
            "Reconcile-step wall time per operation class",
            ident + ["component"],
            buckets=_STEP_BUCKETS,
            registry=self.registry,
        )
        self.phase = Gauge(
            "tpumlops_operator_phase",
            "Rollout phase (one-hot per CR)",
            ident + ["phase"],
            registry=self.registry,
        )
        self.traffic = Gauge(
            "tpumlops_operator_traffic_percent",
            "Traffic on the current (new) version",
            ident,
            registry=self.registry,
        )
        self.promotions = Counter(
            "tpumlops_operator_promotions_total",
            "Finished rollouts by outcome",
            ident + ["outcome"],
            registry=self.registry,
        )
        self.events = Counter(
            "tpumlops_operator_events_total",
            "Kubernetes events posted, by reason",
            ident + ["reason"],
            registry=self.registry,
        )
        self.resources = Gauge(
            "tpumlops_operator_resources",
            "MlflowModel resources currently managed",
            registry=self.registry,
        )
        # Promotion-gate decision series (fed from ReconcileOutcome.gate;
        # no samples appear until a CR actually runs a canary gate).
        self.gate_margin = Gauge(
            "tpumlops_operator_gate_margin",
            "Signed headroom (budget - observed) of the last gate "
            "evaluation, per check; >= 0 promotes",
            ident + ["check"],
            registry=self.registry,
        )
        self.gate_evaluations = Counter(
            "tpumlops_operator_gate_evaluations_total",
            "Gate evaluations by decision class",
            ident + ["result"],
            registry=self.registry,
        )
        self.gate_attempt = Gauge(
            "tpumlops_operator_gate_attempt",
            "Attempt number of the last gate evaluation at the current "
            "traffic level (1-based; resets each promote step)",
            ident,
            registry=self.registry,
        )
        # Replica-autoscaler decision series (fed from
        # ReconcileOutcome.scale and the post-step state; no samples
        # until a CR enables spec.autoscaling).
        self.autoscale_replicas = Gauge(
            "tpumlops_operator_autoscale_replicas",
            "Autoscaler-controlled replica count of the current version "
            "(absent while spec.autoscaling is disabled)",
            ident,
            registry=self.registry,
        )
        self.autoscale_desired = Gauge(
            "tpumlops_operator_autoscale_desired_replicas",
            "Replica count the last autoscaler evaluation wanted before "
            "hysteresis (> replicas = scale-up pending stabilization; "
            "< replicas = scale-down pending cooldown)",
            ident,
            registry=self.registry,
        )
        self.autoscale_events = Counter(
            "tpumlops_operator_autoscale_events_total",
            "Applied replica scalings by direction",
            ident + ["direction"],
            registry=self.registry,
        )
        self.autoscale_holds = Counter(
            "tpumlops_operator_autoscale_holds_total",
            "Autoscaler evaluations held back, by reason (cooldown / "
            "stabilization / metrics_missing)",
            ident + ["reason"],
            registry=self.registry,
        )
        # SLO error-budget accounting (spec.slo; operator/slo.py) — no
        # samples until a CR configures spec.slo.
        self.slo_attainment = Gauge(
            "tpumlops_operator_slo_attainment",
            "Rolling fraction of in-window samples meeting the SLO "
            "target (spec.slo)",
            ident + ["slo"],
            registry=self.registry,
        )
        self.slo_budget_remaining = Gauge(
            "tpumlops_operator_slo_error_budget_remaining",
            "Rolling error budget remaining (1 = untouched, 0 = "
            "exhausted) per SLO over spec.slo.windowMinutes",
            ident + ["slo"],
            registry=self.registry,
        )
        self.slo_burn_rate = Gauge(
            "tpumlops_operator_slo_burn_rate",
            "Error-budget burn rate per SLO (1.0 = consuming the "
            "budget exactly as fast as the objective allows)",
            ident + ["slo"],
            registry=self.registry,
        )
        # Multi-model multiplexing (spec.multiplex; operator/
        # multiplexer.py) — no samples until a CR joins a shared pool.
        self.mux_moves = Counter(
            "tpumlops_operator_mux_moves_total",
            "Executed multiplexer moves by action (attach = onto an "
            "empty replica, replace = evicted another model)",
            ident + ["action"],
            registry=self.registry,
        )
        self.mux_parked = Gauge(
            "tpumlops_operator_mux_parked_requests",
            "Router-parked requests awaiting this model's attach, as "
            "last observed by the multiplexer",
            ident,
            registry=self.registry,
        )
        # Fleet anomaly observatory (spec.anomaly; operator/anomaly.py)
        # — no samples until a CR enables spec.anomaly.
        self.anomaly_active = Gauge(
            "tpumlops_operator_anomaly_active",
            "Active anomaly verdicts by kind (straggler / drift), as "
            "stamped at the last journaled verdict-set transition",
            ident + ["kind"],
            registry=self.registry,
        )
        self.anomaly_events = Counter(
            "tpumlops_operator_anomaly_events_total",
            "Journaled anomaly verdicts by kind, plus 'cleared' "
            "all-quiet transitions",
            ident + ["kind"],
            registry=self.registry,
        )
        self.rollout_seconds = Histogram(
            "tpumlops_operator_rollout_duration_seconds",
            "Wall time from NEW_VERSION detection to a terminal phase "
            "(promoted / failed / rolled back)",
            ident,
            buckets=_ROLLOUT_BUCKETS,
            registry=self.registry,
        )
        # Canary start times for rollout_duration (keyed per CR).
        self._rollout_t0: dict[tuple[str, str], float] = {}
        # Every labeled series this object has minted, keyed by CR, so
        # forget() can prune with the public remove() API only (no reaching
        # into prometheus_client internals).
        self._series: dict[tuple[str, str], set] = {}
        # slo-label children currently exported per CR (pruned when an
        # SLO vanishes from the spec or spec.slo is removed).
        self._slo_children: dict[tuple[str, str], set] = {}
        # Per-CR control-plane view for /debug/fleet-overview: the
        # latest anomaly verdicts and mux assignment per model.
        self._overview: dict[tuple[str, str], dict] = {}

    def _child(self, metric, namespace: str, name: str, *extra: str):
        values = (namespace, name, *extra)
        self._series.setdefault((namespace, name), set()).add((metric, values))
        return metric.labels(*values)

    # -- recording (called by OperatorRuntime) -------------------------------

    def record_outcome(self, namespace: str, name: str, outcome, seconds: float):
        """Record a successful reconcile step and its resulting state."""
        self._child(self.reconciles, namespace, name, "ok").inc()
        self._child(self.reconcile_seconds, namespace, name).observe(seconds)
        for component, secs in (getattr(outcome, "timings", None) or {}).items():
            self._child(
                self.step_component_seconds, namespace, name, component
            ).observe(secs)
        state = outcome.state
        for phase in Phase:
            self._child(self.phase, namespace, name, phase.value).set(
                1.0 if state.phase == phase else 0.0
            )
        self._child(self.traffic, namespace, name).set(state.traffic_current)
        reasons = {event.reason for event in outcome.events}
        for event in outcome.events:
            self._child(self.events, namespace, name, event.reason).inc()
        for reason, outcome_label in _TERMINAL_REASONS:
            if reason in reasons:
                self._child(
                    self.promotions, namespace, name, outcome_label
                ).inc()
                break
        gate = getattr(outcome, "gate", None)
        if gate is not None:
            self._child(
                self.gate_evaluations, namespace, name,
                gate.refusal or "promote",
            ).inc()
            self._child(self.gate_attempt, namespace, name).set(gate.attempt)
            if gate.margins:
                for check, margin in gate.margins.items():
                    self._child(
                        self.gate_margin, namespace, name, check
                    ).set(margin)
            else:
                # The latest evaluation ran NO budget comparisons
                # (metrics missing / below min samples): drop the
                # per-check children rather than keep exporting the
                # previous evaluation's headroom as if it were current.
                for check in GATE_CHECKS:
                    try:
                        self.gate_margin.remove(namespace, name, check)
                    except KeyError:
                        pass
        scale = getattr(outcome, "scale", None)
        if state.replicas is not None:
            self._child(self.autoscale_replicas, namespace, name).set(
                state.replicas
            )
        elif (namespace, name) in self._series:
            # Autoscaling just disabled: stop exporting a stale count.
            for metric in (self.autoscale_replicas, self.autoscale_desired):
                try:
                    metric.remove(namespace, name)
                except KeyError:
                    pass
        if scale is not None:
            self._child(self.autoscale_desired, namespace, name).set(
                scale.desired
            )
            if scale.applied:
                self._child(
                    self.autoscale_events, namespace, name, scale.direction
                ).inc()
            elif scale.hold is not None:
                self._child(
                    self.autoscale_holds, namespace, name, scale.hold
                ).inc()
        mux = getattr(outcome, "mux", None)
        if mux is not None:
            for rec in mux:
                if rec.action in ("attach", "replace"):
                    self._child(
                        self.mux_moves, namespace, name, rec.action
                    ).inc()
            muxv = getattr(state, "multiplex", None) or {}
            if muxv.get("parked") is not None:
                self._child(self.mux_parked, namespace, name).set(
                    muxv["parked"]
                )
        anomaly = getattr(outcome, "anomaly", None)
        if anomaly:
            for rec in anomaly:
                if rec.verdicts:
                    for v in rec.verdicts:
                        self._child(
                            self.anomaly_events, namespace, name, v.kind
                        ).inc()
                else:
                    self._child(
                        self.anomaly_events, namespace, name, "cleared"
                    ).inc()
        anoms = getattr(state, "anomalies", None)
        if anoms is not None:
            counts = {"straggler": 0, "drift": 0}
            for a in anoms:
                k = a.get("kind") if isinstance(a, dict) else None
                if k in counts:
                    counts[k] += 1
            for kind, n in counts.items():
                self._child(
                    self.anomaly_active, namespace, name, kind
                ).set(n)
        elif (namespace, name) in self._series:
            # spec.anomaly removed: stop exporting stale verdict counts.
            for kind in ("straggler", "drift"):
                try:
                    self.anomaly_active.remove(namespace, name, kind)
                except KeyError:
                    pass
        # Fleet-overview stash: what the control plane currently
        # believes about this model, next to the rings fetched live.
        ov: dict = {}
        if anoms is not None:
            ov["anomalies"] = list(anoms)
        muxv = getattr(state, "multiplex", None)
        if muxv is not None:
            ov["multiplex"] = dict(muxv)
        if ov:
            self._overview[(namespace, name)] = ov
        else:
            self._overview.pop((namespace, name), None)
        slo = getattr(outcome, "slo", None)
        slo_gauges = (
            self.slo_attainment, self.slo_budget_remaining,
            self.slo_burn_rate,
        )
        if slo:
            stale = self._slo_children.get((namespace, name), set()) - set(
                slo
            )
            for slo_name, ev in slo.items():
                values = (
                    (ev.attainment, self.slo_attainment),
                    (ev.budget_remaining, self.slo_budget_remaining),
                    (ev.burn_rate, self.slo_burn_rate),
                )
                for value, gauge in values:
                    if value is not None:
                        self._child(gauge, namespace, name, slo_name).set(
                            value
                        )
            self._slo_children[(namespace, name)] = set(slo)
        else:
            # spec.slo removed: stop exporting stale budget numbers.
            stale = self._slo_children.pop((namespace, name), set())
        for slo_name in stale:
            for gauge in slo_gauges:
                try:
                    gauge.remove(namespace, name, slo_name)
                except KeyError:
                    pass
        # Rollout duration: arm on canary start, observe on terminal.
        key = (namespace, name)
        if "NewModelVersionDetected" in reasons and state.phase == Phase.CANARY:
            self._rollout_t0[key] = time.monotonic()
        if reasons & {"PromotionComplete", "RollbackComplete"} or (
            "PromotionFailed" in reasons and state.phase == Phase.FAILED
        ):
            t0 = self._rollout_t0.pop(key, None)
            if t0 is not None:
                self._child(self.rollout_seconds, namespace, name).observe(
                    time.monotonic() - t0
                )

    def record_failure(self, namespace: str, name: str, seconds: float):
        self._child(self.reconciles, namespace, name, "error").inc()
        self._child(self.reconcile_seconds, namespace, name).observe(seconds)

    def set_resource_count(self, n: int):
        self.resources.set(n)

    def forget(self, namespace: str, name: str):
        """Drop a deleted CR's labeled series so /metrics stops exporting a
        phantom model (a stale phase=Canary gauge would fire "canary stuck"
        alerts forever)."""
        for metric, values in self._series.pop((namespace, name), ()):
            try:
                metric.remove(*values)
            except KeyError:
                pass
        self._rollout_t0.pop((namespace, name), None)
        self._slo_children.pop((namespace, name), None)
        self._overview.pop((namespace, name), None)

    def exposition(self) -> bytes:
        return generate_latest(self.registry)

    def serve(self, port: int, addr: str = "0.0.0.0", recorder=None,
              fleet_trace_sources=None):
        """Expose /metrics, /debug/spans, and (with a RolloutRecorder
        attached) /debug/rollouts + /debug/rollouts/trace on a
        daemon-thread listener.

        /debug/spans serves the ``utils/tracing.py`` GLOBAL_TRACER stats
        (reconcile-step span timings) as JSON — the same payload shape
        the data-plane server exposes, so one tool reads both planes.
        /debug/rollouts is the live per-CR gate/phase journal;
        /debug/rollouts/trace?format=chrome renders it as Chrome
        trace-event JSON (Perfetto), mirroring the server's
        /debug/engine + /debug/trace pair.

        ``fleet_trace_sources`` — a zero-arg callable returning
        ``[{"name", "base_url", "kind": "router"|"replica"}, ...]``
        (typically derived from the routing manifest: the router admin
        address plus every live replica) — additionally serves ``GET
        /debug/fleet-trace``: the sources' chrome traces fetched,
        shifted onto one clock, and merged into ONE Perfetto trace whose
        request spans share the propagated request ids
        (``utils/trace_stitch.py``).  404 when not wired.

        The same sources also drive ``GET /debug/fleet-overview``: each
        source's timeseries ring (plus the router's circuit states)
        fetched live and merged with the control plane's per-model
        anomaly verdicts and mux assignments — what
        ``scripts/fleet_top.py`` renders."""
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlparse

        from ..utils.tracing import GLOBAL_TRACER

        telemetry = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib handler contract)
                parsed = urlparse(self.path)
                path = parsed.path
                if path == "/metrics":
                    body = telemetry.exposition()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/debug/spans":
                    body = json.dumps(
                        {"spans": GLOBAL_TRACER.as_dict()}
                    ).encode()
                    ctype = "application/json"
                elif path == "/debug/fleet-trace":
                    if fleet_trace_sources is None:
                        self.send_error(
                            404,
                            "fleet trace sources not wired (pass "
                            "fleet_trace_sources to telemetry.serve)",
                        )
                        return
                    from ..utils.trace_stitch import fleet_trace

                    try:
                        specs = list(fleet_trace_sources())
                        merged = fleet_trace(specs)
                    except Exception as e:  # a dark component is a 502,
                        self.send_error(502, f"fleet trace fetch: {e}")
                        return  # not a silent partial story
                    q = parse_qs(parsed.query).get("request_id", [None])[0]
                    if q:
                        from ..utils.trace_stitch import filter_request

                        merged = filter_request(merged, q)
                    body = json.dumps(merged).encode()
                    ctype = "application/json"
                elif path == "/debug/fleet-overview":
                    if fleet_trace_sources is None:
                        self.send_error(
                            404,
                            "fleet trace sources not wired (pass "
                            "fleet_trace_sources to telemetry.serve)",
                        )
                        return
                    try:
                        specs = list(fleet_trace_sources())
                    except Exception as e:
                        self.send_error(502, f"fleet overview sources: {e}")
                        return
                    models = {
                        f"{ns}/{nm}": dict(ov)
                        for (ns, nm), ov in sorted(
                            telemetry._overview.items()
                        )
                    }
                    body = json.dumps(
                        fleet_overview(specs, models)
                    ).encode()
                    ctype = "application/json"
                elif path == "/debug/rollouts":
                    if recorder is None:
                        self.send_error(404, "rollout recorder disabled")
                        return
                    body = json.dumps(recorder.snapshot()).encode()
                    ctype = "application/json"
                elif path == "/debug/rollouts/trace":
                    if recorder is None:
                        self.send_error(404, "rollout recorder disabled")
                        return
                    fmt = parse_qs(parsed.query).get("format", ["chrome"])[0]
                    if fmt == "chrome":
                        body = json.dumps(recorder.chrome_trace()).encode()
                    elif fmt == "json":
                        body = json.dumps(recorder.snapshot()).encode()
                    else:
                        self.send_error(400, f"unknown format {fmt!r}")
                        return
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log events
                pass

        httpd = ThreadingHTTPServer((addr, port), _Handler)
        httpd.daemon_threads = True
        threading.Thread(
            target=httpd.serve_forever, daemon=True, name="operator-metrics"
        ).start()
        return httpd
