"""Rollout flight recorder: per-CR journal of the canary control loop.

The data plane got its flight recorder in the tracing PR
(``server/flight_recorder.py``); this is the control-plane half.  The
promotion gate used to collapse two raw ``ModelMetrics`` readings, the
thresholds in force, and three budget comparisons into a boolean plus
prose reason strings that only ever hit the operator log — so "why has
this canary been stuck at 30% for an hour?" was unanswerable from the
CR, the metrics endpoint, or anything but scrollback.

Every gate evaluation now produces a structured :class:`GateRecord`
(raw new/old metrics, thresholds, per-check signed margins from
``judge.should_promote``, decision + reasons, traffic before/after,
attempt count, op-timer breakdown) and every rollout phase change a
:class:`TransitionRecord`; the replica autoscaler journals its decisions
beside them as :class:`~.autoscaler.ScaleRecord` (``kind: "scale"``).
They surface three ways:

- ``status.lastGate`` / ``status.history`` on the CR itself (opt-in via
  ``spec.observability.historyLimit``; 0 — the default — writes neither
  key, keeping status byte-for-byte), so ``kubectl get -o yaml`` alone
  explains a stalled rollout;
- this recorder's bounded per-CR rings, served by the operator's
  telemetry listener as ``GET /debug/rollouts`` (live JSON) and ``GET
  /debug/rollouts/trace?format=chrome`` (Perfetto timeline: one track
  per CR, traffic-level spans, gate instants carrying margins) — the
  same chrome-trace conventions as the engine recorder;
- ``tpumlops_operator_gate_*`` Prometheus series plus one structured
  JSON decision log line per evaluation (``operator/telemetry.py`` and
  ``operator/reconciler.py``).

Constructed only when ``--rollout-ring > 0`` on the operator CLI; the
default operator builds no recorder object at all.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Mapping

# Gate checks, in the order the judge evaluates them.  Keys of
# ``GateDecision.margins`` / ``GateRecord.margins`` and values of the
# ``check`` label on ``tpumlops_operator_gate_margin``.
GATE_CHECKS = ("latency_p95", "error_rate", "latency_avg")


def _iso(ts: float) -> str:
    """ISO-8601 UTC for a unix-epoch reading."""
    import datetime

    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%SZ")


@dataclass(frozen=True)
class GateRecord:
    """One promotion-gate evaluation, with everything the judge saw.

    ``margins`` is the signed headroom per check (budget − observed;
    ≥ 0 promotes, computed by ``judge.should_promote``) and is EMPTY —
    not zero — when the gate refused before the budget comparisons ran
    (metrics missing or below ``minSampleCount``).

    ``ts`` is the injected ``Clock.now()`` reading (pacing context;
    monotonic in production, fake seconds in tests) and stays
    process-internal; the EXPORTED ``ts``/``time`` come from ``wall``
    (unix epoch), because journal records round-trip through CR status
    and survive operator restarts — a monotonic ts would reset to ~0 on
    every restart and make cross-restart deltas meaningless."""

    ts: float  # Clock.now() at evaluation time
    wall: float = 0.0  # unix epoch seconds at evaluation time
    new_version: str | None = None
    old_version: str | None = None
    traffic_before: int = 0
    traffic_after: int = 0
    attempt: int = 0  # 1-based attempt number at this traffic level
    promote: bool = False
    reasons: tuple[str, ...] = ()
    missing_on: tuple[str, ...] = ()
    margins: Mapping[str, float] = field(default_factory=dict)
    new_metrics: Mapping[str, Any] = field(default_factory=dict)
    old_metrics: Mapping[str, Any] = field(default_factory=dict)
    thresholds: Mapping[str, Any] = field(default_factory=dict)
    timings: Mapping[str, float] = field(default_factory=dict)
    # Duplicate PromotionHold Warning events suppressed so far at this
    # refusal shape (traffic level + failing checks / missing models) —
    # the stuck-canary event rate limiter.
    suppressed_events: int = 0

    @property
    def result(self) -> str:
        return "promote" if self.promote else "refuse"

    @property
    def refusal(self) -> str | None:
        """Typed refusal class (``None`` when the gate promoted):
        ``missing_metrics`` / ``min_sample`` / ``threshold``."""
        if self.promote:
            return None
        if self.missing_on:
            return "missing_metrics"
        if not self.margins:
            return "min_sample"
        return "threshold"

    def as_dict(self) -> dict[str, Any]:
        """Full journal shape (recorder rings and ``status.history``)."""
        return {
            "kind": "gate",
            "ts": self.wall,
            "time": _iso(self.wall),
            "result": self.result,
            "refusal": self.refusal,
            "newVersion": self.new_version,
            "oldVersion": self.old_version,
            "trafficBefore": self.traffic_before,
            "trafficAfter": self.traffic_after,
            "attempt": self.attempt,
            "reasons": list(self.reasons),
            "missingOn": sorted(self.missing_on),
            "margins": dict(self.margins),
            "newMetrics": dict(self.new_metrics),
            "oldMetrics": dict(self.old_metrics),
            "thresholds": dict(self.thresholds),
            "timings": dict(self.timings),
            "suppressedEvents": self.suppressed_events,
        }

    def compact(self) -> dict[str, Any]:
        """The ``status.lastGate`` block: decision + margins without the
        raw metric dumps (those live in ``status.history``)."""
        return {
            "time": _iso(self.wall),
            "result": self.result,
            "refusal": self.refusal,
            "attempt": self.attempt,
            "trafficBefore": self.traffic_before,
            "trafficAfter": self.traffic_after,
            "margins": dict(self.margins),
            "reasons": list(self.reasons),
        }


@dataclass(frozen=True)
class TransitionRecord:
    """One rollout phase change (NEW_VERSION detection, promotion to
    Stable, rollback, halt) keyed by the Event reason that announced it."""

    ts: float
    wall: float = 0.0  # unix epoch seconds
    from_phase: str = ""
    to_phase: str = ""
    reason: str = ""  # the K8s Event reason, e.g. "PromotionComplete"
    new_version: str | None = None
    old_version: str | None = None
    traffic: int = 0

    def as_dict(self) -> dict[str, Any]:
        return {
            "kind": "phase",
            "ts": self.wall,
            "time": _iso(self.wall),
            "from": self.from_phase,
            "to": self.to_phase,
            "reason": self.reason,
            "newVersion": self.new_version,
            "oldVersion": self.old_version,
            "traffic": self.traffic,
        }


@dataclass(frozen=True)
class CrashLoopRecord:
    """Replica churn observed by the reconciler (``kind: "crashloop"``).

    Journaled when the summed container restart count across a CR's
    pods GROWS — one record per observed increase, beside the gate and
    scale records, so "the canary gate refused while the new pod was
    crash-looping" is reconstructable from ``status.history`` alone.
    ``pods`` carries only the pods whose counts grew this observation."""

    wall: float  # unix epoch seconds at observation time
    total: int = 0  # summed restarts across all pods now
    prior_total: int = 0  # what status.restarts carried before
    pods: tuple = ()  # ((pod_name, restart_count), ...) for grown pods
    reason: str = ""  # last terminated reason when one is visible

    def as_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "kind": "crashloop",
            "ts": self.wall,
            "time": _iso(self.wall),
            "total": self.total,
            "priorTotal": self.prior_total,
            "pods": {name: int(n) for name, n in self.pods},
        }
        if self.reason:
            out["reason"] = self.reason
        return out


class RolloutRecorder:
    """Bounded per-CR journal of gate and transition records.

    Writers are reconcile steps (any pool thread), readers the telemetry
    listener's ``/debug/rollouts*`` handlers; one lock covers both, and
    every write is an O(1) deque append."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(
                f"rollout ring capacity must be >= 1, got {capacity}"
            )
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._journals: dict[tuple[str, str], deque] = {}
        self._recorded: dict[tuple[str, str], int] = {}

    # -- writers (reconciler side) -------------------------------------------

    def record(self, namespace: str, name: str, record) -> None:
        rec = record.as_dict() if hasattr(record, "as_dict") else dict(record)
        key = (namespace, name)
        with self._lock:
            journal = self._journals.get(key)
            if journal is None:
                journal = self._journals[key] = deque(maxlen=self.capacity)
            journal.append(rec)
            self._recorded[key] = self._recorded.get(key, 0) + 1

    def forget(self, namespace: str, name: str) -> None:
        """Drop a deleted CR's journal (mirrors ``OperatorTelemetry.forget``)."""
        with self._lock:
            self._journals.pop((namespace, name), None)
            self._recorded.pop((namespace, name), None)

    # -- readers (/debug/rollouts side) --------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """Live journal for ``GET /debug/rollouts``: records verbatim plus
        lifetime totals (ring rotation visible as recorded > len)."""
        with self._lock:
            journals = {k: list(v) for k, v in self._journals.items()}
            recorded = dict(self._recorded)
        return {
            "capacity": self.capacity,
            "rollouts": {
                f"{ns}/{name}": {
                    "recorded": recorded.get((ns, name), 0),
                    "records": [dict(r) for r in recs],
                }
                for (ns, name), recs in sorted(journals.items())
            },
        }

    def chrome_trace(self) -> dict[str, Any]:
        """Chrome trace-event JSON (Perfetto / chrome://tracing).

        One track (tid) per CR.  Traffic levels render as complete
        (``X``) spans named ``traffic N%`` — a rollout reads as a
        staircase — with gate evaluations as instant events carrying
        margins/reasons and phase changes as instants between them.
        The time base is the earliest record in the journal (records
        export wall-clock epoch seconds, so spans stay comparable even
        across operator restarts)."""
        with self._lock:
            journals = {k: [dict(r) for r in v] for k, v in self._journals.items()}

        out: list[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "tid": 0,
                "args": {"name": "tpumlops-operator rollouts"},
            }
        ]
        bases = [
            float(r.get("ts", 0.0))
            for recs in journals.values()
            for r in recs
        ]
        base = min(bases) if bases else 0.0

        def us(r: dict) -> int:
            return max(0, int((float(r.get("ts", base)) - base) * 1e6))
        for tid, ((ns, name), recs) in enumerate(sorted(journals.items()), start=1):
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": tid,
                    "args": {"name": f"{ns}/{name}"},
                }
            )
            # Traffic staircase: close a span whenever the level changes.
            level: int | None = None
            span_start = 0
            last_ts = 0
            for r in recs:
                ts = us(r)
                last_ts = max(last_ts, ts)
                r_level = (
                    r.get("trafficAfter")
                    if r.get("kind") == "gate"
                    else r.get("traffic")
                )
                if r.get("kind") == "gate":
                    out.append(
                        {
                            "name": f"gate {r.get('result')}",
                            "cat": "gate",
                            "ph": "i",
                            "s": "t",
                            "ts": ts,
                            "pid": 1,
                            "tid": tid,
                            "args": {
                                "refusal": r.get("refusal"),
                                "attempt": r.get("attempt"),
                                "margins": r.get("margins") or {},
                                "reasons": r.get("reasons") or [],
                            },
                        }
                    )
                elif r.get("kind") == "scale":
                    # Autoscaler decision (operator/autoscaler.py
                    # ScaleRecord): applied scalings and typed holds.
                    out.append(
                        {
                            "name": (
                                f"scale {r.get('from')} -> {r.get('to')}"
                                if r.get("hold") is None
                                else f"scale hold ({r.get('hold')})"
                            ),
                            "cat": "scale",
                            "ph": "i",
                            "s": "t",
                            "ts": ts,
                            "pid": 1,
                            "tid": tid,
                            "args": {
                                "desired": r.get("desired"),
                                "reason": r.get("reason"),
                                "observed": r.get("observed") or {},
                            },
                        }
                    )
                elif r.get("kind") == "anomaly":
                    # Fleet anomaly observatory (operator/anomaly.py
                    # AnomalyRecord): verdict-set transitions.
                    out.append(
                        {
                            "name": f"anomaly {r.get('action')}",
                            "cat": "anomaly",
                            "ph": "i",
                            "s": "t",
                            "ts": ts,
                            "pid": 1,
                            "tid": tid,
                            "args": {
                                "replicas": r.get("replicas"),
                                "verdicts": r.get("verdicts") or [],
                            },
                        }
                    )
                else:
                    out.append(
                        {
                            "name": f"{r.get('from')} -> {r.get('to')}",
                            "cat": "phase",
                            "ph": "i",
                            "s": "t",
                            "ts": ts,
                            "pid": 1,
                            "tid": tid,
                            "args": {"reason": r.get("reason")},
                        }
                    )
                if r_level is None:
                    continue
                if level is None:
                    level, span_start = r_level, ts
                elif r_level != level:
                    out.append(
                        {
                            "name": f"traffic {level}%",
                            "cat": "traffic",
                            "ph": "X",
                            "ts": span_start,
                            "dur": max(0, ts - span_start),
                            "pid": 1,
                            "tid": tid,
                            "args": {"level": level},
                        }
                    )
                    level, span_start = r_level, ts
            if level is not None:
                out.append(
                    {
                        "name": f"traffic {level}%",
                        "cat": "traffic",
                        "ph": "X",
                        "ts": span_start,
                        "dur": max(0, last_ts - span_start),
                        "pid": 1,
                        "tid": tid,
                        "args": {"level": level},
                    }
                )
        return {"traceEvents": out, "displayTimeUnit": "ms"}
