"""Scheduler-loop watchdog: detect a wedged engine tick and contain it.

The failure this guards against is the one nothing else in the stack
can see: a device dispatch that never returns (hung XLA execution, a
wedged chip, a deadlocked collective on a multi-host unit).  The
scheduler thread blocks inside the jitted call, so no exception fires,
``/readyz`` stays green, the router keeps routing, and every request
hangs until its client times out — the worst failure mode a replica
has.

The watchdog is a tiny monitor thread beside the scheduler:

- the engine **beats** it at every loop iteration and stamps the tick
  kind it is about to dispatch (``decode`` / ``verify`` / ``multistep``
  / ``prefill`` / ``packed-prefill`` / ``admit``);
- if no beat lands for ``deadline_s``, the tick is declared **stalled**:
  ``on_stall(kind, age_s, inventory)`` fires ONCE per incident — the
  server flips ``/readyz`` unready (balancers route elsewhere), the
  flight recorder journals a ``watchdog`` event carrying the in-flight
  tick kind and the slot inventory, and
  ``tpumlops_engine_watchdog_stalls_total`` increments;
- if the tick then completes (a transient — device contention, a
  pathological compile), the next beat fires ``on_recover`` and the
  server re-readies;
- if the stall persists past ``deadline_s + grace_s``, ``on_exit``
  fires: the process exits non-zero so Kubernetes restarts the pod —
  a restart is the only remedy for a wedged device, and a fast one
  beats an invisible hang every time.

Armed only AFTER warmup (the warmup sweep legitimately blocks for
minutes compiling); disabled entirely at ``deadline_s = 0`` — the
default — in which case no thread is created and the engine loop is
byte-for-byte what it was.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable

_log = logging.getLogger(__name__)

_IDLE = "idle"


def _default_exit(code: int = 70) -> None:  # pragma: no cover - process exit
    # os._exit, not sys.exit: the scheduler thread is wedged inside a
    # device call and will never unwind; interpreter teardown would hang
    # behind it exactly like the requests already do.
    os._exit(code)


class EngineWatchdog:
    """Monitor thread over the generation scheduler's heartbeat.

    ``slot_inventory`` is called (from the monitor thread) at stall time
    to snapshot what was in flight — best effort, the payload of the
    flight-recorder event.  All callbacks are assignable after
    construction so the server can wire itself in once it exists.
    """

    def __init__(
        self,
        deadline_s: float,
        grace_s: float = 30.0,
        on_stall: Callable | None = None,
        on_recover: Callable | None = None,
        on_exit: Callable | None = None,
        on_age: Callable | None = None,
        slot_inventory: Callable | None = None,
        poll_s: float | None = None,
    ):
        if deadline_s <= 0:
            raise ValueError(
                f"watchdog deadline_s must be > 0, got {deadline_s}"
            )
        self.deadline_s = float(deadline_s)
        self.grace_s = max(0.0, float(grace_s))
        self.on_stall = on_stall
        self.on_recover = on_recover
        self.on_exit = on_exit if on_exit is not None else _default_exit
        self.on_age = on_age  # fed the beat age every poll (the gauge)
        self.slot_inventory = slot_inventory
        # Poll fine enough to flip readiness "within the deadline" with
        # margin, bounded below so a tight test deadline still works.
        self.poll_s = (
            float(poll_s) if poll_s is not None
            else min(max(self.deadline_s / 4.0, 0.05), 1.0)
        )
        self.stalls_total = 0
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._kind = _IDLE
        self._armed = False
        self._stalled = False
        self._stall_kind = _IDLE
        self._exited = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- engine side (scheduler thread) --------------------------------------

    def beat(self, kind: str | None = None) -> None:
        """One scheduler heartbeat; ``kind`` stamps what is about to run
        (None keeps the current stamp).  Called at every loop iteration
        — the whole integration cost when healthy is this method."""
        recovered = False
        with self._lock:
            self._last_beat = time.monotonic()
            if kind is not None:
                self._kind = kind
            if self._stalled:
                self._stalled = False
                recovered = True
        if recovered:
            _log.warning(
                "watchdog: stalled tick completed after all; re-readying"
            )
            if self.on_recover is not None:
                try:
                    self.on_recover()
                except Exception:
                    _log.exception("watchdog on_recover failed")

    def arm(self) -> None:
        """Start enforcing the deadline (called once warmup finishes —
        the compile sweep legitimately blocks far past any deadline)."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._armed = True

    def disarm(self) -> None:
        with self._lock:
            self._armed = False
            self._stalled = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="engine-watchdog"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- monitor thread ------------------------------------------------------

    def _snapshot_inventory(self) -> list:
        if self.slot_inventory is None:
            return []
        try:
            return list(self.slot_inventory())
        except Exception:  # racing the wedged thread's last mutation
            return []

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                armed = self._armed
                age = time.monotonic() - self._last_beat
                kind = self._kind
                stalled = self._stalled
            if self.on_age is not None:
                try:
                    self.on_age(age if armed else 0.0)
                except Exception:
                    _log.exception("watchdog on_age failed")
            if not armed:
                continue
            if not stalled and age > self.deadline_s:
                with self._lock:
                    self._stalled = True
                    self._stall_kind = kind
                self.stalls_total += 1
                inventory = self._snapshot_inventory()
                _log.error(
                    "watchdog: engine tick kind=%s exceeded deadline "
                    "(%.1fs > %.1fs); flipping unready, exiting after "
                    "%.1fs grace unless it completes (in flight: %s)",
                    kind, age, self.deadline_s, self.grace_s, inventory,
                )
                if self.on_stall is not None:
                    try:
                        self.on_stall(kind, age, inventory)
                    except Exception:
                        _log.exception("watchdog on_stall failed")
            elif stalled and age > self.deadline_s + self.grace_s:
                if self._exited:
                    continue
                self._exited = True
                _log.critical(
                    "watchdog: stall persisted %.1fs past the deadline; "
                    "exiting so the pod restarts (kind=%s)",
                    self.grace_s, self._stall_kind,
                )
                try:
                    self.on_exit()
                except Exception:  # injected exit hooks in tests
                    _log.exception("watchdog on_exit failed")
